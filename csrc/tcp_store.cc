// TCPStore — key-value rendezvous for multi-host distributed init.
//
// TPU-native counterpart of the reference's
// paddle/phi/core/distributed/store/tcp_store.{h,cc} (TCPStore:117) used by
// ProcessGroup bootstrap: rank 0 runs a server; all ranks set/get/add/wait
// keys (NCCL unique ids there, coordinator addresses here).
//
// Wire protocol (little-endian):
//   request  = u8 op | u32 klen | key | u64 vlen/delta | value
//   ops: 1=SET 2=GET 3=ADD 4=WAIT 5=DELETE
//   response = i64 status/len | payload
// Server: one thread per connection (connection count == world size scale).

#include <arpa/inet.h>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct StoreState {
  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::string> kv;
  std::atomic<bool> stop{false};
  int listen_fd = -1;
  std::thread accept_thread;
  std::vector<std::thread> conns;
};

bool read_full(int fd, void* buf, size_t n) {
  uint8_t* p = reinterpret_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= (size_t)r;
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const uint8_t* p = reinterpret_cast<const uint8_t*>(buf);
  while (n > 0) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= (size_t)r;
  }
  return true;
}

void serve_conn(StoreState* st, int fd) {
  for (;;) {
    uint8_t op;
    if (!read_full(fd, &op, 1)) break;
    uint32_t klen;
    if (!read_full(fd, &klen, 4) || klen > 1 << 20) break;
    std::string key(klen, '\0');
    if (!read_full(fd, &key[0], klen)) break;
    uint64_t vlen;
    if (!read_full(fd, &vlen, 8) || vlen > 1ull << 32) break;
    std::string val(vlen, '\0');
    if (vlen && op != 3 && !read_full(fd, &val[0], vlen)) break;

    int64_t status = 0;
    std::string payload;
    if (op == 1) {  // SET
      std::lock_guard<std::mutex> g(st->mu);
      st->kv[key] = val;
      st->cv.notify_all();
    } else if (op == 2) {  // GET (non-blocking; -1 if missing)
      std::lock_guard<std::mutex> g(st->mu);
      auto it = st->kv.find(key);
      if (it == st->kv.end()) {
        status = -1;
      } else {
        payload = it->second;
        status = (int64_t)payload.size();
      }
    } else if (op == 3) {  // ADD vlen as signed delta; returns new value
      std::lock_guard<std::mutex> g(st->mu);
      int64_t cur = 0;
      auto it = st->kv.find(key);
      if (it != st->kv.end()) cur = std::stoll(it->second);
      cur += (int64_t)vlen;
      st->kv[key] = std::to_string(cur);
      st->cv.notify_all();
      status = cur;
    } else if (op == 4) {  // WAIT until key exists, then return value
      std::unique_lock<std::mutex> lk(st->mu);
      st->cv.wait(lk, [&] {
        return st->stop.load() || st->kv.count(key) > 0;
      });
      if (st->stop.load() && !st->kv.count(key)) {
        status = -1;
      } else {
        payload = st->kv[key];
        status = (int64_t)payload.size();
      }
    } else if (op == 5) {  // DELETE
      std::lock_guard<std::mutex> g(st->mu);
      status = (int64_t)st->kv.erase(key);
    } else {
      break;
    }
    if (!write_full(fd, &status, 8)) break;
    if (status > 0 && (op == 2 || op == 4)) {
      if (!write_full(fd, payload.data(), payload.size())) break;
    }
  }
  ::close(fd);
}

}  // namespace

extern "C" {

// Start a server on port (0 = ephemeral). Returns handle; *out_port gets
// the bound port.
void* ptq_store_server_start(int port, int* out_port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons((uint16_t)port);
  if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0 || listen(fd, 128) != 0) {
    ::close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd, (sockaddr*)&addr, &alen);
  if (out_port) *out_port = ntohs(addr.sin_port);

  StoreState* st = new StoreState();
  st->listen_fd = fd;
  st->accept_thread = std::thread([st] {
    for (;;) {
      int cfd = ::accept(st->listen_fd, nullptr, nullptr);
      if (cfd < 0) break;  // listen_fd closed => shutdown
      int one = 1;
      setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> g(st->mu);
      st->conns.emplace_back(serve_conn, st, cfd);
    }
  });
  return st;
}

void ptq_store_server_stop(void* handle) {
  StoreState* st = reinterpret_cast<StoreState*>(handle);
  st->stop.store(true);
  {
    std::lock_guard<std::mutex> g(st->mu);
    st->cv.notify_all();
  }
  ::shutdown(st->listen_fd, SHUT_RDWR);
  ::close(st->listen_fd);
  if (st->accept_thread.joinable()) st->accept_thread.join();
  std::vector<std::thread> conns;
  {
    std::lock_guard<std::mutex> g(st->mu);
    conns.swap(st->conns);
  }
  for (auto& t : conns)
    if (t.joinable()) t.detach();  // blocked conns die with process
  delete st;
}

// ---- client ----

void* ptq_store_connect(const char* host, int port, int timeout_ms) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    ::close(fd);
    return nullptr;
  }
  // retry-connect within timeout (server may start later)
  int waited = 0;
  while (connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    ::close(fd);
    if (waited >= timeout_ms) return nullptr;
    usleep(100 * 1000);
    waited += 100;
    fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return nullptr;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return reinterpret_cast<void*>(static_cast<intptr_t>(fd + 1));
}

static int cfd_of(void* h) {
  return (int)(reinterpret_cast<intptr_t>(h) - 1);
}

static bool send_req(int fd, uint8_t op, const char* key, uint32_t klen,
                     const uint8_t* val, uint64_t vlen) {
  std::string buf;
  buf.push_back((char)op);
  buf.append((const char*)&klen, 4);
  buf.append(key, klen);
  buf.append((const char*)&vlen, 8);
  if (val && vlen) buf.append((const char*)val, vlen);
  return write_full(fd, buf.data(), buf.size());
}

int64_t ptq_store_set(void* h, const char* key, const uint8_t* val,
                      uint64_t vlen) {
  int fd = cfd_of(h);
  if (!send_req(fd, 1, key, (uint32_t)strlen(key), val, vlen)) return -1;
  int64_t status;
  if (!read_full(fd, &status, 8)) return -1;
  return status;
}

// GET/WAIT: returns len and fills buf up to cap; -1 missing/err, -2 buf
// too small (value bytes are drained and discarded).
static int64_t get_like(void* h, uint8_t op, const char* key, uint8_t* buf,
                        uint64_t cap) {
  int fd = cfd_of(h);
  if (!send_req(fd, op, key, (uint32_t)strlen(key), nullptr, 0)) return -1;
  int64_t status;
  if (!read_full(fd, &status, 8)) return -1;
  if (status <= 0) return status;
  if ((uint64_t)status > cap) {
    std::vector<uint8_t> sink(status);
    read_full(fd, sink.data(), status);
    return -2;
  }
  if (!read_full(fd, buf, status)) return -1;
  return status;
}

int64_t ptq_store_get(void* h, const char* key, uint8_t* buf, uint64_t cap) {
  return get_like(h, 2, key, buf, cap);
}

int64_t ptq_store_wait(void* h, const char* key, uint8_t* buf, uint64_t cap) {
  return get_like(h, 4, key, buf, cap);
}

int64_t ptq_store_add(void* h, const char* key, int64_t delta) {
  int fd = cfd_of(h);
  if (!send_req(fd, 3, key, (uint32_t)strlen(key), nullptr,
                (uint64_t)delta))
    return INT64_MIN;
  int64_t status;
  if (!read_full(fd, &status, 8)) return INT64_MIN;
  return status;
}

int64_t ptq_store_delete(void* h, const char* key) {
  int fd = cfd_of(h);
  if (!send_req(fd, 5, key, (uint32_t)strlen(key), nullptr, 0)) return -1;
  int64_t status;
  if (!read_full(fd, &status, 8)) return -1;
  return status;
}

void ptq_store_disconnect(void* h) { ::close(cfd_of(h)); }

}  // extern "C"
