/* paddle_tpu inference C API — native serving host.
 *
 * Reference analog: paddle/fluid/inference/capi_exp/pd_inference_api.h
 * (PD_PredictorCreate / PD_PredictorRun / PD_Tensor*), the stable C ABI a
 * non-Python serving process links against. There the ABI fronts the C++
 * AnalysisPredictor; here it fronts the StableHLO artifact produced by
 * paddle_tpu.jit.save, executed by the embedded runtime (XLA did the
 * graph-level optimization at export time). The embedding keeps the C
 * surface identical whether the backing executable runs on CPU or a TPU
 * chip — device selection is a property of the exported artifact + the
 * runtime the host process is pointed at.
 *
 * Usage (see tests/test_capi_predictor.py for a compiled end-to-end host):
 *   PD_Predictor* p = PD_PredictorCreate("/path/model_prefix");
 *   PD_TensorData in = {PD_DTYPE_FLOAT32, ndim, shape, data};
 *   PD_TensorData* outs; int n_out;
 *   PD_PredictorRun(p, &in, 1, &outs, &n_out);
 *   ... use outs[i].data ...
 *   PD_OutputsDestroy(outs, n_out);
 *   PD_PredictorDestroy(p);
 */
#ifndef PADDLE_TPU_CAPI_H_
#define PADDLE_TPU_CAPI_H_

#include <stdint.h>
#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct PD_Predictor PD_Predictor;

typedef enum {
  PD_DTYPE_FLOAT32 = 0,
  PD_DTYPE_FLOAT64 = 1,
  PD_DTYPE_INT32 = 2,
  PD_DTYPE_INT64 = 3,
} PD_DType;

/* Borrowed-view tensor for inputs; owned (malloc'd) for outputs. */
typedef struct {
  int32_t dtype;      /* PD_DType */
  int32_t ndim;
  int64_t shape[8];
  void* data;         /* row-major, contiguous */
} PD_TensorData;

/* Create a predictor from a jit.save prefix (the ".pdmodel"-style prefix
 * paddle_tpu.jit.save wrote). Returns NULL on failure — see
 * PD_GetLastError(). Initializes the embedded runtime on first call;
 * thread-safe. */
PD_Predictor* PD_PredictorCreate(const char* model_prefix);

/* Run inference. `inputs` is an array of n_inputs borrowed tensor views
 * (data is copied in). On success (*outputs, *n_outputs) receive a
 * malloc'd array of owned output tensors; free with PD_OutputsDestroy.
 * Returns 0 on success, nonzero on failure (PD_GetLastError()). */
int PD_PredictorRun(PD_Predictor* pred,
                    const PD_TensorData* inputs, int n_inputs,
                    PD_TensorData** outputs, int* n_outputs);

void PD_OutputsDestroy(PD_TensorData* outputs, int n_outputs);
void PD_PredictorDestroy(PD_Predictor* pred);

/* Last error message on this thread, or "" when none. The pointer stays
 * valid until the next failing call on the same thread. */
const char* PD_GetLastError(void);

#ifdef __cplusplus
}
#endif

#endif /* PADDLE_TPU_CAPI_H_ */
