// Shared-memory blocking ring queue for DataLoader tensor transport.
//
// TPU-native counterpart of the reference's reader plumbing:
// paddle/fluid/operators/reader/blocking_queue.h (bounded blocking queue)
// combined with the shared-memory LoDTensor transport used by the
// multiprocess DataLoader (python/paddle/fluid/dataloader/worker.py).
// Worker processes memcpy serialized batches into a POSIX shm ring; the
// trainer process pops them without the pipe copies of mp.Queue.
//
// Layout: [Header][slot 0][slot 1]...[slot n-1], each slot =
// [uint64 len][payload bytes]. Synchronization: process-shared pthread
// mutex + condvars living inside the shm header.

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Header {
  pthread_mutex_t mu;
  pthread_cond_t not_empty;
  pthread_cond_t not_full;
  uint64_t n_slots;
  uint64_t slot_bytes;  // payload capacity per slot (excl. len word)
  uint64_t head;        // next slot to pop
  uint64_t tail;        // next slot to push
  uint64_t count;
  uint32_t closed;
  uint32_t _pad;
};

struct Queue {
  Header* hdr;
  uint8_t* slots;
  size_t map_bytes;
  char name[256];
  bool owner;
};

inline uint8_t* slot_ptr(Queue* q, uint64_t i) {
  return q->slots + i * (sizeof(uint64_t) + q->hdr->slot_bytes);
}

}  // namespace

extern "C" {

// Create (owner=1) or attach (owner=0) a queue. Returns nullptr on error.
void* ptq_shm_queue_open(const char* name, uint64_t n_slots,
                         uint64_t slot_bytes, int owner) {
  size_t bytes =
      sizeof(Header) + n_slots * (sizeof(uint64_t) + slot_bytes);
  int flags = owner ? (O_CREAT | O_EXCL | O_RDWR) : O_RDWR;
  int fd = shm_open(name, flags, 0600);
  if (fd < 0) return nullptr;
  if (owner && ftruncate(fd, (off_t)bytes) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, bytes, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;

  Queue* q = new Queue();
  q->hdr = reinterpret_cast<Header*>(mem);
  q->slots = reinterpret_cast<uint8_t*>(mem) + sizeof(Header);
  q->map_bytes = bytes;
  snprintf(q->name, sizeof(q->name), "%s", name);
  q->owner = owner != 0;

  if (owner) {
    Header* h = q->hdr;
    memset(h, 0, sizeof(Header));
    h->n_slots = n_slots;
    h->slot_bytes = slot_bytes;
    pthread_mutexattr_t ma;
    pthread_mutexattr_init(&ma);
    pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
    pthread_mutex_init(&h->mu, &ma);
    pthread_condattr_t ca;
    pthread_condattr_init(&ca);
    pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
    pthread_cond_init(&h->not_empty, &ca);
    pthread_cond_init(&h->not_full, &ca);
  }
  return q;
}

// Push payload; blocks while full. Returns 0 ok, -1 closed, -2 too large.
int ptq_shm_queue_push(void* qp, const uint8_t* data, uint64_t len) {
  Queue* q = reinterpret_cast<Queue*>(qp);
  Header* h = q->hdr;
  if (len > h->slot_bytes) return -2;
  pthread_mutex_lock(&h->mu);
  while (h->count == h->n_slots && !h->closed)
    pthread_cond_wait(&h->not_full, &h->mu);
  if (h->closed) {
    pthread_mutex_unlock(&h->mu);
    return -1;
  }
  uint8_t* s = slot_ptr(q, h->tail);
  memcpy(s, &len, sizeof(uint64_t));
  memcpy(s + sizeof(uint64_t), data, len);
  h->tail = (h->tail + 1) % h->n_slots;
  h->count++;
  pthread_cond_signal(&h->not_empty);
  pthread_mutex_unlock(&h->mu);
  return 0;
}

// Pop into buf (cap bytes). Returns payload size, -1 if closed+empty,
// -2 if buf too small (item is left in the queue).
int64_t ptq_shm_queue_pop(void* qp, uint8_t* buf, uint64_t cap) {
  Queue* q = reinterpret_cast<Queue*>(qp);
  Header* h = q->hdr;
  pthread_mutex_lock(&h->mu);
  while (h->count == 0 && !h->closed)
    pthread_cond_wait(&h->not_empty, &h->mu);
  if (h->count == 0 && h->closed) {
    pthread_mutex_unlock(&h->mu);
    return -1;
  }
  uint8_t* s = slot_ptr(q, h->head);
  uint64_t len;
  memcpy(&len, s, sizeof(uint64_t));
  if (len > cap) {
    pthread_mutex_unlock(&h->mu);
    return -2;
  }
  memcpy(buf, s + sizeof(uint64_t), len);
  h->head = (h->head + 1) % h->n_slots;
  h->count--;
  pthread_cond_signal(&h->not_full);
  pthread_mutex_unlock(&h->mu);
  return (int64_t)len;
}

// Size of the item at the head (for buffer allocation); -1 empty+closed,
// 0 with *waiting*=1 if empty but open.
int64_t ptq_shm_queue_peek_size(void* qp) {
  Queue* q = reinterpret_cast<Queue*>(qp);
  Header* h = q->hdr;
  pthread_mutex_lock(&h->mu);
  while (h->count == 0 && !h->closed)
    pthread_cond_wait(&h->not_empty, &h->mu);
  if (h->count == 0 && h->closed) {
    pthread_mutex_unlock(&h->mu);
    return -1;
  }
  uint64_t len;
  memcpy(&len, slot_ptr(q, h->head), sizeof(uint64_t));
  pthread_mutex_unlock(&h->mu);
  return (int64_t)len;
}

uint64_t ptq_shm_queue_count(void* qp) {
  Queue* q = reinterpret_cast<Queue*>(qp);
  pthread_mutex_lock(&q->hdr->mu);
  uint64_t c = q->hdr->count;
  pthread_mutex_unlock(&q->hdr->mu);
  return c;
}

void ptq_shm_queue_close(void* qp) {
  Queue* q = reinterpret_cast<Queue*>(qp);
  Header* h = q->hdr;
  pthread_mutex_lock(&h->mu);
  h->closed = 1;
  pthread_cond_broadcast(&h->not_empty);
  pthread_cond_broadcast(&h->not_full);
  pthread_mutex_unlock(&h->mu);
}

void ptq_shm_queue_free(void* qp) {
  Queue* q = reinterpret_cast<Queue*>(qp);
  bool owner = q->owner;
  char name[256];
  snprintf(name, sizeof(name), "%s", q->name);
  munmap(q->hdr, q->map_bytes);
  if (owner) shm_unlink(name);
  delete q;
}

}  // extern "C"
