// Native serving host: C ABI over the StableHLO predictor.
//
// Reference analog: paddle/fluid/inference/capi_exp/pd_predictor.cc — the
// C functions there forward into the C++ AnalysisPredictor; here they
// forward into the embedded runtime (CPython interpreter hosting the
// paddle_tpu predictor, which executes the AOT-exported StableHLO module
// through XLA). The host process is pure C/C++: it links this library and
// never includes Python headers itself. Marshalling copies buffers at the
// boundary, matching the reference's copy_from_cpu/copy_to_cpu contract.
//
// Interpreter lifecycle: initialized lazily on the first PD_PredictorCreate
// and kept alive for the process (finalizing a runtime with live device
// clients is undefined in the reference too — AnalysisPredictor never
// tears down CUDA). All entry points take the GIL via PyGILState_Ensure,
// so any host thread may call them.

#include "paddle_tpu_capi.h"

#include <Python.h>

#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>

namespace {

thread_local std::string g_last_error;

void set_error(const std::string& msg) { g_last_error = msg; }

std::string fetch_py_error() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  std::string msg = "python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c) msg = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  return msg;
}

const char* dtype_name(int32_t dt) {
  switch (dt) {
    case PD_DTYPE_FLOAT32: return "float32";
    case PD_DTYPE_FLOAT64: return "float64";
    case PD_DTYPE_INT32: return "int32";
    case PD_DTYPE_INT64: return "int64";
    default: return nullptr;
  }
}

int dtype_code(const char* name) {
  if (!strcmp(name, "float32")) return PD_DTYPE_FLOAT32;
  if (!strcmp(name, "float64")) return PD_DTYPE_FLOAT64;
  if (!strcmp(name, "int32")) return PD_DTYPE_INT32;
  if (!strcmp(name, "int64")) return PD_DTYPE_INT64;
  return -1;
}

size_t dtype_size(int32_t dt) {
  switch (dt) {
    case PD_DTYPE_FLOAT32: case PD_DTYPE_INT32: return 4;
    default: return 8;
  }
}

// Python-side bridge, defined once: creates predictors and runs them on
// (bytes, shape, dtype) triples so the C side only marshals PyBytes /
// PyLong / PyUnicode — no numpy C API dependency.
const char* kBootstrap = R"PY(
import os as _os
import numpy as _np

_predictors = {}
_next_id = [1]

def _capi_create(prefix):
    # Honor an explicit platform pin before the first jax import settles
    # on a backend (site customizations may pre-pin a device plugin whose
    # env-var override is ignored).
    plat = _os.environ.get("PADDLE_TPU_CAPI_PLATFORM")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)
    from paddle_tpu.inference import Config, create_predictor
    pred = create_predictor(Config(prefix))
    pid = _next_id[0]; _next_id[0] += 1
    _predictors[pid] = pred
    return pid

def _capi_run(pid, inputs):
    pred = _predictors[pid]
    # frombuffer views are safe without a copy: the bytes objects stay
    # alive for the call and inputs are consumed read-only.
    arrays = [_np.frombuffer(b, dtype=dt).reshape(shape)
              for (b, shape, dt) in inputs]
    outs = pred.run(arrays)
    result = []
    for o in outs:
        a = _np.ascontiguousarray(o)
        if a.dtype == _np.bool_:
            a = a.astype(_np.int32)
        if a.dtype not in (_np.float32, _np.float64,
                           _np.int32, _np.int64):
            a = a.astype(_np.float32)
        result.append((a.tobytes(), tuple(int(d) for d in a.shape),
                       str(a.dtype)))
    return result

def _capi_destroy(pid):
    _predictors.pop(pid, None)
)PY";

PyObject* g_bridge = nullptr;  // module dict holding the bridge functions
std::once_flag g_init_once;
bool g_init_ok = false;

void init_interpreter() {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    // Release the GIL acquired by initialization so PyGILState_Ensure
    // works uniformly from every thread (including this one).
    PyEval_SaveThread();
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* mod = PyImport_AddModule("__paddle_tpu_capi__");  // borrowed
  PyObject* dict = PyModule_GetDict(mod);                     // borrowed
  // __builtins__ is absent from a fresh module's dict when running
  // embedded; PyRun_String needs it resolvable.
  if (!PyDict_GetItemString(dict, "__builtins__")) {
    PyDict_SetItemString(dict, "__builtins__", PyEval_GetBuiltins());
  }
  PyObject* res = PyRun_String(kBootstrap, Py_file_input, dict, dict);
  if (!res) {
    set_error("capi bootstrap failed: " + fetch_py_error());
  } else {
    Py_DECREF(res);
    Py_INCREF(dict);
    g_bridge = dict;
    g_init_ok = true;
  }
  PyGILState_Release(gil);
}

PyObject* bridge_call(const char* fn, PyObject* args /* stolen */) {
  PyObject* f = PyDict_GetItemString(g_bridge, fn);  // borrowed
  if (!f) {
    Py_XDECREF(args);
    set_error(std::string("bridge function missing: ") + fn);
    return nullptr;
  }
  PyObject* out = PyObject_CallObject(f, args);
  Py_XDECREF(args);
  if (!out) set_error(fetch_py_error());
  return out;
}

}  // namespace

struct PD_Predictor {
  long long pid;
};

extern "C" {

PD_Predictor* PD_PredictorCreate(const char* model_prefix) {
  g_last_error.clear();
  std::call_once(g_init_once, init_interpreter);
  if (!g_init_ok) {
    // init_interpreter recorded the detail on the thread that ran it;
    // other threads still need a diagnostic on their own thread_local.
    if (g_last_error.empty()) {
      set_error("embedded runtime failed to initialize");
    }
    return nullptr;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  PD_Predictor* handle = nullptr;
  PyObject* args = Py_BuildValue("(s)", model_prefix);
  PyObject* pid = bridge_call("_capi_create", args);
  if (pid) {
    handle = new PD_Predictor{PyLong_AsLongLong(pid)};
    Py_DECREF(pid);
  }
  PyGILState_Release(gil);
  return handle;
}

int PD_PredictorRun(PD_Predictor* pred,
                    const PD_TensorData* inputs, int n_inputs,
                    PD_TensorData** outputs, int* n_outputs) {
  g_last_error.clear();
  if (!pred || !outputs || !n_outputs) {
    set_error("null argument");
    return 1;
  }
  *outputs = nullptr;
  *n_outputs = 0;
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = 1;
  PyObject* in_list = PyList_New(n_inputs);
  bool build_ok = in_list != nullptr;
  for (int i = 0; build_ok && i < n_inputs; ++i) {
    const PD_TensorData& t = inputs[i];
    const char* dt = dtype_name(t.dtype);
    if (!dt || t.ndim < 0 || t.ndim > 8 || !t.data) {
      set_error("bad input dtype/ndim/data at index " + std::to_string(i));
      build_ok = false;
      break;
    }
    size_t n = 1;
    bool shape_ok = true;
    for (int d = 0; d < t.ndim; ++d) {
      // negative dims would wrap the size_t product into a huge read
      if (t.shape[d] < 0 ||
          (t.shape[d] > 0 &&
           n > static_cast<size_t>(1) << 40)) {  // cap: 1T elements
        shape_ok = false;
        break;
      }
      n *= static_cast<size_t>(t.shape[d]);
    }
    if (!shape_ok) {
      set_error("bad input shape at index " + std::to_string(i) +
                " (negative or overflowing dims)");
      build_ok = false;
      break;
    }
    PyObject* shape = PyTuple_New(t.ndim);
    for (int d = 0; shape && d < t.ndim; ++d) {
      PyTuple_SetItem(shape, d, PyLong_FromLongLong(t.shape[d]));
    }
    PyObject* bytes = PyBytes_FromStringAndSize(
        static_cast<const char*>(t.data),
        static_cast<Py_ssize_t>(n * dtype_size(t.dtype)));
    PyObject* dts = PyUnicode_FromString(dt);
    PyObject* triple = (shape && bytes && dts)
        ? PyTuple_Pack(3, bytes, shape, dts) : nullptr;
    Py_XDECREF(bytes);
    Py_XDECREF(shape);
    Py_XDECREF(dts);
    if (!triple) {
      PyErr_Clear();
      set_error("input marshalling failed at index " + std::to_string(i));
      build_ok = false;
      break;
    }
    PyList_SetItem(in_list, i, triple);  // steals
  }
  if (build_ok) {
    // "O" increfs in_list: args owns its own reference, drop ours now.
    PyObject* args = Py_BuildValue("(LO)", pred->pid, in_list);
    Py_DECREF(in_list);
    in_list = nullptr;
    PyObject* result = bridge_call("_capi_run", args);
    if (result) {
      Py_ssize_t n_out = PyList_Size(result);
      PD_TensorData* outs = static_cast<PD_TensorData*>(
          calloc(static_cast<size_t>(n_out), sizeof(PD_TensorData)));
      bool ok = true;
      for (Py_ssize_t i = 0; ok && i < n_out; ++i) {
        PyObject* triple = PyList_GetItem(result, i);  // borrowed
        PyObject* bytes = PyTuple_GetItem(triple, 0);
        PyObject* shape = PyTuple_GetItem(triple, 1);
        PyObject* dtype = PyTuple_GetItem(triple, 2);
        int code = dtype_code(PyUnicode_AsUTF8(dtype));
        Py_ssize_t ndim = PyTuple_Size(shape);
        if (code < 0 || ndim > 8) {
          set_error("unsupported output dtype/rank at " + std::to_string(i));
          ok = false;
          break;
        }
        outs[i].dtype = code;
        outs[i].ndim = static_cast<int32_t>(ndim);
        for (Py_ssize_t d = 0; d < ndim; ++d) {
          outs[i].shape[d] = PyLong_AsLongLong(PyTuple_GetItem(shape, d));
        }
        Py_ssize_t len = PyBytes_Size(bytes);
        outs[i].data = malloc(static_cast<size_t>(len));
        memcpy(outs[i].data, PyBytes_AsString(bytes),
               static_cast<size_t>(len));
      }
      if (ok) {
        *outputs = outs;
        *n_outputs = static_cast<int>(n_out);
        rc = 0;
      } else {
        PD_OutputsDestroy(outs, static_cast<int>(n_out));
      }
      Py_DECREF(result);
    }
  }
  Py_XDECREF(in_list);
  PyGILState_Release(gil);
  return rc;
}

void PD_OutputsDestroy(PD_TensorData* outputs, int n_outputs) {
  if (!outputs) return;
  for (int i = 0; i < n_outputs; ++i) free(outputs[i].data);
  free(outputs);
}

void PD_PredictorDestroy(PD_Predictor* pred) {
  if (!pred) return;
  if (g_init_ok) {
    PyGILState_STATE gil = PyGILState_Ensure();
    PyObject* args = Py_BuildValue("(L)", pred->pid);
    PyObject* r = bridge_call("_capi_destroy", args);
    Py_XDECREF(r);
    PyGILState_Release(gil);
  }
  delete pred;
}

const char* PD_GetLastError(void) { return g_last_error.c_str(); }

}  // extern "C"
