"""Serving benchmark: continuous-batching throughput on one chip.

Drives ``serving.LLMEngine`` with a staggered open-loop workload
(requests keep arriving while the batch is in flight, so continuous
admission and the mixed prefill+decode kernel path are both exercised)
and prints ONE line::

    BENCH_SERVE {"metric": "serve_tokens_per_sec_chip", ...}

with tokens/sec/chip, TTFT p50/p95 and request-latency p50/p95 — the
Gemma-on-Cloud-TPU serving comparison's headline numbers (PAPERS.md).
Percentiles come from the ``serve_*`` histograms in the metrics
registry (enabled for the run).  Real numbers on CPU via the jnp
reference path; on TPU the Pallas kernel path compiles through the
persistent XLA cache.

Env knobs (all optional): PADDLE_TPU_BENCH_SERVE_PRESET (default
llama-debug), _REQUESTS, _PROMPT (max prompt len), _NEW (tokens per
request), _MAX_RUNNING, _CHUNK, _PAGE, _PAGES (pool pages — shrink to
force pool pressure), _MAX_QUEUE (admission bound — overload runs shed
past it), _TTFT_SLO_MS / _LAT_SLO_MS (SLO targets checked in the
resilience block), and PADDLE_TPU_BENCH_TIMEOUT for the watchdog
deadline shared with bench.py.

``--workload shared-prefix`` (or _WORKLOAD=shared-prefix) switches the
prompt mix to N requests over M shared system prompts (_SYS_PROMPTS,
default 2) and turns on the PR-12 reuse stack — prefix caching plus
self-draft speculative decoding (_SPEC_K, default 3; the draft IS the
target, so acceptance isolates the machinery from draft quality).  The
JSON line then carries a ``reuse`` block: prefix hit-rate, prefill
tokens saved, and the spec-decode acceptance rate.

The JSON line carries a ``resilience`` block (shed / recoveries /
quarantined / deadline-expired counts for the measured run, plus the
observed-vs-target SLO verdicts) so overload and chaos E2E runs are
assertable from the one-line contract.

``--workload diurnal|bursty|flash-crowd`` replays the matching seeded
arrival process from ``serving.workloads`` (the same streams
``tools/fleet_sim.py`` simulates), mapped onto engine steps so bursts
land as bursts.  Every run's JSON line carries a ``fleet`` block: the
per-replica service model calibrated from this run's measured step
wall-times (prefill-chunk / decode step costs, concurrency, predicted
capacity rps/chip, and the min-chips answer for the offered load) —
the live side of the fleet simulator's planning arithmetic.

``--kv-dtype int8`` (or _KV_DTYPE=int8) serves the same workload over
the quantized paged KV cache (int8 pages + per-page f32 scale pools;
parity-within-tolerance vs the bf16 pools, not bit-identical) and the
JSON line carries a ``kv`` block: page dtype, pool pages, scale-pool
bytes, and the pool's predicted max-concurrent capacity — the
measured side of the ``pod_report.py serving --kv-dtype`` prediction.

``--trace-out DIR`` (or _TRACE_OUT) turns on the flight recorder for
the measured run: every request's lifecycle events (queued -> admitted
-> prefill -> first token -> decode -> terminal) land in a rank-tagged
JSONL sidecar under DIR, the SLO block gains the TTFT breakdown
(queue/prefill/decode p95), and the sidecar path rides in the JSON
line — feed it to ``tools/trace_report.py`` for per-request timelines
whose breakdown sums exactly to the measured TTFT.

``--ledger-out [PATH]`` (or PADDLE_TPU_BENCH_LEDGER_OUT) appends the
normalized provenance-stamped row to the perf ledger (default
``PERF_LEDGER.jsonl``; gate it with ``tools/perf_ledger.py check``).
With ``FLAGS_tpu_metrics_port`` set the run is scrapeable live at
``/metrics`` and ``/slo`` (``paddle_tpu/profiler/exporter.py``) and the
JSON line carries the bound ``metrics_port``.
"""
from __future__ import annotations

import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.abspath(__file__))
# scratch record of the last successful run lives under runs/ (untracked)
# — the durable artifact is the perf ledger row (--ledger-out)
_LAST_FILE = os.path.join(_REPO, "runs", "bench_serve_last.json")
_LAST_FILE_LEGACY = os.path.join(_REPO, ".bench_serve_last.json")
_T0 = time.monotonic()


def _ledger_out():
    """--ledger-out [PATH] / PADDLE_TPU_BENCH_LEDGER_OUT: perf ledger
    destination, or None when ledger emission is off."""
    path = os.environ.get("PADDLE_TPU_BENCH_LEDGER_OUT")
    if "--ledger-out" in sys.argv:
        i = sys.argv.index("--ledger-out")
        if i + 1 < len(sys.argv) and not sys.argv[i + 1].startswith("--"):
            path = sys.argv[i + 1]
        else:
            path = os.path.join(_REPO, "PERF_LEDGER.jsonl")
    return path


def _ledger_append(result):
    """Append the normalized row (success or error) to the perf ledger;
    a ledger failure must never break the BENCH_SERVE line."""
    path = _ledger_out()
    if not path:
        return
    try:
        from paddle_tpu.profiler import ledger as _ledger
        cmd = "python " + " ".join(
            [os.path.basename(sys.argv[0] or "bench_serve.py")]
            + sys.argv[1:])
        row = _ledger.from_bench_serve_result(result, ts=time.time(),
                                              cmd=cmd)
        _ledger.append(path, row)
        _log(f"ledger row appended to {path}")
    except Exception as e:
        _log(f"ledger append failed: {e}")


def _log(msg):
    sys.stderr.write(f"bench_serve[{time.monotonic() - _T0:6.1f}s]: "
                     f"{msg}\n")
    sys.stderr.flush()


def _env_int(name, default):
    return int(os.environ.get(f"PADDLE_TPU_BENCH_SERVE_{name}", default))


def _percentiles(hist_name, fallback):
    """p50/p95 (seconds) from a metrics-registry histogram, falling
    back to numpy over the raw per-request numbers."""
    import numpy as np

    from paddle_tpu.profiler import metrics
    v = metrics.snapshot().get(hist_name)
    if isinstance(v, dict) and v.get("count"):
        return float(v["p50"]), float(v["p95"])
    if not fallback:
        return 0.0, 0.0
    arr = np.asarray(fallback, dtype=float)
    return (float(np.percentile(arr, 50)), float(np.percentile(arr, 95)))


def main():
    import jax
    import numpy as np

    from paddle_tpu.core import flags as _flags
    from paddle_tpu.models import llama
    from paddle_tpu import serving
    from paddle_tpu.serving import workloads as _workloads

    _flags.set_flags({"FLAGS_tpu_metrics": True})
    from paddle_tpu.core import compile_cache
    try:
        compile_cache.ensure(force=True)
    except Exception as e:
        _log(f"compilation cache unavailable: {e}")

    preset = os.environ.get("PADDLE_TPU_BENCH_SERVE_PRESET",
                            "llama-debug")
    workload = os.environ.get("PADDLE_TPU_BENCH_SERVE_WORKLOAD",
                              "uniform")
    if "--workload" in sys.argv:
        workload = sys.argv[sys.argv.index("--workload") + 1]
    # one shared preset catalogue (serving/workloads.py): the error
    # enumerates every valid preset, and the shaped arrival processes
    # (diurnal/bursty/flash-crowd) are the exact streams fleet_sim
    # and pod_report plan against
    _workloads.validate(workload)
    kv_dtype = os.environ.get("PADDLE_TPU_BENCH_SERVE_KV_DTYPE", "bf16")
    if "--kv-dtype" in sys.argv:
        kv_dtype = sys.argv[sys.argv.index("--kv-dtype") + 1]
    if kv_dtype not in ("bf16", "int8"):
        raise ValueError(f"unknown --kv-dtype {kv_dtype!r} "
                         "(bf16 | int8)")
    trace_out = os.environ.get("PADDLE_TPU_BENCH_SERVE_TRACE_OUT")
    if "--trace-out" in sys.argv:
        trace_out = sys.argv[sys.argv.index("--trace-out") + 1]
    from paddle_tpu.profiler import trace as _trace
    from paddle_tpu.serving import autoscale as _autoscale
    if trace_out:
        _flags.set_flags({"FLAGS_tpu_trace": True})
    shared = workload == "shared-prefix"
    shaped = workload in ("diurnal", "bursty", "flash-crowd")
    n_req = _env_int("REQUESTS", 16)
    max_prompt = _env_int("PROMPT", 24)
    n_new = _env_int("NEW", 16)
    max_running = _env_int("MAX_RUNNING", 8)
    chunk = _env_int("CHUNK", 8)
    page = _env_int("PAGE", 16)
    n_sys = _env_int("SYS_PROMPTS", 2)
    spec_k = _env_int("SPEC_K", 3)
    max_queue = _env_int("MAX_QUEUE", 8 * max_running)
    pages_env = os.environ.get("PADDLE_TPU_BENCH_SERVE_PAGES")
    ttft_slo = os.environ.get("PADDLE_TPU_BENCH_SERVE_TTFT_SLO_MS")
    lat_slo = os.environ.get("PADDLE_TPU_BENCH_SERVE_LAT_SLO_MS")

    dev = jax.devices()[0]
    n_chips = jax.device_count()
    _log(f"backend={dev.platform} preset={preset} workload={workload} "
         f"requests={n_req} max_running={max_running} chunk={chunk} "
         f"page={page}")

    cfg = llama.preset(preset)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    max_model_len = min(cfg.max_position_embeddings,
                        max_prompt + n_new + chunk)
    slo = serving.SLOConfig(
        ttft_p95_s=float(ttft_slo) / 1e3 if ttft_slo else None,
        latency_p95_s=float(lat_slo) / 1e3 if lat_slo else None)
    reuse_kw = {}
    if shared:
        # self-draft: the draft model IS the target, so every proposal
        # verifies (acceptance rate ~1) — the bench isolates the spec
        # machinery's cost/benefit from draft-model quality
        reuse_kw = dict(prefix_cache=True,
                        spec=serving.SpecDecodeConfig(
                            cfg=cfg, params=params, k=spec_k))
    eng = serving.LLMEngine(cfg, params, max_running=max_running,
                            chunk=chunk, page_size=page,
                            num_pages=int(pages_env) if pages_env
                            else None,
                            max_model_len=max_model_len,
                            kv_dtype=(kv_dtype if kv_dtype != "bf16"
                                      else None),
                            max_queue=max_queue, slo=slo, **reuse_kw)

    rng = np.random.RandomState(0)
    arrivals = None
    if shaped:
        # the preset's seeded arrival process — the exact stream
        # tools/fleet_sim.py replays against the simulated fleet, so a
        # live bench and a sim run disagree only on time, never on
        # what arrived
        horizon_s = float(os.environ.get(
            "PADDLE_TPU_BENCH_SERVE_HORIZON_S", "60"))
        arrivals = _workloads.generate(
            workload, n_req, seed=_env_int("SEED", 0),
            horizon_s=horizon_s, prompt_len=max_prompt,
            max_new_tokens=n_new, vocab=cfg.vocab_size)
        prompts = [list(a.prompt) for a in arrivals]
    elif shared:
        # N requests over M distinct system prompts: the shared head is
        # most of the prompt (the few-shot/system-prompt shape), the
        # tail is per-request
        sys_len = max(max_prompt * 3 // 4, 2)
        sys_prompts = [list(rng.randint(0, cfg.vocab_size, sys_len))
                       for _ in range(n_sys)]
        prompts = [
            sys_prompts[i % n_sys]
            + list(rng.randint(0, cfg.vocab_size,
                               rng.randint(1, max(max_prompt - sys_len,
                                                  1) + 1)))
            for i in range(n_req)]
    else:
        prompts = [list(rng.randint(0, cfg.vocab_size,
                                    rng.randint(2, max_prompt + 1)))
                   for _ in range(n_req)]

    # warmup: compile both buckets before the clock starts.  In
    # shared-prefix mode warmup also runs one request per system
    # prompt, so the radix cache holds every shared head before the
    # measured run — the production shape, where system prompts are
    # warm long before the traffic being measured
    if shared:
        warm_ids = [eng.add_request(list(sp), 2) for sp in sys_prompts]
    else:
        warm_ids = [eng.add_request(prompts[0], 2)]
    while eng.has_work():
        eng.step()
    _log(f"warmup done ({len(eng._step_fns)} bucket(s) compiled), "
         f"warm tokens {eng.output_of(warm_ids[0])}")
    # drop the warmup's compile-inflated observations so the reported
    # percentiles describe steady-state serving only
    from paddle_tpu.profiler import metrics as _m
    _m.reset()
    eng._ttft_s.clear()
    eng._latency_s.clear()
    eng._queue_s.clear()
    eng._prefill_s.clear()
    eng._decode_s.clear()
    if trace_out:
        _trace.clear()  # measured-run lifecycle events only
    # the module stats dict is cumulative across the process — the
    # resilience block reports measured-run deltas from this snapshot
    base = serving.serving_stats()

    # measured run: half the requests up front, the rest arriving while
    # the batch is in flight — continuous admission, no drain between.
    # Overload runs (_MAX_QUEUE below the offered load) shed here with
    # the typed retriable AdmissionRejected — counted, never fatal.
    t_start = time.monotonic()
    rids = []
    shed_submits = 0

    def _submit(p):
        nonlocal shed_submits
        try:
            rids.append(eng.add_request(p, n_new))
        except serving.AdmissionRejected:
            shed_submits += 1

    steps = 0
    if shaped:
        # shaped presets arrive on the preset's own timeline, mapped
        # onto engine steps (workloads.step_schedule) — bursts land as
        # bursts instead of being smoothed into one-per-two-steps
        sched = _workloads.step_schedule(arrivals, max(2 * n_req, 1))
        last_step = max(sched) if sched else 0
        while eng.has_work() or steps <= last_step:
            for a in sched.get(steps, ()):
                _submit(list(a.prompt))
            eng.step()
            steps += 1
            if steps > 100000:
                raise RuntimeError("serve loop did not converge")
    else:
        for p in prompts[:n_req // 2]:
            _submit(p)
        pending = list(prompts[n_req // 2:])
        while eng.has_work() or pending:
            if pending and steps % 2 == 1:
                _submit(pending.pop(0))
            eng.step()
            steps += 1
            if steps > 100000:
                raise RuntimeError("serve loop did not converge")
    wall_s = time.monotonic() - t_start

    stats_now = serving.serving_stats()
    res = {k: int(stats_now[k] - base[k])
           for k in ("shed", "admission_waits", "recoveries",
                     "quarantined", "deadline_expired",
                     "callback_errors")}
    reqs = [eng._requests[r] for r in rids]
    done = [r for r in reqs if r.state.value == "finished"]
    assert all(len(r.output) == n_new for r in done), \
        "request finished short"
    if not (res["quarantined"] or res["deadline_expired"]):
        # without a terminal resilience event every admitted request
        # must complete — shedding only ever rejects at the front door
        assert len(done) == len(reqs), "admitted request lost"
    tokens = sum(len(r.output) for r in done)
    ttfts = [r.first_token_s - r.arrival_s for r in done
             if r.first_token_s is not None]
    lats = [r.finish_s - r.arrival_s for r in done
            if r.finish_s is not None]
    ttft_p50, ttft_p95 = _percentiles("serve_ttft_seconds", ttfts)
    lat_p50, lat_p95 = _percentiles("serve_request_latency_seconds",
                                    lats)
    tps_chip = tokens / wall_s / max(n_chips, 1)
    stats = stats_now

    def _ms(v):
        return None if v is None else round(v * 1e3, 2)

    # work-reuse report (measured-run deltas): prefix hit-rate over
    # the admitted prompt tokens — every hit token is a prefill token
    # the engine never fed — and the spec-decode acceptance rate
    hit = int(stats_now["prefix_hit_tokens"] - base["prefix_hit_tokens"])
    proposed = int(stats_now["spec_proposed"] - base["spec_proposed"])
    accepted = int(stats_now["spec_accepted"] - base["spec_accepted"])
    prompt_tokens = sum(len(r.prompt) for r in reqs)
    reuse = {
        "prefix_hit_tokens": hit,
        "prompt_tokens": prompt_tokens,
        "prefix_hit_rate": (round(hit / prompt_tokens, 4)
                            if prompt_tokens else 0.0),
        "prefill_tokens_saved": hit,
        "spec_proposed": proposed,
        "spec_accepted": accepted,
        "spec_acceptance_rate": (round(accepted / proposed, 4)
                                 if proposed else 0.0),
    }

    rep = eng.slo_report()
    res["slo"] = {
        "ttft_p95_ms": _ms(rep["ttft_p95_s"]),
        "ttft_slo_ms": _ms(rep["ttft_slo_s"]),
        "ttft_ok": rep["ttft_ok"],
        "latency_p95_ms": _ms(rep["latency_p95_s"]),
        "latency_slo_ms": _ms(rep["latency_slo_s"]),
        "latency_ok": rep["latency_ok"],
    }
    bd = rep.get("breakdown")
    if bd:
        res["slo"]["ttft_breakdown_ms"] = {
            "queue_p95": _ms(bd["queue_p95_s"]),
            "prefill_p95": _ms(bd["prefill_p95_s"]),
            "decode_p95": _ms(bd["decode_p95_s"]),
            "samples": bd["samples"],
        }

    # fleet block: the per-replica service model calibrated from this
    # run's measured step wall-times (by compiled bucket), plus the
    # capacity arithmetic fleet_sim and the autoscaler plan with —
    # predicted rps-per-chip next to the measured trajectory above
    sm = eng.service_model()
    mean_prompt = (prompt_tokens // len(reqs)) if reqs else max_prompt
    cap_rps = sm.capacity_rps(mean_prompt, n_new)
    offered_rps = (len(rids) + shed_submits) / wall_s if wall_s else 0.0
    fleet = {
        "calibrated": sm.calibrated,
        "prefill_chunk_ms": _ms(sm.prefill_chunk_s),
        "decode_step_ms": _ms(sm.decode_step_s),
        "concurrency": sm.concurrency,
        "capacity_rps_per_chip": round(cap_rps, 3),
        "offered_rps": round(offered_rps, 3),
        "min_chips_for_offered": _autoscale.replicas_for(
            sm, offered_rps, prompt_len=max(mean_prompt, 1),
            new_tokens=n_new),
    }

    trace_sidecar = None
    if trace_out:
        os.makedirs(trace_out, exist_ok=True)
        trace_sidecar = _trace.write_sidecar(
            _trace.sidecar_path(trace_out),
            extra={"bench": "serve", "workload": workload,
                   "requests": len(rids)})
        _log(f"trace sidecar: {trace_sidecar} (read with "
             "tools/trace_report.py)")

    result = {
        "metric": "serve_tokens_per_sec_chip",
        "value": round(tps_chip, 2),
        "unit": "tokens/s/chip",
        "ttft_p50_ms": round(ttft_p50 * 1e3, 2),
        "ttft_p95_ms": round(ttft_p95 * 1e3, 2),
        "latency_p50_ms": round(lat_p50 * 1e3, 2),
        "latency_p95_ms": round(lat_p95 * 1e3, 2),
        "requests": len(rids),
        "shed_submits": shed_submits,
        "max_queue": max_queue,
        "workload": workload,
        "reuse": reuse,
        "fleet": fleet,
        "resilience": res,
        "tokens": tokens,
        "steps": steps,
        "wall_seconds": round(wall_s, 3),
        "prefill_tokens": int(stats["prefill_tokens"]),
        "decode_tokens": int(stats["decode_tokens"]),
        "preemptions": int(stats["requests_preempted"]),
        "compiled_buckets": int(stats["compiled_buckets"]),
        "max_running": max_running,
        "chunk": chunk,
        "page_size": page,
        # predicted-vs-measured capacity: the pool's own arithmetic
        # (pages / blocks-per-request), pod_report serving's measured
        # counterpart for the BENCH_SERVE trajectory
        "kv": {
            "dtype": kv_dtype,
            "pages": int(eng.num_pages),
            "scale_pool_bytes": int(eng._scale_bytes),
            "max_concurrent_predicted":
                (eng.num_pages - 1) // eng.max_blocks,
        },
        "preset": preset,
        "device": getattr(dev, "device_kind", dev.platform),
        "chips": n_chips,
    }
    if trace_sidecar is not None:
        result["trace_sidecar"] = trace_sidecar
    exp = _exporter_active()
    if exp is not None:
        result["metrics_port"] = exp.port
    try:
        os.makedirs(os.path.dirname(_LAST_FILE), exist_ok=True)
        with open(_LAST_FILE, "w") as f:
            json.dump(result, f)
    except OSError:
        pass
    return result


def _exporter_active():
    """The live exporter, if FLAGS_tpu_metrics_port started one when the
    engine was constructed."""
    try:
        from paddle_tpu.profiler import exporter
        return exporter.active()
    except Exception:
        return None


def _error_result(msg, incident=None):
    out = {
        "metric": "serve_tokens_per_sec_chip",
        "value": 0.0,
        "unit": "tokens/s/chip",
        "error": msg[-1500:] or "unknown",
    }
    if incident is None:
        try:
            from paddle_tpu.runtime.watchdog import last_incident
            incident = last_incident()
        except Exception:
            incident = None
    if incident is not None:
        out["incident"] = incident
    for path in (_LAST_FILE, _LAST_FILE_LEGACY):
        try:
            with open(path) as f:
                out["last_measured"] = json.load(f)
            break
        except Exception:
            continue
    return out


def run():
    """Never exit without the BENCH_SERVE line (same contract as
    bench.py): failures and hangs print value 0.0 with the error and
    the runtime health layer's incident record attached."""
    from paddle_tpu.runtime.watchdog import (PhaseTimeout,
                                             persist_incidents,
                                             run_with_deadline)

    timeout_s = float(os.environ.get("PADDLE_TPU_BENCH_TIMEOUT", "900"))
    try:
        result = run_with_deadline(main, timeout_s, phase="serve_measure")
    except PhaseTimeout:
        result = _error_result(
            f"bench_serve timed out after {timeout_s:.0f}s "
            "(compile or execute hang)")
        print("BENCH_SERVE " + json.dumps(result))
        sys.stdout.flush()
        _ledger_append(result)
        try:
            # os._exit skips atexit — flush the incident sidecar now
            persist_incidents()
        except OSError as e:
            _log(f"incident persist failed: {e}")
        os._exit(0)  # the hung measure thread would block a clean exit
    except BaseException as e:  # noqa: BLE001 — the line must print
        result = _error_result(str(e) or repr(e))
    print("BENCH_SERVE " + json.dumps(result))
    _ledger_append(result)
    return 0


if __name__ == "__main__":
    sys.exit(run())
