"""Benchmark: Llama train-step MFU on one chip (BASELINE.json north star:
Llama-2 pretrain >=40% MFU on v5p — here measured single-chip on a scaled
config with the identical compute path: bf16 matmuls on MXU, Pallas/XLA
fused attention, remat, fused adamw update inside one jit).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
from __future__ import annotations

import functools
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

_REPO = os.path.dirname(os.path.abspath(__file__))
_LAST_FILE = os.path.join(_REPO, ".bench_last.json")
_LEDGER_OUT = os.environ.get("PADDLE_TPU_BENCH_LEDGER_OUT")
_T0 = time.monotonic()


def _log(msg):
    sys.stderr.write(f"bench[{time.monotonic() - _T0:6.1f}s]: {msg}\n")
    sys.stderr.flush()


def _ledger_append(result):
    """Append the normalized row to the perf ledger (--ledger-out).

    Runs on success AND error paths — an error round is a ledger row too
    — but a ledger failure must never break the bench JSON line."""
    if not _LEDGER_OUT:
        return
    try:
        from paddle_tpu.profiler import ledger as _ledger
        cmd = "python " + " ".join(
            [os.path.basename(sys.argv[0] or "bench.py")] + sys.argv[1:])
        row = _ledger.from_bench_result(result, ts=time.time(), cmd=cmd)
        _ledger.append(_LEDGER_OUT, row)
        _log(f"ledger row appended to {_LEDGER_OUT}")
    except Exception as e:
        _log(f"ledger append failed: {e}")


def _enable_compile_cache():
    """Persistent XLA compilation cache: repeat runs (and driver retries)
    skip the multi-minute trace+compile of the 1B-param train step. Now
    lives in the framework (core.compile_cache, FLAGS_tpu_persistent_cache)
    so tests/examples/tools warm-start too; bench always forces it on.
    Best effort — the remote-compile tunnel may bypass it."""
    try:
        from paddle_tpu.core import compile_cache
        path = compile_cache.ensure(force=True)
        if path is None:
            _log("compilation cache unavailable")
    except Exception as e:
        _log(f"compilation cache unavailable: {e}")

# peak bf16 TFLOP/s by device generation
_PEAK_TFLOPS = {
    "v5 lite": 197.0, "v5litepod": 197.0, "v5e": 197.0,
    "v5p": 459.0, "v5": 459.0,
    "v4": 275.0, "v3": 123.0, "v2": 45.0,
    "v6 lite": 918.0, "v6e": 918.0,
    "cpu": 0.5,  # nominal, so the script still reports off-TPU
}


def _peak_flops(dev) -> float:
    kind = getattr(dev, "device_kind", "cpu").lower()
    for key, tf in _PEAK_TFLOPS.items():
        if key in kind:
            return tf * 1e12
    return _PEAK_TFLOPS["cpu"] * 1e12


def main():
    """Measure and return the result dict (raises on total failure; run()
    wraps that into an error JSON line)."""
    from paddle_tpu.models.llama import LlamaConfig, init_params, loss_fn
    import optax

    _enable_compile_cache()
    _log("initializing device backend")
    dev = jax.devices()[0]
    _log(f"device ready: {getattr(dev, 'device_kind', dev)}")
    on_tpu = "tpu" in getattr(dev, "platform", "cpu").lower() or \
        "tpu" in getattr(dev, "device_kind", "").lower()

    if on_tpu:
        # ~0.95B params: fits one v5e chip (16G HBM) with Adam state.
        # remat-policy ladder: "dots" keeps matmul outputs (backward does
        # no matmul recompute — fastest) but costs the most HBM; fall back
        # to full remat, then a smaller batch, if it doesn't fit.
        variants = [("dots", 4), ("full", 4), ("full", 2)]
        base = dict(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_hidden_layers=16, num_attention_heads=16,
            num_key_value_heads=16, max_position_embeddings=2048,
            dtype=jnp.bfloat16, use_remat=True)
        S, iters = 2048, 10
    else:  # CPU smoke config
        variants = [("full", 2)]
        base = dict(
            vocab_size=1024, hidden_size=256, intermediate_size=512,
            num_hidden_layers=4, num_attention_heads=4,
            num_key_value_heads=4, max_position_embeddings=512,
            dtype=jnp.float32, use_remat=False)
        S, iters = 256, 3

    tuned_blocks = None
    if on_tpu:
        # Autotune the flash-attention block sizes for the bench shape
        # before the step is traced (phi/kernels/autotune analog). A
        # committed cache (.flash_autotune.json, measured on v5e) seeds
        # the winner so the usual run skips the 2-3 min sweep; absent or
        # stale entries fall through to live tuning. Bounded and
        # best-effort: a tuning failure must never cost the number.
        try:
            from paddle_tpu.ops import autotune, pallas_ops
            import os as _os
            cache_file = _os.path.join(_os.path.dirname(
                _os.path.abspath(__file__)), ".flash_autotune.json")
            if _os.path.exists(cache_file):
                autotune.load(cache_file)
            tuned_blocks = pallas_ops.tune_causal_attention(
                B=4, S=S, H=base["num_attention_heads"],
                D=base["hidden_size"] // base["num_attention_heads"],
                dtype=jnp.bfloat16, budget_s=120, iters=30, verbose=True)
            _log(f"flash blocks: {tuned_blocks} (cache hit is instant; "
                 "a live sweep is budgeted 120s)")
            # fused decoder-block kernels (the path the step actually
            # takes on TPU under FLAGS_tpu_fused_blocks=auto): tune
            # their block shapes too, same cache / budget discipline
            tuned_fused = pallas_ops.tune_fused_blocks(
                B=1, S=S, H=base["hidden_size"],
                D=base["hidden_size"] // base["num_attention_heads"],
                I=base["intermediate_size"],
                dtype=jnp.bfloat16, budget_s=120, iters=10, verbose=True)
            _log(f"fused blocks: {tuned_fused}")
        except Exception as e:
            sys.stderr.write(f"bench: autotune skipped: {e}\n")

    def run_variant(policy, B):
        cfg = LlamaConfig(remat_policy=policy, **base)
        params = init_params(cfg, jax.random.PRNGKey(0))
        opt = optax.adamw(3e-4, b1=0.9, b2=0.95, weight_decay=0.1)
        opt_state = opt.init(params)

        # donate params + opt_state: the update aliases into the same HBM
        # buffers instead of allocating a second copy of every tensor
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def step(params, opt_state, batch):
            (total, ce), grads = jax.value_and_grad(
                lambda p: loss_fn(cfg, p, batch), has_aux=True)(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, ce

        rng = np.random.default_rng(0)
        batch = {
            "input_ids": jnp.asarray(
                rng.integers(0, cfg.vocab_size, (B, S)), jnp.int32),
            "labels": jnp.asarray(
                rng.integers(0, cfg.vocab_size, (B, S)), jnp.int32),
        }

        # AOT-first when xmem capture is on: one lower().compile() both
        # serves the run and records the executable's HBM/FLOP analysis
        from paddle_tpu.profiler import xmem
        step_call = step
        if xmem.enabled():
            compiled = xmem.aot_compile(
                "bench", f"llama_step[remat={policy},B={B}]",
                step, (params, opt_state, batch))
            if compiled is not None:
                step_call = compiled

        # compile + warmup; scalar readback (not block_until_ready)
        # because the axon tunnel's block_until_ready does not reliably
        # fence execution
        _log(f"compiling variant remat={policy} B={B}")
        try:
            params, opt_state, ce = step_call(params, opt_state, batch)
        except Exception:
            if step_call is step:
                raise
            step_call = step  # AOT dispatch quirk: retrace instead
            params, opt_state, ce = step_call(params, opt_state, batch)
        float(ce)
        _log("compile + warmup done; measuring")

        t0 = time.perf_counter()
        for _ in range(iters):
            params, opt_state, ce = step_call(params, opt_state, batch)
        float(ce)
        dt = (time.perf_counter() - t0) / iters
        return cfg, params, dt, B

    def _is_oom(e):
        return "RESOURCE_EXHAUSTED" in str(e) or "Out of memory" in str(e)

    last_err = None
    done = False
    for policy, B in variants:
        attempts = 2  # second attempt: flash kernels disabled
        for _ in range(attempts):
            try:
                cfg, params, dt, B = run_variant(policy, B)
                done = True
                break
            except Exception as e:
                # keep only the message: the traceback would pin the failed
                # variant's multi-GB locals in HBM while the next rung runs
                last_err = RuntimeError(str(e)[-2000:])
                was_oom = _is_oom(e)
                del e
                import gc
                gc.collect()
                if was_oom:
                    break  # next rung of the batch/remat ladder
                from paddle_tpu.ops import pallas_ops
                if pallas_ops._DISABLE:
                    break  # already on the jnp path; a real error — next rung
                # compile/runtime error in the Pallas path: fall back to the
                # XLA-fused jnp attention and retry the same variant. The
                # bench must always record a number (r01/r02 recorded none).
                pallas_ops._DISABLE = True
                sys.stderr.write(
                    f"bench: disabling Pallas flash after: {last_err}\n")
        if done:
            break
    if not done:
        raise last_err

    n_params = sum(int(np.prod(a.shape))
                   for a in jax.tree_util.tree_leaves(params))
    tokens = B * S
    # 6ND model FLOPs + attention 12*B*S^2*H*L (fwd+bwd, causal halves it)
    attn_flops = 6 * B * S * S * cfg.hidden_size * cfg.num_hidden_layers
    flops = 6.0 * n_params * tokens + attn_flops
    mfu = 100.0 * flops / dt / _peak_flops(dev)
    tok_per_sec = tokens / dt

    from paddle_tpu.ops import autotune, pallas_ops
    used_flash = pallas_ops.flash_attention_available(
        (B, S, cfg.num_attention_heads,
         cfg.hidden_size // cfg.num_attention_heads))
    used_fused_attn = on_tpu and pallas_ops.fused_attention_available(
        (B, S, cfg.hidden_size), cfg.head_dim, cfg.dtype)
    used_fused_mlp = on_tpu and pallas_ops.fused_mlp_available(
        (B, S, cfg.hidden_size), cfg.intermediate_size, cfg.dtype)
    result = {
        "metric": "llama_train_mfu_1chip",
        "value": round(mfu, 2),
        "unit": "percent_mfu",
        "vs_baseline": round(mfu / 40.0, 3),
        "detail": {
            "tokens_per_sec_per_chip": round(tok_per_sec, 1),
            "step_ms": round(dt * 1e3, 1),
            "n_params": n_params,
            "device": getattr(dev, "device_kind", str(dev)),
            "batch": B, "seq": S,
            "attention": "pallas_flash" if used_flash else "xla_jnp",
            "flash_blocks": (list(tuned_blocks)
                             if (tuned_blocks and used_flash) else None),
            "fused_blocks": {"attention": used_fused_attn,
                             "mlp": used_fused_mlp},
            "remat_policy": cfg.remat_policy if cfg.use_remat else "none",
            # what was tuned and how the cache behaved, so BENCH_rNN
            # records carry the winning configs, not just the MFU
            "autotune": {"stats": autotune.cache_stats(),
                         "configs": autotune.entries()},
        },
    }
    # xmem capture (when enabled): the step executable's static HBM peak
    from paddle_tpu.profiler import xmem
    bench_profiles = [p for p in xmem.profiles() if p["source"] == "bench"]
    if bench_profiles:
        p = max(bench_profiles, key=lambda q: q["peak_bytes"])
        result["detail"]["peak_hbm_bytes"] = p["peak_bytes"]
        result["detail"]["temp_hbm_bytes"] = p["temp_bytes"]
    if on_tpu:
        # record for future _error_result fallbacks (committed when a
        # real-chip run succeeds, so the provenance commit is the one
        # that measured it)
        try:
            import subprocess
            commit = subprocess.run(
                ["git", "-C", _REPO, "rev-parse", "--short", "HEAD"],
                capture_output=True, text=True, timeout=10,
            ).stdout.strip() or "unknown"
            with open(_LAST_FILE, "w") as f:
                json.dump({"value": result["value"], "unit": result["unit"],
                           "tokens_per_sec_per_chip":
                               result["detail"]["tokens_per_sec_per_chip"],
                           "note": f"{result['detail']['device']}, "
                                   f"bench.py@{commit}"}, f, indent=1)
        except Exception as e:
            _log(f"could not write {_LAST_FILE}: {e}")
    return result


def multichip_main(n_devices=8, trace_out=None):
    """--multichip preset: the Plan compile path on ``n_devices`` virtual
    host-platform devices (dp=2 x pp=2 x mp=2), 1F1B with double-buffered
    p2p (overlap=True) against the lockstep scan on the same config.

    Reports per-step wall time for both schedules, the PR-1 collective
    metrics (bytes/calls/latency from the instrumented collective API),
    modeled per-step collective traffic, and the static-schedule
    ``overlap_fraction`` (fraction of stage-boundary transfers with a
    full tick of slack to ride under compute — real async timing is not
    observable on the CPU backend, so the number comes from the shared
    schedule model in ``distributed.overlap``). With ``trace_out`` the
    flight recorder is enabled: train/step spans plus the recorded
    pipeline schedule land in a rank-tagged JSONL sidecar there, the
    measured overlap fraction (scored from the *recorded* schedule) is
    reported next to the static one, and the sidecar path rides in the
    JSON line for ``tools/trace_report.py``."""
    jax.config.update("jax_platforms", "cpu")
    import _xla_cpu_flags
    _xla_cpu_flags.ensure(device_count=n_devices)

    import optax
    from paddle_tpu.core.flags import set_flags
    from paddle_tpu.distributed.overlap import (measured_overlap,
                                                overlap_fraction,
                                                schedule_events,
                                                transfer_stats)
    from paddle_tpu.distributed.plan import Plan
    from paddle_tpu.models.llama import LlamaConfig
    from paddle_tpu.profiler import trace as _trace

    set_flags({"FLAGS_tpu_metrics": True,
               "FLAGS_tpu_trace": trace_out is not None})
    _enable_compile_cache()
    devices = jax.devices()
    _log(f"{len(devices)} virtual devices ready")

    dp, pp, mp = 2, 2, 2
    n_micro = 4
    cfg = LlamaConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=4, num_attention_heads=4,
                      num_key_value_heads=4, max_position_embeddings=64,
                      dtype=jnp.float32, use_remat=False)
    B, S = 8, 32
    rng = np.random.default_rng(0)
    batch_host = {
        "input_ids": rng.integers(0, cfg.vocab_size, (B, S)),
        "labels": rng.integers(0, cfg.vocab_size, (B, S)),
    }

    def measure(overlap):
        from jax.sharding import NamedSharding, PartitionSpec as P
        plan = Plan(dp=dp, pp=pp, mp=mp, schedule="1f1b",
                    n_microbatches=n_micro, overlap=overlap)
        step_fn, init_fn = plan.train_step(
            cfg, devices, optimizer=optax.sgd(1e-3), verify=False)
        params, opt_state = init_fn(jax.random.PRNGKey(0))
        topo = step_fn.plan_topology
        sh = NamedSharding(topo.mesh, P(topo.batch_axes, None))
        batch = {k: jax.device_put(jnp.asarray(v, jnp.int32), sh)
                 for k, v in batch_host.items()}
        params, opt_state, m = step_fn(params, opt_state, batch)  # compile
        jax.block_until_ready(m["loss"])
        iters = 5
        t0 = time.perf_counter()
        for _ in range(iters):
            params, opt_state, m = step_fn(params, opt_state, batch)
        jax.block_until_ready(m["loss"])
        return (time.perf_counter() - t0) / iters * 1e3, float(m["loss"])

    _log("measuring overlapped 1F1B Plan path")
    overlap_ms, loss_o = measure(True)
    evs_after_overlap = _trace.events() if trace_out else []
    _log("measuring lockstep 1F1B scan")
    lockstep_ms, loss_l = measure(False)

    # static schedule model: serialized transfer->compute ticks
    ev_o = schedule_events(pp, n_micro, overlap=True)
    ev_l = schedule_events(pp, n_micro, overlap=False)
    st_o, st_l = transfer_stats(ev_o), transfer_stats(ev_l)

    # measured schedule: scored from what the flight recorder saw the
    # executed plans emit — must match the static model bit-for-bit
    measured = None
    trace_sidecar = None
    if trace_out:
        all_evs = _trace.events()
        meas_o = _trace.pipeline_schedule_events(evs_after_overlap)
        meas_l = _trace.pipeline_schedule_events(
            all_evs[len(evs_after_overlap):])
        measured = {
            "overlap_fraction": round(
                measured_overlap(meas_o)["overlap_fraction"], 3),
            "overlap_fraction_lockstep": round(
                measured_overlap(meas_l)["overlap_fraction"], 3),
            "matches_static": meas_o == ev_o and meas_l == ev_l,
        }
        os.makedirs(trace_out, exist_ok=True)
        trace_sidecar = _trace.write_sidecar(
            _trace.sidecar_path(trace_out),
            extra={"bench": "multichip", "devices": len(devices)})
        _log(f"trace sidecar: {trace_sidecar}")

    # modeled per-step collective traffic on this plan
    itemsize = 4  # fp32
    edge_bytes = (B // dp // n_micro) * S * cfg.hidden_size * itemsize
    p2p_bytes = 2 * n_micro * (pp - 1) * edge_bytes  # fwd + bwd edges
    from paddle_tpu.models.llama import init_params
    n_params = sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(
        jax.eval_shape(functools.partial(init_params, cfg),
                       jax.ShapeDtypeStruct((2,), jnp.uint32))))
    grad_bytes = n_params * itemsize

    # exercise the instrumented collective API once at grad volume so
    # the PR-1 metric counters carry real measured entries for this run
    import paddle_tpu as paddle
    from paddle_tpu.profiler import metrics as _metrics
    paddle.distributed.all_reduce(
        paddle.to_tensor(np.zeros(n_params // 64, np.float32)))
    snap = _metrics.snapshot()
    coll = {k: v for k, v in snap.items() if k.startswith("collective_")}

    result = {
        "metric": "llama_train_multichip_step",
        "value": round(overlap_ms, 2),
        "unit": "ms_per_step",
        # baseline = the lockstep scan on the identical config
        "vs_baseline": round(lockstep_ms / overlap_ms, 3),
        "detail": {
            "plan": {"dp": dp, "pp": pp, "mp": mp, "schedule": "1f1b",
                     "n_microbatches": n_micro, "overlap": True},
            "devices": len(devices),
            "device": getattr(devices[0], "device_kind", "cpu"),
            "batch": B, "seq": S,
            "step_ms_overlap": round(overlap_ms, 2),
            "step_ms_lockstep": round(lockstep_ms, 2),
            "loss": round(loss_o, 6),
            "loss_lockstep": round(loss_l, 6),
            "overlap": {
                "overlap_fraction": round(overlap_fraction(ev_o), 3),
                "overlap_fraction_lockstep":
                    round(overlap_fraction(ev_l), 3),
                "serialized_transfers": st_o["serialized_transfers"],
                "serialized_transfers_lockstep":
                    st_l["serialized_transfers"],
                "total_transfers": st_o["total_transfers"],
            },
            "collective_bytes_modeled": {
                "pipeline_p2p_per_step": p2p_bytes,
                "grad_allreduce_per_step": grad_bytes,
            },
            "collective_metrics": coll,
        },
    }
    if measured is not None:
        result["detail"]["overlap"]["measured"] = measured
        result["detail"]["trace_sidecar"] = trace_sidecar
    assert st_o["serialized_transfers"] < st_l["serialized_transfers"], \
        "overlap schedule must serialize strictly fewer transfers"
    return result


def run_multichip(n_devices=8, trace_out=None):
    """--multichip run harness: same never-exit-silent contract as
    run(), on the virtual-pod Plan path."""
    from paddle_tpu.runtime.watchdog import (PhaseTimeout,
                                             persist_incidents,
                                             run_with_deadline)
    timeout_s = float(os.environ.get("PADDLE_TPU_BENCH_TIMEOUT", "1000"))
    try:
        result = run_with_deadline(
            lambda: multichip_main(n_devices, trace_out=trace_out),
            timeout_s, phase="measure")
    except PhaseTimeout:
        result = _error_result(
            f"multichip bench timed out after {timeout_s:.0f}s")
        print(json.dumps(result))
        sys.stdout.flush()
        _ledger_append(result)
        _persist_incidents_quietly(persist_incidents)
        os._exit(0)
    except BaseException as e:  # noqa: BLE001 — the line must print
        result = _error_result(str(e) or repr(e))
    print(json.dumps(result))
    _ledger_append(result)
    return 0


def multichip_gang_main(nproc, trace_out=None, steps=2):
    """--multichip --gang N: the same llama pipeline preset, but run as
    N REAL worker processes through ``python -m
    paddle_tpu.distributed.launch`` (pp spans process boundaries over
    the gloo CPU backend) instead of N virtual devices in one process.
    Parses the per-rank ``GANG_RESULT`` lines out of the workerlogs and
    folds them into one bench result whose ``detail.real_processes``
    records the actual process count — the ledger row for a gang run is
    distinguishable from a virtual-device run."""
    import re
    import subprocess
    import tempfile

    log_dir = tempfile.mkdtemp(prefix="bench_gang_")
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--nproc_per_node", str(nproc), "--max_restarts", "0",
           "--log_dir", log_dir,
           "--module", "paddle_tpu.distributed.gang",
           "--steps", str(steps)]
    if trace_out:
        cmd += ["--trace-out", trace_out]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.pop("PTQ_CHAOS", None)  # never inherit chaos into a bench pod
    # each worker must see exactly ONE local device: a stray
    # host-platform-device-count flag would multiply the global device
    # count and break the pp=world_size plan
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+\s*",
                   " ", env.get("XLA_FLAGS", "")).strip()
    if flags:
        env["XLA_FLAGS"] = flags
    else:
        env.pop("XLA_FLAGS", None)
    timeout_s = float(os.environ.get("PADDLE_TPU_BENCH_TIMEOUT", "1000"))
    _log(f"launching {nproc}-process gang pod (logs: {log_dir})")
    t0 = time.monotonic()
    proc = subprocess.run(cmd, env=env, cwd=_REPO, timeout=timeout_s,
                          capture_output=True, text=True)
    wall_s = time.monotonic() - t0

    results = {}
    for rank in range(nproc):
        path = os.path.join(log_dir, f"workerlog.{rank}")
        try:
            with open(path) as f:
                for ln in f:
                    if ln.startswith("GANG_RESULT "):
                        r = json.loads(ln[len("GANG_RESULT "):])
                        results[r["rank"]] = r
        except OSError:
            pass
    if proc.returncode != 0 or len(results) != nproc:
        tail = (proc.stderr or proc.stdout or "")[-800:]
        raise RuntimeError(
            f"gang pod failed: rc={proc.returncode}, "
            f"{len(results)}/{nproc} GANG_RESULT lines "
            f"(logs: {log_dir})\n{tail}")

    r0 = results[0]
    losses0 = r0["losses"]
    for rank, r in sorted(results.items()):
        if r["losses"] != losses0:
            raise RuntimeError(
                f"rank {rank} loss trajectory diverged from rank 0: "
                f"{r['losses']} != {losses0}")
    # None = tracing off for that rank; False = recorded schedule
    # diverged from the static model — a hard failure
    matches = [r["matches_static"] for _, r in sorted(results.items())]
    if any(m is False for m in matches):
        raise RuntimeError(
            f"recorded 1F1B schedule diverged from static model: "
            f"per-rank matches_static={matches}")
    step_ms = max(r["step_ms"] for r in results.values())
    return {
        "metric": "llama_train_multichip_step",
        "value": round(step_ms, 2),
        "unit": "ms_per_step",
        "vs_baseline": None,  # no lockstep twin run in gang mode
        "detail": {
            "real_processes": nproc,
            "plan": {"dims": r0["plan"], "schedule": r0["schedule"],
                     "n_microbatches": r0["n_microbatches"],
                     "overlap": r0["overlap"]},
            "world_size": r0["world_size"],
            "steps": r0["steps"],
            "loss": losses0[-1] if losses0 else None,
            "losses": losses0,
            "step_ms_per_rank": {str(rank): r["step_ms"]
                                 for rank, r in sorted(results.items())},
            "matches_static": matches,
            "pod_wall_s": round(wall_s, 2),
            "log_dir": log_dir,
        },
    }


def run_multichip_gang(nproc, trace_out=None, steps=2):
    """--multichip --gang harness: same never-exit-silent contract."""
    from paddle_tpu.runtime.watchdog import persist_incidents
    try:
        result = multichip_gang_main(nproc, trace_out=trace_out,
                                     steps=steps)
    except BaseException as e:  # noqa: BLE001 — the line must print
        result = _error_result(str(e) or repr(e))
        result["metric"] = "llama_train_multichip_step"
        print(json.dumps(result))
        sys.stdout.flush()
        _ledger_append(result)
        _persist_incidents_quietly(persist_incidents)
        return 1
    print(json.dumps(result))
    _ledger_append(result)
    return 0


def _persist_incidents_quietly(persist_fn):
    """Flush the incident buffer before an os._exit path (which skips
    atexit) — the post-mortem sidecar must land even on a hang exit."""
    try:
        persist_fn()
    except OSError as e:
        _log(f"incident persist failed: {e}")


def _init_device_with_retries(probe_fn, window_s=240.0, base_delay=5.0,
                              factor=2.0, max_delay=60.0, log=None,
                              sleep=time.sleep, clock=time.monotonic):
    """Delegates to the shared runtime watchdog
    (paddle_tpu.runtime.watchdog.init_with_retries, where bench's
    original retry loop now lives); kept under the bench-local name for
    existing callers. Returns (ok, attempts, last_error)."""
    from paddle_tpu.runtime.watchdog import init_with_retries
    return init_with_retries(
        probe_fn, window_s=window_s, base_delay=base_delay,
        factor=factor, max_delay=max_delay, log=log, sleep=sleep,
        clock=clock, phase="device_init")


def _error_result(msg, incident=None):
    out = {
        "metric": "llama_train_mfu_1chip",
        "value": 0.0,
        "unit": "percent_mfu",
        "vs_baseline": 0.0,
        "error": msg[-1500:] or "unknown",
    }
    # structured incident record from the runtime health layer: what
    # phase hung/failed and against which deadline — a 0.0 with a cause,
    # never a silent stale carry-forward
    if incident is None:
        try:
            from paddle_tpu.runtime.watchdog import last_incident
            incident = last_incident()
        except Exception:
            incident = None
    if incident is not None:
        out["incident"] = incident
    # last successful real-chip measurement, if one is recorded (written
    # by a successful run and committed alongside the code it measured —
    # never a hardcoded constant that outlives the code it described)
    try:
        with open(_LAST_FILE) as f:
            out["last_measured"] = json.load(f)
    except Exception:
        pass
    return out


def run():
    """Never exit without the JSON line: a failed bench prints value 0.0
    with the error attached, and the shared runtime watchdog
    (paddle_tpu.runtime.watchdog) covers hangs by printing the error
    record — with the structured incident attached — before the
    driver's own timeout kills the process silently. Stage 1: device
    init gets a retry window (PADDLE_TPU_BENCH_DEVICE_TIMEOUT total,
    exponential backoff from PADDLE_TPU_BENCH_DEVICE_RETRY_DELAY) —
    transient claim failures retry, a hung make_c_api_client fails fast
    instead of burning the whole budget (round 3's 0.0). Stage 2: the
    full measurement must land within PADDLE_TPU_BENCH_TIMEOUT."""
    from paddle_tpu.runtime.watchdog import (PhaseTimeout,
                                             persist_incidents,
                                             run_with_deadline)
    from paddle_tpu.testing.chaos import chaos_point

    timeout_s = float(os.environ.get("PADDLE_TPU_BENCH_TIMEOUT", "1000"))
    dev_timeout_s = float(
        os.environ.get("PADDLE_TPU_BENCH_DEVICE_TIMEOUT", "240"))
    retry_delay_s = float(
        os.environ.get("PADDLE_TPU_BENCH_DEVICE_RETRY_DELAY", "5"))

    def _probe():
        chaos_point("device.init")
        jax.devices()

    # probe device init (with retries) before measurement starts, so it
    # never runs against a dead tunnel
    ok, attempts, err = _init_device_with_retries(
        _probe, window_s=dev_timeout_s, base_delay=retry_delay_s,
        log=_log)
    if not ok:
        result = _error_result(
            f"device backend init failed within {dev_timeout_s:.0f}s "
            f"({attempts} attempt(s); TPU tunnel down or unclaimable): "
            f"{err}")
        print(json.dumps(result))
        sys.stdout.flush()
        _ledger_append(result)
        _persist_incidents_quietly(persist_incidents)
        os._exit(0)  # a hung init thread would block a clean exit

    try:
        result = run_with_deadline(main, timeout_s, phase="measure")
    except PhaseTimeout:
        result = _error_result(
            f"bench timed out after {timeout_s:.0f}s "
            "(compile or execute hang)")
        print(json.dumps(result))
        sys.stdout.flush()
        _ledger_append(result)
        _persist_incidents_quietly(persist_incidents)
        os._exit(0)  # the hung measure thread would block a clean exit
    except BaseException as e:  # noqa: BLE001 — the line must print
        result = _error_result(str(e) or repr(e))
    print(json.dumps(result))
    _ledger_append(result)
    return 0


if __name__ == "__main__":
    import argparse
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--multichip", action="store_true",
                    help="bench the distributed Plan compile path "
                         "(1F1B + overlap) on virtual host devices "
                         "instead of the 1-chip MFU bench")
    ap.add_argument("--devices", type=int, default=8,
                    help="virtual device count for --multichip")
    ap.add_argument("--gang", type=int, default=None, metavar="N",
                    help="with --multichip: run the preset as N real "
                         "worker processes through the launcher "
                         "(pp crosses process boundaries) instead of "
                         "N virtual devices in one process")
    ap.add_argument("--gang-steps", type=int, default=2,
                    help="train steps for the --gang pod (default 2)")
    ap.add_argument("--trace-out", default=None, metavar="DIR",
                    help="enable the flight recorder and write the "
                         "rank-tagged trace sidecar into DIR "
                         "(--multichip only; read it with "
                         "tools/trace_report.py)")
    ap.add_argument("--ledger-out", nargs="?", metavar="PATH",
                    const=os.path.join(_REPO, "PERF_LEDGER.jsonl"),
                    default=_LEDGER_OUT,
                    help="append the normalized run record (with "
                         "provenance) to the perf ledger at PATH "
                         "(default PERF_LEDGER.jsonl; gate it with "
                         "tools/perf_ledger.py check)")
    cli = ap.parse_args()
    _LEDGER_OUT = cli.ledger_out
    if cli.multichip and cli.gang:
        sys.exit(run_multichip_gang(cli.gang, trace_out=cli.trace_out,
                                    steps=cli.gang_steps))
    sys.exit(run_multichip(cli.devices, trace_out=cli.trace_out)
             if cli.multichip else run())
