"""Benchmark: Llama train-step MFU on one chip (BASELINE.json north star:
Llama-2 pretrain >=40% MFU on v5p — here measured single-chip on a scaled
config with the identical compute path: bf16 matmuls on MXU, Pallas/XLA
fused attention, remat, fused adamw update inside one jit).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
from __future__ import annotations

import functools
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

# peak bf16 TFLOP/s by device generation
_PEAK_TFLOPS = {
    "v5 lite": 197.0, "v5litepod": 197.0, "v5e": 197.0,
    "v5p": 459.0, "v5": 459.0,
    "v4": 275.0, "v3": 123.0, "v2": 45.0,
    "v6 lite": 918.0, "v6e": 918.0,
    "cpu": 0.5,  # nominal, so the script still reports off-TPU
}


def _peak_flops(dev) -> float:
    kind = getattr(dev, "device_kind", "cpu").lower()
    for key, tf in _PEAK_TFLOPS.items():
        if key in kind:
            return tf * 1e12
    return _PEAK_TFLOPS["cpu"] * 1e12


def main():
    from paddle_tpu.models.llama import LlamaConfig, init_params, loss_fn
    import optax

    dev = jax.devices()[0]
    on_tpu = "tpu" in getattr(dev, "platform", "cpu").lower() or \
        "tpu" in getattr(dev, "device_kind", "").lower()

    if on_tpu:
        # ~0.95B params: fits one v5e chip (16G HBM) with Adam state.
        # remat-policy ladder: "dots" keeps matmul outputs (backward does
        # no matmul recompute — fastest) but costs the most HBM; fall back
        # to full remat, then a smaller batch, if it doesn't fit.
        variants = [("dots", 4), ("full", 4), ("full", 2)]
        base = dict(
            vocab_size=32000, hidden_size=2048, intermediate_size=5632,
            num_hidden_layers=16, num_attention_heads=16,
            num_key_value_heads=16, max_position_embeddings=2048,
            dtype=jnp.bfloat16, use_remat=True)
        S, iters = 2048, 10
    else:  # CPU smoke config
        variants = [("full", 2)]
        base = dict(
            vocab_size=1024, hidden_size=256, intermediate_size=512,
            num_hidden_layers=4, num_attention_heads=4,
            num_key_value_heads=4, max_position_embeddings=512,
            dtype=jnp.float32, use_remat=False)
        S, iters = 256, 3

    def run_variant(policy, B):
        cfg = LlamaConfig(remat_policy=policy, **base)
        params = init_params(cfg, jax.random.PRNGKey(0))
        opt = optax.adamw(3e-4, b1=0.9, b2=0.95, weight_decay=0.1)
        opt_state = opt.init(params)

        # donate params + opt_state: the update aliases into the same HBM
        # buffers instead of allocating a second copy of every tensor
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def step(params, opt_state, batch):
            (total, ce), grads = jax.value_and_grad(
                lambda p: loss_fn(cfg, p, batch), has_aux=True)(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, ce

        rng = np.random.default_rng(0)
        batch = {
            "input_ids": jnp.asarray(
                rng.integers(0, cfg.vocab_size, (B, S)), jnp.int32),
            "labels": jnp.asarray(
                rng.integers(0, cfg.vocab_size, (B, S)), jnp.int32),
        }

        # compile + warmup; scalar readback (not block_until_ready)
        # because the axon tunnel's block_until_ready does not reliably
        # fence execution
        params, opt_state, ce = step(params, opt_state, batch)
        float(ce)

        t0 = time.perf_counter()
        for _ in range(iters):
            params, opt_state, ce = step(params, opt_state, batch)
        float(ce)
        dt = (time.perf_counter() - t0) / iters
        return cfg, params, dt, B

    last_err = None
    for policy, B in variants:
        try:
            cfg, params, dt, B = run_variant(policy, B)
            break
        except Exception as e:  # OOM → next rung of the ladder
            if "RESOURCE_EXHAUSTED" not in str(e) and \
                    "Out of memory" not in str(e):
                raise
            # keep only the message: the traceback would pin the failed
            # variant's multi-GB locals in HBM while the next rung runs
            last_err = RuntimeError(str(e))
            del e
            import gc
            gc.collect()
    else:
        raise last_err

    n_params = sum(int(np.prod(a.shape))
                   for a in jax.tree_util.tree_leaves(params))
    tokens = B * S
    # 6ND model FLOPs + attention 12*B*S^2*H*L (fwd+bwd, causal halves it)
    attn_flops = 6 * B * S * S * cfg.hidden_size * cfg.num_hidden_layers
    flops = 6.0 * n_params * tokens + attn_flops
    mfu = 100.0 * flops / dt / _peak_flops(dev)
    tok_per_sec = tokens / dt

    result = {
        "metric": "llama_train_mfu_1chip",
        "value": round(mfu, 2),
        "unit": "percent_mfu",
        "vs_baseline": round(mfu / 40.0, 3),
        "detail": {
            "tokens_per_sec_per_chip": round(tok_per_sec, 1),
            "step_ms": round(dt * 1e3, 1),
            "n_params": n_params,
            "device": getattr(dev, "device_kind", str(dev)),
            "batch": B, "seq": S,
            "remat_policy": cfg.remat_policy if cfg.use_remat else "none",
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
