#!/usr/bin/env python
"""ckpt_inspect: checkpoint forensics CLI for paddle_tpu checkpoints.

Prints a committed checkpoint's manifest — step, framework version,
payload inventory, elastic-resume topology/sharding block, RNG streams,
data-pipeline cursor — and verifies the commit protocol's checksums,
all WITHOUT importing jax (or paddle_tpu at all: the commit manifest is
plain JSON + CRC32s, so this tool is stdlib-only and starts in
milliseconds, exactly what you want on a wedged pod host).

Usage:
    python tools/ckpt_inspect.py CKPT_DIR            # one step dir
    python tools/ckpt_inspect.py ROOT                # newest committed step
    python tools/ckpt_inspect.py ROOT --step 400
    python tools/ckpt_inspect.py ROOT --all          # every step, one line each
    python tools/ckpt_inspect.py CKPT_DIR --json     # machine-readable
    python tools/ckpt_inspect.py CKPT_DIR --no-checksums   # size-only (fast)

Exit codes (tpu_lint convention): 0 committed and verified, 1 verified
with warnings (no topology/RNG block, stale tmp/old siblings, version
unknown), 2 corrupt or uncommitted.

The on-disk format is the fault_tolerance commit protocol: a directory
is committed iff it carries a ``ptq_manifest.json`` listing every
payload file's size and CRC32; ``*.ptq-tmp`` siblings are in-flight
saves, ``*.ptq-old`` are displaced copies mid-swap.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
import zlib

# fault_tolerance protocol constants, duplicated so this CLI never
# imports the framework (asserted equal in tests/test_elastic_reshard.py)
MANIFEST_NAME = "ptq_manifest.json"
TMP_SUFFIX = ".ptq-tmp"
OLD_SUFFIX = ".ptq-old"
_STEP_RE = re.compile(r"^step_(\d+)$")


def _crc32(path: str, chunk: int = 1 << 20) -> int:
    c = 0
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(chunk), b""):
            c = zlib.crc32(block, c)
    return c & 0xFFFFFFFF


def read_manifest(dirpath: str):
    try:
        with open(os.path.join(dirpath, MANIFEST_NAME)) as f:
            man = json.load(f)
    except (OSError, ValueError):
        return None
    return man if isinstance(man, dict) and "files" in man else None


def committed_steps(root: str):
    steps = []
    try:
        names = os.listdir(root)
    except OSError:
        return steps
    for name in names:
        m = _STEP_RE.match(name)
        if m and read_manifest(os.path.join(root, name)) is not None:
            steps.append(int(m.group(1)))
    return sorted(steps)


def verify(dirpath: str, man: dict, checksums: bool = True):
    """[] when every manifest entry checks out, else problem strings."""
    problems = []
    for ent in man.get("files", []):
        p = os.path.join(dirpath, ent["path"])
        if not os.path.isfile(p):
            problems.append(f"missing payload file {ent['path']!r}")
            continue
        size = os.path.getsize(p)
        if size != ent["bytes"]:
            problems.append(
                f"{ent['path']!r}: {size} bytes on disk, manifest says "
                f"{ent['bytes']} (truncated write?)")
            continue
        if checksums and _crc32(p) != ent["crc32"]:
            problems.append(f"{ent['path']!r}: CRC32 mismatch (bit rot "
                            f"or torn write)")
    return problems


def inspect_dir(dirpath: str, checksums: bool = True) -> dict:
    """Everything about one checkpoint dir, as a JSON-able report."""
    dirpath = os.path.abspath(dirpath)
    report = {"path": dirpath, "verdict": None, "warnings": [],
              "problems": []}
    man = read_manifest(dirpath)
    if man is None:
        report["verdict"] = "uncommitted"
        report["problems"].append(
            f"no commit manifest ({MANIFEST_NAME}): the save never "
            f"committed" if os.path.isdir(dirpath)
            else "directory does not exist")
        return report
    report["step"] = man.get("step")
    report["framework_version"] = man.get("framework_version", "unknown")
    report["bytes_total"] = man.get("bytes_total")
    report["n_files"] = len(man.get("files", []))
    topo = man.get("topology")
    if isinstance(topo, dict):
        report["topology"] = topo
    else:
        report["warnings"].append(
            "no topology block (pre-elastic checkpoint: restores only "
            "onto an identical mesh without reshard.restore_resharded)")
    shardings = man.get("shardings")
    if isinstance(shardings, dict):
        report["n_sharded_params"] = len(shardings)
        report["shardings"] = {
            k: {"shape": v.get("shape"), "spec": v.get("spec")}
            for k, v in sorted(shardings.items())}
    rng = man.get("rng")
    if isinstance(rng, dict):
        report["rng"] = {
            "rank": rng.get("rank"),
            "framework": rng.get("framework"),
            "tracker_streams": sorted(rng.get("tracker") or {}),
        }
    else:
        report["warnings"].append(
            "no RNG block (dropout/data-aug streams reseed on resume)")
    data = man.get("data")
    if isinstance(data, dict):
        report["data"] = data
    if report["framework_version"] == "unknown":
        report["warnings"].append("framework version unknown (RNG "
                                  "version-skew check cannot run)")
    for sib in (dirpath + TMP_SUFFIX, dirpath + OLD_SUFFIX):
        if os.path.exists(sib):
            report["warnings"].append(
                f"stale sibling {os.path.basename(sib)!r} (crashed "
                f"save? recover_dir would clean it)")
    report["problems"] = verify(dirpath, man, checksums=checksums)
    report["verdict"] = "corrupt" if report["problems"] else "committed"
    return report


def _fmt_bytes(n) -> str:
    if not isinstance(n, (int, float)):
        return "?"
    size = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if size < 1024 or unit == "TiB":
            return f"{size:.1f}{unit}" if unit != "B" else f"{int(size)}B"
        size /= 1024
    return f"{n}B"


def _print_report(rep: dict):
    print(f"checkpoint: {rep['path']}")
    print(f"  verdict: {rep['verdict'].upper()}")
    for p in rep["problems"]:
        print(f"    problem: {p}")
    if rep["verdict"] == "uncommitted":
        return
    print(f"  step: {rep.get('step')}   framework: "
          f"{rep.get('framework_version')}   payload: "
          f"{rep.get('n_files')} files, "
          f"{_fmt_bytes(rep.get('bytes_total'))}")
    topo = rep.get("topology")
    if topo:
        mesh = topo.get("mesh")
        mesh_s = "x".join(f"{k}={v}" for k, v in mesh.items()) \
            if isinstance(mesh, dict) else "?"
        print(f"  topology: world_size={topo.get('world_size')} "
              f"rank={topo.get('rank')} mesh[{mesh_s}] "
              f"devices={topo.get('devices', '?')}")
    for key, ent in (rep.get("shardings") or {}).items():
        spec = ent.get("spec")
        spec_s = ", ".join("+".join(a) if a else "-" for a in (spec or []))
        print(f"    param {key}: shape={ent.get('shape')} "
              f"spec=({spec_s})")
    rng = rep.get("rng")
    if rng:
        streams = ",".join(rng.get("tracker_streams") or []) or "-"
        print(f"  rng: rank={rng.get('rank')} "
              f"framework={rng.get('framework')} tracker=[{streams}]")
    data = rep.get("data")
    if data:
        print(f"  data cursor: epoch={data.get('epoch')} "
              f"offset={data.get('offset')} "
              f"global_batch_size={data.get('global_batch_size')}")
    for w in rep["warnings"]:
        print(f"  warning: {w}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ckpt_inspect",
        description="Inspect and verify a paddle_tpu checkpoint "
                    "(commit manifest, topology, checksums) without "
                    "importing jax.")
    ap.add_argument("path", help="a step_N checkpoint dir, or a root "
                                 "containing step_* dirs")
    ap.add_argument("--step", type=int, default=None,
                    help="pick this step under a root (default: newest)")
    ap.add_argument("--all", action="store_true",
                    help="inspect every committed step under a root")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the report(s) as JSON on stdout")
    ap.add_argument("--no-checksums", action="store_true",
                    help="skip CRC32 verification (sizes only)")
    args = ap.parse_args(argv)

    path = os.path.abspath(args.path)
    checksums = not args.no_checksums
    targets = []
    if read_manifest(path) is not None or _STEP_RE.match(
            os.path.basename(path)):
        targets = [path]
    else:
        steps = committed_steps(path)
        if args.step is not None:
            targets = [os.path.join(path, f"step_{args.step:08d}")]
        elif args.all:
            targets = [os.path.join(path, f"step_{s:08d}") for s in steps]
        elif steps:
            targets = [os.path.join(path, f"step_{steps[-1]:08d}")]
        else:
            targets = [path]  # report it as uncommitted

    reports = [inspect_dir(t, checksums=checksums) for t in targets]
    if args.as_json:
        doc = reports[0] if len(reports) == 1 and not args.all else reports
        json.dump(doc, sys.stdout, indent=1, sort_keys=True)
        print()
    else:
        for rep in reports:
            _print_report(rep)
    if any(r["verdict"] != "committed" for r in reports):
        return 2
    if any(r["warnings"] for r in reports):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
