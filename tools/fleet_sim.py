#!/usr/bin/env python
"""fleet_sim: trace-driven discrete-event fleet simulator.

Replays a serving workload — recorded PR-14 flight-recorder sidecars
(``trace_rank<N>.jsonl``) or a synthesized arrival process from
``paddle_tpu/serving/workloads.py`` — through R simulated replicas
behind the REAL ``Router`` (placement, failover, drain, autoscaling
are the shipped code, not a model of it).  Each replica is the real
``Scheduler`` + ``PagedKVCache`` + ``AdmissionGate`` host state; the
only thing modelled is time: the two compiled step costs (Tc=1
decode, Tc=chunk prefill), calibrated from trace-measured
``serve/step`` spans when a trace is given, else the shared defaults
in ``serving/autoscale.py``.  Because admission, batching, paging and
preemption run the live code paths, admitted/shed counts match a
live run over the same workload *exactly*; latency is as good as the
calibration.

Sweeps (replicas x kv_dtype x page budget) and reports the
minimum-chip configuration meeting a TTFT/latency SLO, with
per-window SLO burn-rate timelines.  ``--autoscale`` closes the loop:
an ``AutoscalePolicy`` drives the router on virtual time, scale-ups
provision fresh simulated replicas, scale-downs drain real ones.

Stdlib-only and jax-free: the needed paddle_tpu modules are loaded
standalone (same trick as tools/tpu_lint.py), so this starts in
milliseconds on any machine.  Output is deterministic for a fixed
seed — no wall-clock anywhere.

Usage:
    python tools/fleet_sim.py --workload flash-crowd --requests 200 \
        --horizon-s 60 --replicas 1-4 --slo-ttft-s 0.5 --out FLEET.json
    python tools/fleet_sim.py --trace-dir /tmp/serve_run --replicas 2
    python tools/fleet_sim.py --workload diurnal \
        --capacity-json cap.json --replicas 1-8 --autoscale

Exit codes (tpu_lint convention): 0 = some swept configuration meets
the SLO, 1 = none does, 2 = bad input (unknown sidecar schema,
corrupt trace, bad arguments).
"""
from __future__ import annotations

import argparse
import importlib
import json
import os
import sys
import types
from typing import Dict, List, Optional, Sequence, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the jax-free slice of paddle_tpu the simulator runs on; loaded as a
# synthetic package so relative imports resolve without executing any
# __init__.py (those import jax)
_PKG = "_fleet_sim_pt"
_SUBPKGS = ("core", "profiler", "runtime", "testing", "serving")
_MODULES = ("core.flags", "profiler.metrics", "profiler.trace",
            "runtime.watchdog", "runtime.health", "testing.chaos",
            "serving.errors", "serving.stats", "serving.kv_cache",
            "serving.prefix_cache", "serving.scheduler",
            "serving.workloads", "serving.autoscale",
            "serving.router")


class _Paddle:
    """Namespace over the standalone-loaded paddle_tpu modules."""


def load_paddle(root: str = REPO_ROOT) -> _Paddle:
    """Load the stdlib-only paddle_tpu modules WITHOUT importing
    paddle_tpu (or jax): synthetic parent packages whose ``__path__``
    points at the real source tree let every relative import inside
    the modules resolve normally, while the real ``__init__.py``
    chain (which imports jax) never runs."""
    base = os.path.join(root, "paddle_tpu")
    if _PKG not in sys.modules:
        pkg = types.ModuleType(_PKG)
        pkg.__path__ = [base]
        sys.modules[_PKG] = pkg
        for sub in _SUBPKGS:
            m = types.ModuleType(f"{_PKG}.{sub}")
            m.__path__ = [os.path.join(base, sub)]
            sys.modules[f"{_PKG}.{sub}"] = m
    mods = {name: importlib.import_module(f"{_PKG}.{name}")
            for name in _MODULES}
    pt = _Paddle()
    pt.flags = mods["core.flags"]
    pt.metrics = mods["profiler.metrics"]
    pt.trace = mods["profiler.trace"]
    pt.errors = mods["serving.errors"]
    pt.kv_cache = mods["serving.kv_cache"]
    pt.scheduler = mods["serving.scheduler"]
    pt.stats = mods["serving.stats"]
    pt.workloads = mods["serving.workloads"]
    pt.autoscale = mods["serving.autoscale"]
    pt.router = mods["serving.router"]
    return pt


# -- virtual time ---------------------------------------------------------
class SimClock:
    """Virtual time for the fleet.  ``serial`` mode sums every
    replica's step cost (matches an in-process Router stepping its
    replicas one after another — the sim-vs-live cross-check);
    parallel mode (default) gives each replica its own lane within a
    router iteration and commits the max — real fleets step replicas
    concurrently."""

    def __init__(self, serial: bool = False):
        self.serial = serial
        self.t = 0.0
        self._base = 0.0
        self._lanes: Dict[str, float] = {}
        self._cur: Optional[str] = None

    def now(self) -> float:
        return self.t

    def jump_to(self, t: float) -> None:
        self.t = max(self.t, float(t))

    def begin_iteration(self) -> None:
        self._base = self.t
        self._lanes.clear()

    def enter(self, name: str) -> float:
        if not self.serial:
            self._cur = name
            self._lanes.setdefault(name, 0.0)
            self.t = self._base + self._lanes[name]
        return self.t

    def advance(self, dur: float) -> float:
        if self.serial:
            self.t += dur
        else:
            self._lanes[self._cur] += dur
            self.t = self._base + self._lanes[self._cur]
        return self.t

    def commit_iteration(self) -> None:
        if not self.serial:
            self.t = self._base + (max(self._lanes.values())
                                   if self._lanes else 0.0)


# -- the simulated replica -----------------------------------------------
class SimEngine:
    """Duck-types the LLMEngine surface the Router drives
    (``add_request/step/state_of/error_of/cancel/scheduler``) on the
    real host-side machinery — Scheduler, PagedKVCache,
    AdmissionGate — so admission, batching, paging and preemption
    behave exactly like a live engine.  The device forward is
    replaced by a clock advance: one ServiceModel step cost per
    scheduled step, bucket-dependent."""

    def __init__(self, pt: _Paddle, model, clock: SimClock,
                 name: str = "sim0"):
        self.pt = pt
        self.model = model
        self.clock = clock
        self.name = name
        blocks = model.blocks_per_request
        self.kv = pt.kv_cache.PagedKVCache(model.num_pages,
                                           model.page_size, blocks)
        self.scheduler = pt.scheduler.Scheduler(
            self.kv, max_running=model.max_running, chunk=model.chunk,
            max_model_len=model.max_model_len)
        self.max_queue = model.max_queue
        self._gate = pt.scheduler.AdmissionGate(self.max_queue)
        self._requests: Dict[int, object] = {}
        self.shed = 0
        self.steps = 0
        self.busy_s = 0.0

    # engine surface ------------------------------------------------------
    def add_request(self, prompt, max_new_tokens: int,
                    eos_token_id: Optional[int] = None,
                    on_token=None,
                    deadline_s: Optional[float] = None) -> int:
        depth = self.scheduler.num_waiting
        if self._gate.check(depth):
            self.shed += 1
            raise self.pt.errors.AdmissionRejected(
                f"admission queue at {depth}/{self.max_queue}; "
                f"shedding until it drains below "
                f"{self._gate.recover_below} — retry with backoff")
        now = self.clock.now()
        req = self.pt.scheduler.Request(
            prompt=[int(t) for t in prompt],
            max_new_tokens=int(max_new_tokens),
            eos_token_id=eos_token_id, on_token=on_token,
            arrival_s=now,
            deadline_s=(None if deadline_s is None
                        else now + float(deadline_s)))
        self.scheduler.add(req)
        self._requests[req.rid] = req
        return req.rid

    def state_of(self, rid: int):
        return self._requests[rid].state

    def error_of(self, rid: int):
        return self._requests[rid].error

    def has_work(self) -> bool:
        return self.scheduler.has_work()

    def cancel(self, rid: int) -> bool:
        RequestState = self.pt.scheduler.RequestState
        req = self._requests.get(rid)
        if req is None or req.state not in (RequestState.WAITING,
                                            RequestState.RUNNING):
            return False
        self.scheduler.remove(req, now_s=self.clock.now(),
                              state=RequestState.CANCELLED)
        return True

    def _expire_deadlines(self, now: float) -> None:
        RequestState = self.pt.scheduler.RequestState
        active = [r for r in self.scheduler.slots if r is not None]
        active.extend(self.scheduler.waiting)
        for req in active:
            if req.deadline_s is None or now <= req.deadline_s:
                continue
            self.scheduler.remove(
                req, now_s=now, state=RequestState.FAILED,
                error=self.pt.errors.DeadlineExceeded(
                    f"request {req.rid} missed its deadline by "
                    f"{now - req.deadline_s:.3f}s"))

    def step(self) -> List[int]:
        self.clock.enter(self.name)
        now = self.clock.now()
        self._expire_deadlines(now)
        plan = self.scheduler.schedule()
        self.kv.drain_copies()
        if not plan.seqs:
            return []
        dur = (self.model.prefill_chunk_s if plan.bucket > 1
               else self.model.decode_step_s)
        now = self.clock.advance(dur)
        self.steps += 1
        self.busy_s += dur
        out = {s.slot: 1 for s in plan.seqs if s.produces}
        finished = self.scheduler.apply(plan, out, now_s=now)
        return [r.rid for r in finished]


# -- trace ingestion ------------------------------------------------------
def die(code: int, msg: str) -> None:
    print(f"fleet_sim: error: {msg}", file=sys.stderr)
    raise SystemExit(code)


def find_sidecars(trace_dir: str) -> List[str]:
    paths = sorted(
        os.path.join(trace_dir, f) for f in os.listdir(trace_dir)
        if f.startswith("trace_rank") and f.endswith(".jsonl"))
    if not paths:
        die(2, f"no trace_rank<N>.jsonl sidecars in {trace_dir!r}")
    return paths


def check_sidecar_schema(pt: _Paddle, path: str) -> None:
    """Reject unknown/corrupt sidecars up front with a clear
    diagnostic (exit 2), instead of crashing mid-replay."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            first = f.readline()
    except OSError as exc:
        die(2, f"{path}: unreadable sidecar: {exc}")
    if not first.strip():
        die(2, f"{path}: empty file — not a trace sidecar")
    try:
        header = json.loads(first)
    except json.JSONDecodeError:
        die(2, f"{path}: first line is not JSON — not a trace "
               f"sidecar (expected a {pt.trace.SCHEMA} header)")
    schema = header.get("schema") if isinstance(header, dict) else None
    if schema != pt.trace.SCHEMA:
        die(2, f"{path}: unknown trace schema {schema!r} "
               f"(this build reads {pt.trace.SCHEMA!r}; re-record "
               f"the trace or use a matching fleet_sim)")


def load_trace(pt: _Paddle, trace_dir: str):
    """Workload + calibration samples from recorded sidecars:
    arrivals from ``serve/queued`` request events, per-bucket step
    costs from ``serve/step`` span durations."""
    paths = find_sidecars(trace_dir)
    for p in paths:
        check_sidecar_schema(pt, p)
    try:
        events = pt.trace.merge_sidecars(paths)
    except ValueError as exc:
        die(2, f"{trace_dir}: corrupt trace: {exc}")
    queued = [e for e in events
              if e.get("kind") == "request"
              and e.get("name") == "serve/queued"]
    steps: Dict[int, List[float]] = {}
    for e in events:
        if (e.get("kind") == "span" and e.get("name") == "serve/step"
                and "dur" in e and "bucket" in e):
            steps.setdefault(int(e["bucket"]), []).append(
                float(e["dur"]))
    if not queued:
        die(2, f"{trace_dir}: trace holds no serve/queued request "
               f"events — record with FLAGS_tpu_trace=1 while "
               f"serving (bench_serve --trace-out writes one)")
    t0 = min(float(e["t"]) for e in queued)
    arrivals = []
    for i, e in enumerate(sorted(queued, key=lambda e: float(e["t"]))):
        plen = int(e.get("prompt_len", 16) or 16)
        arrivals.append(pt.workloads.Arrival(
            t_s=float(e["t"]) - t0,
            prompt=tuple(1 + (i + j) % 97 for j in range(plen)),
            max_new_tokens=int(e.get("max_new_tokens", 8) or 8)))
    return arrivals, steps


# -- one simulation run ---------------------------------------------------
def simulate(pt: _Paddle, model, arrivals, n_replicas: int, *,
             slo_ttft_s: Optional[float] = None,
             slo_latency_s: Optional[float] = None,
             serial: bool = False, burn_window_s: float = 5.0,
             budget: float = 0.05, autoscale: bool = False,
             autoscale_apply: bool = False,
             max_wall_s: float = 3600.0) -> Dict[str, object]:
    """Drive the real Router over virtual time; returns the run
    report (counts, latency percentiles, burn timeline, scale
    events)."""
    clock = SimClock(serial=serial)
    engines = [SimEngine(pt, model, clock, name=f"sim{i}")
               for i in range(int(n_replicas))]
    policy = None
    if autoscale:
        p_nom = max((len(a.prompt) for a in arrivals), default=16)
        n_nom = max((a.max_new_tokens for a in arrivals), default=8)
        policy = pt.autoscale.AutoscalePolicy(
            model, slo_ttft_s=slo_ttft_s, prompt_len=p_nom,
            new_tokens=n_nom, budget=budget,
            windows_s=(burn_window_s, 4 * burn_window_s),
            horizon_s=2 * burn_window_s, cooldown_s=4 * burn_window_s,
            # simulated provisioning is instant, so a fast forecaster
            # can buy capacity within ~1s of a spike's onset — before
            # the queue turns into TTFT violations
            forecast_tau_s=max(burn_window_s / 5.0, 1.0),
            clock=clock.now)
    router = pt.router.Router(
        [(e.name, e) for e in engines], clock=clock.now,
        heartbeat_timeout=1e12, autoscaler=policy,
        autoscale_apply=autoscale_apply)

    pending = sorted(arrivals, key=lambda a: (a.t_s, a.prompt))
    recs: Dict[int, Dict[str, Optional[float]]] = {}
    scale_events: List[Dict[str, object]] = []
    shed = 0
    i = 0

    def cb(gid, token, finished):
        r = recs[gid]
        if r["first_token_s"] is None:
            r["first_token_s"] = clock.now()
        if finished:
            r["finish_s"] = clock.now()

    n_added = 0
    while True:
        now = clock.now()
        while i < len(pending) and pending[i].t_s <= now:
            a = pending[i]
            i += 1
            try:
                gid = router.submit(list(a.prompt), a.max_new_tokens,
                                    on_token=cb)
            except (pt.errors.AdmissionRejected,
                    pt.errors.ReplicaUnavailable):
                shed += 1
                continue
            recs[gid] = {"arrival_s": a.t_s, "first_token_s": None,
                         "finish_s": None}
        if not router.has_work():
            if i >= len(pending):
                break
            clock.jump_to(pending[i].t_s)
            continue
        before = clock.now()
        clock.begin_iteration()
        router.step()
        clock.commit_iteration()
        rec = router.last_recommendation
        if rec is not None and rec.action != "hold" and (
                not scale_events
                or scale_events[-1]["t_s"] != rec.at_s
                or scale_events[-1]["action"] != rec.action):
            scale_events.append({
                "t_s": round(rec.at_s, 6), "action": rec.action,
                "target": rec.target_replicas,
                "live": rec.live_replicas,
                "applied": rec.applied})
            if (autoscale and rec.action == "scale_up"
                    and autoscale_apply):
                # the simulator CAN provision hardware: attach fresh
                # replicas up to the recommended target (live apply
                # only drains — scale-up stays a recommendation
                # there)
                live = len(router.live_replicas())
                while live < rec.target_replicas:
                    n_added += 1
                    eng = SimEngine(pt, model, clock,
                                    name=f"sim-up{n_added}")
                    engines.append(eng)
                    router.add_replica(eng.name, eng)
                    live += 1
                if policy is not None:
                    policy.mark_applied(rec)
                scale_events[-1]["applied"] = True
        if clock.now() <= before:
            # no replica made progress (e.g. orphans waiting): let
            # virtual time flow to the next arrival or one decode
            if i < len(pending):
                clock.jump_to(pending[i].t_s)
            else:
                clock.jump_to(before + model.decode_step_s)
        if clock.now() > max_wall_s:
            break

    ttft = sorted(r["first_token_s"] - r["arrival_s"] for r in
                  recs.values() if r["first_token_s"] is not None)
    latency = sorted(r["finish_s"] - r["arrival_s"] for r in
                     recs.values() if r["finish_s"] is not None)
    end_s = clock.now()

    first_violation_s = None
    n_violations = 0
    if slo_ttft_s is not None:
        viol_at = [r["first_token_s"] for r in recs.values()
                   if r["first_token_s"] is not None
                   and r["first_token_s"] - r["arrival_s"] > slo_ttft_s]
        n_violations = len(viol_at)
        if viol_at:
            first_violation_s = round(min(viol_at), 6)
    first_scale_up_s = next(
        (e["t_s"] for e in scale_events if e["action"] == "scale_up"),
        None)

    # per-window burn timeline over the TTFT SLO
    timeline: List[Dict[str, object]] = []
    if slo_ttft_s is not None and burn_window_s > 0:
        n_win = int(end_s / burn_window_s) + 1
        for w in range(n_win):
            lo, hi = w * burn_window_s, (w + 1) * burn_window_s
            xs = [r for r in recs.values()
                  if r["first_token_s"] is not None
                  and lo <= r["first_token_s"] < hi]
            if not xs:
                continue
            viol = sum(1 for r in xs
                       if r["first_token_s"] - r["arrival_s"]
                       > slo_ttft_s)
            frac = viol / len(xs)
            timeline.append({
                "window_s": [round(lo, 6), round(hi, 6)],
                "requests": len(xs), "violations": viol,
                "burn_rate": round(frac / budget, 4) if budget
                else None})

    report: Dict[str, object] = {
        "replicas": int(n_replicas),
        "replicas_final": len(router.live_replicas()),
        "offered": len(pending),
        "admitted": len(recs),
        "shed": shed,
        "finished": len(latency),
        "sim_end_s": round(end_s, 6),
        "engine_steps": sum(e.steps for e in engines),
        "ttft_p50_s": _pct(ttft, 50), "ttft_p95_s": _pct(ttft, 95),
        "latency_p50_s": _pct(latency, 50),
        "latency_p95_s": _pct(latency, 95),
        "burn_timeline": timeline,
        "scale_events": scale_events,
        "ttft_violations": n_violations,
        "first_violation_s": first_violation_s,
        "first_scale_up_s": first_scale_up_s,
    }
    slo_ok = True
    if slo_ttft_s is not None:
        ok = (report["ttft_p95_s"] is not None
              and report["ttft_p95_s"] <= slo_ttft_s)
        report["ttft_ok"] = ok
        slo_ok = slo_ok and ok
    if slo_latency_s is not None:
        ok = (report["latency_p95_s"] is not None
              and report["latency_p95_s"] <= slo_latency_s)
        report["latency_ok"] = ok
        slo_ok = slo_ok and ok
    report["slo_ok"] = slo_ok if (slo_ttft_s is not None or
                                  slo_latency_s is not None) else None
    return report


def _pct(sorted_xs: Sequence[float], q: float) -> Optional[float]:
    """numpy.percentile(interpolation='linear') on a pre-sorted list
    — keeps the report numerically comparable with slo_report()."""
    if not sorted_xs:
        return None
    if len(sorted_xs) == 1:
        return round(float(sorted_xs[0]), 6)
    pos = (len(sorted_xs) - 1) * q / 100.0
    lo = int(pos)
    hi = min(lo + 1, len(sorted_xs) - 1)
    frac = pos - lo
    return round(sorted_xs[lo] * (1 - frac) + sorted_xs[hi] * frac, 6)


# -- configuration sweep --------------------------------------------------
def parse_int_list(spec: str) -> List[int]:
    """``"1-4"`` -> [1,2,3,4]; ``"1,2,8"`` -> [1,2,8]; ``"2"`` -> [2]."""
    out: List[int] = []
    for part in spec.split(","):
        part = part.strip()
        if "-" in part[1:]:
            lo, hi = part.split("-", 1)
            out.extend(range(int(lo), int(hi) + 1))
        else:
            out.append(int(part))
    if not out or any(v <= 0 for v in out):
        raise ValueError(f"bad int list {spec!r}")
    return sorted(set(out))


def capacity_variants(pt: _Paddle, args,
                      base_model) -> List[Tuple[str, int, object]]:
    """(kv_dtype label, num_pages, ServiceModel) variants to sweep.
    ``--capacity-json`` takes them from a ``pod_report serving``
    report (which owns the HBM arithmetic, int8 page scales
    included); ``--pages`` sweeps explicit page budgets; default is
    the base model alone."""
    variants: List[Tuple[str, int, object]] = []
    if args.capacity_json:
        try:
            with open(args.capacity_json, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as exc:
            die(2, f"--capacity-json {args.capacity_json}: {exc}")
        serving = doc.get("serving", doc)
        blocks = []
        if isinstance(serving.get("baseline_bf16"), dict):
            blocks.append(("bf16", serving["baseline_bf16"]))
            blocks.append((serving.get("kv_dtype", "int8"), serving))
        else:
            blocks.append((serving.get("kv_dtype", "bf16"), serving))
        for label, blk in blocks:
            pages = blk.get("num_pages")
            if pages is None:
                die(2, f"--capacity-json {args.capacity_json}: no "
                       f"num_pages in serving block — generate with "
                       f"tools/pod_report.py serving")
            m = _with_pages(base_model, int(pages),
                            page_size=int(blk.get("page_size",
                                          base_model.page_size)))
            variants.append((label, int(pages), m))
    elif args.pages:
        for pages in parse_int_list(args.pages):
            variants.append(
                (args.kv_dtype, pages,
                 _with_pages(base_model, pages)))
    else:
        variants.append((args.kv_dtype, base_model.num_pages,
                         base_model))
    return variants


def _with_pages(model, num_pages: int, page_size: Optional[int] = None):
    import dataclasses as _dc
    changes = {"num_pages": int(num_pages)}
    if page_size is not None:
        changes["page_size"] = int(page_size)
    return _dc.replace(model, **changes)


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="fleet_sim", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    src = ap.add_argument_group("workload")
    src.add_argument("--workload", default=None,
                     help="synthesized arrival preset "
                          "(see serving/workloads.py)")
    src.add_argument("--trace-dir", default=None,
                     help="replay trace_rank<N>.jsonl sidecars from "
                          "this directory (also calibrates step "
                          "costs from serve/step spans)")
    src.add_argument("--requests", type=int, default=200)
    src.add_argument("--horizon-s", type=float, default=60.0)
    src.add_argument("--seed", type=int, default=0)
    src.add_argument("--prompt-len", type=int, default=12)
    src.add_argument("--max-new-tokens", type=int, default=8)
    eng = ap.add_argument_group("service model (per replica)")
    eng.add_argument("--max-running", type=int, default=8)
    eng.add_argument("--chunk", type=int, default=16)
    eng.add_argument("--page-size", type=int, default=16)
    eng.add_argument("--max-model-len", type=int, default=64)
    eng.add_argument("--max-queue", type=int, default=None,
                     help="admission queue bound "
                          "(default 8*max_running, like the engine)")
    eng.add_argument("--prefill-chunk-s", type=float, default=None,
                     help="override the prefill-bucket step cost")
    eng.add_argument("--decode-step-s", type=float, default=None,
                     help="override the decode-bucket step cost")
    eng.add_argument("--capacity-json", default=None,
                     help="pod_report serving JSON: sweep its "
                          "num_pages/kv_dtype variants")
    eng.add_argument("--pages", default=None,
                     help="page budgets to sweep, e.g. 33,65,129")
    eng.add_argument("--kv-dtype", default="bf16",
                     help="label for --pages variants (capacity "
                          "arithmetic comes from pod_report)")
    sweep = ap.add_argument_group("sweep / SLO")
    sweep.add_argument("--replicas", default="1-4",
                       help="replica counts to sweep: N, lo-hi or "
                            "comma list")
    sweep.add_argument("--slo-ttft-s", type=float, default=None)
    sweep.add_argument("--slo-latency-s", type=float, default=None)
    sweep.add_argument("--budget", type=float, default=0.05,
                       help="SLO error budget (violation fraction)")
    sweep.add_argument("--burn-window-s", type=float, default=5.0)
    sweep.add_argument("--serial", action="store_true",
                       help="sum replica step costs per iteration "
                            "(matches an in-process router stepping "
                            "replicas serially) instead of max "
                            "(a real parallel fleet)")
    auto = ap.add_argument_group("autoscaling")
    auto.add_argument("--autoscale", action="store_true",
                      help="attach an AutoscalePolicy to the router")
    auto.add_argument("--autoscale-apply", action="store_true",
                      help="apply recommendations in the sim: "
                           "scale-ups provision replicas, "
                           "scale-downs drain")
    ap.add_argument("--out", default=None,
                    help="also write the JSON report to this path")
    ap.add_argument("--repo-root", default=REPO_ROOT,
                    help=argparse.SUPPRESS)
    return ap


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    pt = load_paddle(args.repo_root)

    calib_steps: Dict[int, List[float]] = {}
    if args.trace_dir:
        arrivals, calib_steps = load_trace(pt, args.trace_dir)
        workload_label = f"trace:{os.path.basename(args.trace_dir)}"
    else:
        preset = args.workload or "uniform"
        try:
            pt.workloads.validate(preset)
        except ValueError as exc:
            die(2, str(exc))
        arrivals = pt.workloads.generate(
            preset, args.requests, seed=args.seed,
            horizon_s=args.horizon_s, prompt_len=args.prompt_len,
            max_new_tokens=args.max_new_tokens)
        workload_label = preset

    max_queue = (args.max_queue if args.max_queue is not None
                 else 8 * args.max_running)
    model = pt.autoscale.ServiceModel.from_step_samples(
        calib_steps, max_running=args.max_running, chunk=args.chunk,
        page_size=args.page_size,
        num_pages=args.max_running * (
            -(-args.max_model_len // args.page_size)) + 1,
        max_model_len=args.max_model_len, max_queue=max_queue)
    overrides = {}
    if args.prefill_chunk_s is not None:
        overrides["prefill_chunk_s"] = args.prefill_chunk_s
    if args.decode_step_s is not None:
        overrides["decode_step_s"] = args.decode_step_s
    if overrides:
        import dataclasses as _dc
        model = _dc.replace(model, **overrides)

    try:
        replica_counts = parse_int_list(args.replicas)
        variants = capacity_variants(pt, args, model)
    except ValueError as exc:
        die(2, str(exc))

    runs: List[Dict[str, object]] = []
    for kv_label, pages, m in variants:
        analytic = pt.autoscale.recommend_fleet(
            m, arrivals, peak_window_s=args.burn_window_s)
        for n in replica_counts:
            rep = simulate(
                pt, m, arrivals, n, slo_ttft_s=args.slo_ttft_s,
                slo_latency_s=args.slo_latency_s, serial=args.serial,
                burn_window_s=args.burn_window_s, budget=args.budget,
                autoscale=args.autoscale,
                autoscale_apply=args.autoscale_apply)
            rep["kv_dtype"] = kv_label
            rep["num_pages"] = pages
            rep["analytic_min_replicas"] = analytic["min_replicas"]
            rep["offered_rps_peak"] = analytic["offered_rps_peak"]
            rep["capacity_rps_per_replica"] = (
                analytic["capacity_rps_per_replica"])
            runs.append(rep)

    meeting = [r for r in runs if r["slo_ok"]]
    recommended = None
    if meeting:
        # minimum chips first (1 chip per replica), then the leaner
        # page budget
        best = min(meeting, key=lambda r: (r["replicas"],
                                           r["num_pages"]))
        recommended = {k: best[k] for k in
                       ("replicas", "kv_dtype", "num_pages",
                        "ttft_p95_s", "latency_p95_s", "admitted",
                        "shed")}
    doc = {
        "tool": "fleet_sim",
        "workload": workload_label,
        "requests": len(arrivals),
        "seed": args.seed,
        "serial_clock": bool(args.serial),
        "calibrated": model.calibrated,
        "service_model": model.to_dict(),
        "slo": {"ttft_p95_s": args.slo_ttft_s,
                "latency_p95_s": args.slo_latency_s,
                "budget": args.budget,
                "burn_window_s": args.burn_window_s},
        "sweep": runs,
        "recommended": recommended,
    }
    text = json.dumps(doc, indent=2, sort_keys=True)
    print(text)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(text + "\n")
    if (args.slo_ttft_s is None and args.slo_latency_s is None):
        return 0
    return 0 if recommended is not None else 1


if __name__ == "__main__":
    sys.exit(main())
