#!/usr/bin/env python
"""trace_report: offline reader for paddle_tpu flight-recorder sidecars.

Loads the rank-tagged JSONL sidecars the ``profiler.trace`` flight
recorder writes (``trace_rank<N>.jsonl``, schema
``paddle_tpu.trace.v1``), aligns ranks on shared barrier events, and
prints one JSON report on stdout:

* **requests** — per-request serving lifecycle (queued -> admitted ->
  prefill chunks -> first token -> decode -> terminal) with a TTFT
  breakdown whose p95 components are taken from the *same* interpolated
  sample, so ``queue_p95_s + prefill_p95_s == ttft_p95_s`` exactly.
* **steps** — train/serve step-span stats per rank (count, mean, p95).
* **pipeline** — measured overlap from the recorded 1F1B schedule:
  the serialized-transfer rule is re-implemented here verbatim
  (``consumed_tick - produced_tick < 2``) so the report needs no
  paddle_tpu import, and the numbers match
  ``distributed.overlap.transfer_stats`` bit-for-bit.
* **incidents** — ``--incidents`` folds watchdog/health incident
  sidecars (schema ``paddle_tpu.incidents.v1``) into the report.

Usage:
    python tools/trace_report.py out_dir/                 # all sidecars
    python tools/trace_report.py trace_rank0.jsonl --chrome trace.json
    python tools/trace_report.py out/ --incidents out/ --black-box bb.zip
    python tools/trace_report.py out/ --request 17        # one timeline

``--chrome`` writes a Chrome/Perfetto-loadable trace (spans as "X"
slices, instants as "i", plus process/thread metadata); ``--black-box``
bundles every input sidecar, incident file, and the report itself into
one zip archive for post-mortem handoff.

Exit codes (tpu_lint convention): 0 clean, 1 warnings (e.g. an admitted
request without exactly one terminal event), 2 errors (missing,
corrupt, or wrong-schema input). Stdlib-only — starts in milliseconds.
"""
from __future__ import annotations

import argparse
import glob
import json
import math
import os
import sys
import zipfile
from typing import Any, Dict, List, Optional, Tuple

TRACE_SCHEMA = "paddle_tpu.trace.v1"
INCIDENT_SCHEMA = "paddle_tpu.incidents.v1"
TERMINAL_PHASES = ("finish", "cancelled", "failed")


# ---------------------------------------------------------------------------
# sidecar loading + rank merge
# ---------------------------------------------------------------------------

def discover_sidecars(paths: List[str], pattern: str) -> List[str]:
    """Expand files/directories into a sorted sidecar file list."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, pattern))))
        else:
            out.append(p)
    # de-dup, keep order
    seen = set()
    uniq = []
    for p in out:
        if p not in seen:
            seen.add(p)
            uniq.append(p)
    return uniq


def read_sidecar(path: str, schema: str) -> Tuple[dict, List[dict]]:
    """(header, records) from one JSONL sidecar; raises ValueError on
    empty/corrupt/wrong-schema input."""
    with open(path) as f:
        lines = [ln for ln in f.read().splitlines() if ln.strip()]
    if not lines:
        raise ValueError(f"{path}: empty sidecar")
    try:
        header = json.loads(lines[0])
        records = [json.loads(ln) for ln in lines[1:]]
    except json.JSONDecodeError as exc:
        raise ValueError(f"{path}: corrupt JSONL ({exc})") from exc
    got = header.get("schema")
    if got != schema:
        raise ValueError(f"{path}: schema {got!r}, expected {schema!r}")
    return header, records


def merge_ranks(per_rank: Dict[int, List[dict]]) -> List[dict]:
    """Align per-rank event streams on the first barrier event name all
    ranks share (clocks are per-process monotonic — only barrier-relative
    time is comparable) and interleave. Mirrors
    ``profiler.trace.merge_ranks``."""
    if not per_rank:
        return []
    ref = min(per_rank)
    barriers: Dict[int, Dict[str, float]] = {}
    for r, evs in per_rank.items():
        b: Dict[str, float] = {}
        for e in evs:
            if e.get("kind") == "barrier" and e["name"] not in b:
                b[e["name"]] = e["t"]
        barriers[r] = b
    shared = None
    for e in per_rank[ref]:
        if e.get("kind") == "barrier" and all(
                e["name"] in barriers[r] for r in per_rank):
            shared = e["name"]
            break
    merged: List[dict] = []
    for r, evs in per_rank.items():
        off = 0.0
        if shared is not None:
            off = barriers[ref][shared] - barriers[r][shared]
        for e in evs:
            e2 = dict(e)
            e2["t"] = e["t"] + off
            e2["rank"] = r
            merged.append(e2)
    merged.sort(key=lambda e: (e["t"], e["rank"], e.get("seq", 0)))
    return merged


# ---------------------------------------------------------------------------
# per-request lifecycle
# ---------------------------------------------------------------------------

def request_rows(events: List[dict]) -> Tuple[List[dict], List[str]]:
    """One row per request id seen in kind=="request" events, plus
    lifecycle warnings (the invariant: every admitted request ends in
    exactly one terminal event)."""
    by_rid: Dict[int, List[dict]] = {}
    for e in events:
        if e.get("kind") != "request":
            continue
        rid = e.get("rid")
        if rid is None or rid < 0:  # rid -1: pre-admission shed
            continue
        by_rid.setdefault(rid, []).append(e)
    rows: List[dict] = []
    warnings: List[str] = []
    for rid in sorted(by_rid):
        evs = by_rid[rid]
        first_t: Dict[str, float] = {}
        counts: Dict[str, int] = {}
        terminal = []
        for e in evs:
            ph = e.get("phase", "")
            counts[ph] = counts.get(ph, 0) + 1
            if ph not in first_t:
                first_t[ph] = e["t"]
            if ph in TERMINAL_PHASES:
                terminal.append(ph)
        row: Dict[str, Any] = {
            "rid": rid,
            "events": len(evs),
            "terminal": terminal[0] if terminal else None,
            "prefill_chunks": counts.get("prefill", 0),
            "decode_steps": counts.get("decode", 0),
            "preemptions": counts.get("preempted", 0),
            "replays": counts.get("replay", 0),
        }
        q, a = first_t.get("queued"), first_t.get("admitted")
        ft = first_t.get("first_token")
        term_t = first_t.get(terminal[0]) if terminal else None
        if q is not None and a is not None:
            row["queue_s"] = a - q
        if a is not None and ft is not None:
            row["prefill_s"] = ft - a
            row["ttft_s"] = row.get("queue_s", 0.0) + (ft - a)
        if ft is not None and term_t is not None:
            row["decode_s"] = term_t - ft
        if q is not None and term_t is not None:
            row["total_s"] = term_t - q
        rows.append(row)
        admitted = "admitted" in first_t
        if admitted and len(terminal) != 1:
            warnings.append(
                f"request {rid}: admitted but {len(terminal)} terminal "
                f"event(s) {terminal} (want exactly 1)")
        if len(terminal) > 1:
            warnings.append(
                f"request {rid}: multiple terminal events {terminal}")
    return rows, warnings


def _p95_blend(rows: List[dict]) -> Optional[dict]:
    """TTFT p95 with a component breakdown that sums exactly.

    Uses numpy.percentile's linear interpolation (idx = (n-1)*q) on the
    rows sorted by ttft, then blends each row's queue/prefill components
    with the *same* two bracketing samples and weight — per-row
    queue_s + prefill_s == ttft_s, so the blended components sum to the
    blended ttft bit-for-bit."""
    rows = [r for r in rows if "ttft_s" in r and "queue_s" in r
            and "prefill_s" in r]
    if not rows:
        return None
    rows.sort(key=lambda r: r["ttft_s"])
    n = len(rows)
    idx = (n - 1) * 0.95
    lo, hi = math.floor(idx), math.ceil(idx)
    w = idx - lo

    def blend(key):
        return rows[lo][key] * (1.0 - w) + rows[hi][key] * w

    dec = [r["decode_s"] for r in rows if "decode_s" in r]
    out = {
        "queue_p95_s": blend("queue_s"),
        "prefill_p95_s": blend("prefill_s"),
        "queue_mean_s": sum(r["queue_s"] for r in rows) / n,
        "prefill_mean_s": sum(r["prefill_s"] for r in rows) / n,
        "samples": n,
    }
    # the headline p95 is defined as the sum of its blended components
    # (mathematically identical to blend("ttft_s") — per-row
    # ttft == queue + prefill — but summing AFTER the blend keeps the
    # invariant bit-exact instead of reassociating the float ops)
    out["ttft_p95_s"] = out["queue_p95_s"] + out["prefill_p95_s"]
    if dec:
        out["decode_p95_s"] = _p95(dec)
        out["decode_mean_s"] = sum(dec) / len(dec)
    return out


def _p95(vals: List[float]) -> float:
    vals = sorted(vals)
    idx = (len(vals) - 1) * 0.95
    lo, hi = math.floor(idx), math.ceil(idx)
    return vals[lo] * (1.0 - (idx - lo)) + vals[hi] * (idx - lo)


# ---------------------------------------------------------------------------
# step spans + measured pipeline overlap
# ---------------------------------------------------------------------------

def step_stats(events: List[dict]) -> Dict[str, Any]:
    """Duration stats for train/serve step spans, per rank."""
    out: Dict[str, Any] = {}
    for name in ("train/step", "serve/step"):
        spans = [e for e in events
                 if e.get("kind") == "span" and e.get("name") == name]
        if not spans:
            continue
        per_rank: Dict[int, List[float]] = {}
        for e in spans:
            per_rank.setdefault(e.get("rank", 0), []).append(e["dur"])
        durs = [d for ds in per_rank.values() for d in ds]
        out[name] = {
            "count": len(durs),
            "mean_s": sum(durs) / len(durs),
            "p95_s": _p95(durs),
            "ranks": {str(r): {"count": len(ds),
                               "mean_s": sum(ds) / len(ds)}
                      for r, ds in sorted(per_rank.items())},
        }
    return out


def _score_schedule(sched: List[dict]) -> Dict[str, Any]:
    """transfer/serialization stats for one recorded schedule, with the
    simulator's exact sort key and serialization rule re-implemented
    (``distributed.overlap.transfer_stats``): a stage-boundary transfer
    is *serialized* when its consumer runs on the tick right after its
    producer (< 2 ticks of slack)."""
    sched = sorted(sched, key=lambda e: (
        e["tick"], e["stage"] if "stage" in e else e["src"]))
    total = serialized = 0
    for e in sched:
        if e.get("kind") not in ("send_fwd", "send_bwd"):
            continue
        total += 1
        if e["consumed_tick"] - e["produced_tick"] < 2:
            serialized += 1
    return {
        "n_events": len(sched),
        "total_transfers": total,
        "serialized_transfers": serialized,
        "overlap_fraction": (1.0 if total == 0
                             else 1.0 - serialized / total),
        "schedule_events": sched,
    }


def pipeline_overlap(events: List[dict]) -> Optional[dict]:
    """Measured overlap from the recorded pipeline schedule(s).

    Each ``pipeline/schedule`` meta event opens a new recording; the
    following kind=="pipeline" events carry the scheduled units verbatim
    under their ``ev`` key. Reports one entry per recording plus the
    aggregate over all of them."""
    recordings: List[dict] = []
    current: Optional[dict] = None
    all_sched: List[dict] = []
    for e in events:
        if e.get("kind") == "pipeline_meta" and "pp" in e:
            current = {k: e[k] for k in ("pp", "n_micro", "overlap")
                       if k in e}
            current["sched"] = []
            recordings.append(current)
        elif e.get("kind") == "pipeline" and "ev" in e:
            ev = dict(e["ev"])
            all_sched.append(ev)
            if current is not None:
                current["sched"].append(ev)
    if not all_sched:
        return None
    out = _score_schedule(all_sched)
    if len(recordings) > 1:
        out["recordings"] = []
        for r in recordings:
            sc = _score_schedule(r.pop("sched"))
            sc.pop("schedule_events")
            r.update(sc)
            out["recordings"].append(r)
    elif recordings:
        out.update({k: v for k, v in recordings[0].items()
                    if k != "sched"})
    return out


# ---------------------------------------------------------------------------
# Chrome export
# ---------------------------------------------------------------------------

_META = ("name", "kind", "t", "rank", "seq", "dur", "depth", "parent")


def chrome_events(events: List[dict]) -> List[dict]:
    """trace_event JSON: spans -> "X" complete slices, everything else
    -> "i" instants; pid = rank, tid = nesting depth. Mirrors
    ``profiler.trace.chrome_events`` (kept stdlib-side so the report
    never imports paddle_tpu)."""
    out: List[dict] = []
    pids = []
    tids = []
    for e in events:
        pid = e.get("rank", 0)
        tid = e.get("depth", 0)
        if pid not in pids:
            pids.append(pid)
        if (pid, tid) not in tids:
            tids.append((pid, tid))
        args = {k: v for k, v in e.items() if k not in _META}
        base = {"name": e["name"], "pid": pid, "tid": tid,
                "ts": e["t"] * 1e6, "cat": e.get("kind", "event"),
                "args": args}
        if e.get("kind") == "span":
            base.update(ph="X", dur=e.get("dur", 0.0) * 1e6)
        else:
            base.update(ph="i", s="t")
        out.append(base)
    meta = [{"name": "process_name", "ph": "M", "pid": pid,
             "args": {"name": f"rank {pid}"}} for pid in sorted(pids)]
    meta += [{"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
              "args": {"name": f"depth {tid}"}}
             for pid, tid in sorted(tids)]
    return meta + out


# ---------------------------------------------------------------------------
# incidents + black box
# ---------------------------------------------------------------------------

def load_incidents(paths: List[str]) -> Tuple[List[str], List[dict],
                                              List[str]]:
    """(files, records, errors) for incident sidecars."""
    files = discover_sidecars(paths, "incidents_rank*.jsonl")
    records: List[dict] = []
    errors: List[str] = []
    for p in files:
        try:
            _, recs = read_sidecar(p, INCIDENT_SCHEMA)
        except (OSError, ValueError) as exc:
            errors.append(str(exc))
            continue
        records.extend(recs)
    return files, records, errors


def write_black_box(out_path: str, trace_files: List[str],
                    incident_files: List[str], report: dict) -> None:
    """One zip: every input sidecar + the report + a manifest."""
    manifest = {
        "schema": "paddle_tpu.blackbox.v1",
        "trace_files": [os.path.basename(p) for p in trace_files],
        "incident_files": [os.path.basename(p) for p in incident_files],
        "n_events": report.get("n_events", 0),
        "n_incidents": report.get("incidents", {}).get("count", 0),
    }
    with zipfile.ZipFile(out_path, "w",
                         compression=zipfile.ZIP_DEFLATED) as z:
        for p in trace_files + incident_files:
            z.write(p, arcname=os.path.basename(p))
        z.writestr("report.json",
                   json.dumps(report, indent=2, sort_keys=True,
                              default=str))
        z.writestr("manifest.json",
                   json.dumps(manifest, indent=2, sort_keys=True))


# ---------------------------------------------------------------------------
# gang verdict (--gang)
# ---------------------------------------------------------------------------

GANG_TERMINAL_BARRIER = "gang/exit"


def _f_tick(s: int, m: int, overlap: bool) -> int:
    return (2 * s if overlap else s) + m


def _b_tick(s: int, m: int, pp: int, overlap: bool) -> int:
    if overlap:
        return 4 * (pp - 1) + 1 - 2 * s + m
    return 2 * pp - 1 - s + m


def static_schedule(pp: int, n_micro: int, overlap: bool) -> List[dict]:
    """The 1F1B static schedule model, re-implemented verbatim from
    ``distributed.overlap.schedule_events`` (F/B tick arithmetic, edge
    ticks, and the simulator's sort key) so the gang verdict needs no
    paddle_tpu import and the comparison is bit-equal dict-for-dict."""
    events: List[dict] = []
    for m in range(n_micro):
        for s in range(pp):
            tf = _f_tick(s, m, overlap)
            tb = _b_tick(s, m, pp, overlap)
            events.append({"kind": "fwd", "tick": tf, "stage": s,
                           "micro": m})
            events.append({"kind": "bwd", "tick": tb, "stage": s,
                           "micro": m})
            if s < pp - 1:
                events.append({
                    "kind": "send_fwd", "micro": m, "src": s, "dst": s + 1,
                    "tick": tf + 1 if overlap else tf,
                    "produced_tick": tf,
                    "consumed_tick": _f_tick(s + 1, m, overlap)})
            if s > 0:
                events.append({
                    "kind": "send_bwd", "micro": m, "src": s, "dst": s - 1,
                    "tick": tb + 1 if overlap else tb,
                    "produced_tick": tb,
                    "consumed_tick": _b_tick(s - 1, m, pp, overlap)})
    events.sort(key=lambda e: (e["tick"], e["stage"] if "stage" in e
                               else e["src"]))
    return events


def _rank_schedule_verdict(events: List[dict]) -> Optional[dict]:
    """Compare every pipeline-schedule recording in one rank's event
    stream against the static model. None when the rank recorded no
    schedule (pp == 1 runs legitimately record none)."""
    recordings: List[dict] = []
    current: Optional[dict] = None
    for e in events:
        if e.get("kind") == "pipeline_meta" and "pp" in e:
            current = {"pp": int(e["pp"]), "n_micro": int(e["n_micro"]),
                       "overlap": bool(e["overlap"]), "sched": []}
            recordings.append(current)
        elif e.get("kind") == "pipeline" and "ev" in e:
            if current is not None:
                current["sched"].append(dict(e["ev"]))
    if not recordings:
        return None
    out = {"recordings": len(recordings), "matches_static": True}
    for i, rec in enumerate(recordings):
        recorded = sorted(rec["sched"],
                          key=lambda e: (e["tick"],
                                         e["stage"] if "stage" in e
                                         else e["src"]))
        static = static_schedule(rec["pp"], rec["n_micro"],
                                 rec["overlap"])
        out.setdefault("pp", rec["pp"])
        out.setdefault("n_micro", rec["n_micro"])
        out.setdefault("overlap", rec["overlap"])
        if recorded == static:
            continue
        out["matches_static"] = False
        div = {"recording": i, "recorded_events": len(recorded),
               "static_events": len(static)}
        for j, (a, b) in enumerate(zip(recorded, static)):
            if a != b:
                div.update(index=j, recorded=a, static=b)
                break
        else:
            # same prefix, different length: point at the first extra
            j = min(len(recorded), len(static))
            div.update(index=j,
                       recorded=recorded[j] if j < len(recorded) else None,
                       static=static[j] if j < len(static) else None)
        out.setdefault("divergence", div)
    return out


def gang_report(gang_dir: str) -> Tuple[dict, List[str], List[str]]:
    """Merged multi-rank verdict for one gang run's trace sidecar dir.

    Checks, per the flight-recorder contract ``distributed.gang``
    guarantees on every exit path:

    * every rank ``0..world_size-1`` (world size from the sidecar
      headers) wrote a sidecar — a missing file means that rank died
      without flushing, i.e. outside every guaranteed path;
    * each sidecar's event stream contains the ``gang/exit`` terminal
      barrier (finalize ran);
    * every recorded 1F1B pipeline schedule is bit-identical to the
      static model for its (pp, n_micro, overlap).

    Returns (report, failures, errors): ``failures`` → exit 1,
    ``errors`` (unreadable/corrupt input) → exit 2.
    """
    failures: List[str] = []
    errors: List[str] = []
    files = discover_sidecars([gang_dir], "trace_rank*.jsonl")
    ranks: Dict[int, dict] = {}
    for p in files:
        try:
            header, evs = read_sidecar(p, TRACE_SCHEMA)
        except (OSError, ValueError) as exc:
            errors.append(str(exc))
            continue
        rank = int(header.get("rank", 0))
        terminal = next((e for e in evs
                         if e.get("kind") == "barrier"
                         and e.get("name") == GANG_TERMINAL_BARRIER),
                        None)
        row: Dict[str, Any] = {
            "rank": rank,
            "file": p,
            "n_events": len(evs),
            "world_size": header.get("world_size"),
            "restart": header.get("restart"),
            "status": header.get("status"),
            "terminal_barrier": terminal is not None,
        }
        if terminal is not None:
            row["terminal_status"] = terminal.get("status")
            row["terminal_step"] = terminal.get("step")
        else:
            failures.append(
                f"rank {rank}: no {GANG_TERMINAL_BARRIER!r} terminal "
                f"barrier in {p} (finalize never ran)")
        sched = _rank_schedule_verdict(evs)
        row["schedule"] = sched
        if sched is not None and not sched["matches_static"]:
            failures.append(
                f"rank {rank}: recorded 1F1B schedule diverges from the "
                f"static model (pp={sched.get('pp')}, "
                f"n_micro={sched.get('n_micro')}, "
                f"overlap={sched.get('overlap')}) at event "
                f"{sched['divergence'].get('index')}")
        ranks[rank] = row
    if not files:
        errors.append(f"no trace sidecars found under {gang_dir} "
                      "(looked for trace_rank*.jsonl)")
    worlds = sorted({r["world_size"] for r in ranks.values()
                     if r["world_size"] is not None})
    if len(worlds) > 1:
        failures.append(
            f"sidecar headers disagree on world_size: {worlds}")
    expected = worlds[-1] if worlds else len(ranks)
    missing = [r for r in range(expected) if r not in ranks]
    if missing:
        failures.append(
            f"missing sidecar(s) for rank(s) {missing}: expected "
            f"{expected} ranks, found {sorted(ranks)}")
    report = {
        "tool": "trace_report",
        "mode": "gang",
        "version": 1,
        "dir": gang_dir,
        "files": files,
        "world_size": expected,
        "ranks_found": sorted(ranks),
        "missing_ranks": missing,
        "per_rank": [ranks[r] for r in sorted(ranks)],
        "verdict": "pass" if not (failures or errors) else "fail",
        "failures": failures,
        "errors": errors,
    }
    return report, failures, errors


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="trace_report", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", default=None,
                    help="trace sidecar files or directories holding "
                         "trace_rank*.jsonl (default: .)")
    ap.add_argument("--chrome", metavar="OUT",
                    help="write a Chrome/Perfetto trace JSON here")
    ap.add_argument("--incidents", action="append", default=[],
                    metavar="PATH",
                    help="incident sidecar file/dir (repeatable)")
    ap.add_argument("--black-box", metavar="OUT",
                    help="bundle sidecars + incidents + report into "
                         "one zip archive")
    ap.add_argument("--gang", metavar="DIR", default=None,
                    help="gang-run verdict mode: merge the dir's "
                         "trace_rank*.jsonl sidecars, require every "
                         "rank present with a gang/exit terminal "
                         "barrier, and check each recorded 1F1B "
                         "schedule against the static model; exit 1 "
                         "on any failure")
    ap.add_argument("--request", type=int, default=None, metavar="RID",
                    help="include this request's full event timeline")
    ap.add_argument("--max-requests", type=int, default=50,
                    help="cap the per_request rows in the report "
                         "(default 50; stats use all rows)")
    args = ap.parse_args(argv)

    if args.gang is not None:
        report, failures, gang_errors = gang_report(args.gang)
        json.dump(report, sys.stdout, indent=2, sort_keys=True,
                  default=str)
        sys.stdout.write("\n")
        if gang_errors:
            return 2
        if failures:
            return 1
        return 0

    errors: List[str] = []
    warnings: List[str] = []

    files = discover_sidecars(args.paths or ["."], "trace_rank*.jsonl")
    per_rank: Dict[int, List[dict]] = {}
    for p in files:
        try:
            header, evs = read_sidecar(p, TRACE_SCHEMA)
        except (OSError, ValueError) as exc:
            errors.append(str(exc))
            continue
        rank = int(header.get("rank", 0))
        per_rank.setdefault(rank, []).extend(evs)
        if header.get("dropped"):
            warnings.append(
                f"{p}: ring buffer dropped {header['dropped']} "
                "event(s) before the dump")
    if not files:
        errors.append("no trace sidecars found (looked for "
                      "trace_rank*.jsonl under: "
                      + ", ".join(args.paths or ["."]) + ")")

    events = merge_ranks(per_rank)
    rows, req_warnings = request_rows(events)
    warnings.extend(req_warnings)

    report: Dict[str, Any] = {
        "tool": "trace_report",
        "version": 1,
        "files": files,
        "ranks": sorted(per_rank),
        "n_events": len(events),
    }
    if rows:
        breakdown = _p95_blend(rows)
        terminal = sum(1 for r in rows if r["terminal"] is not None)
        report["requests"] = {
            "count": len(rows),
            "terminal": terminal,
            "breakdown": breakdown,
            "per_request": rows[:args.max_requests],
        }
    steps = step_stats(events)
    if steps:
        report["steps"] = steps
    pipe = pipeline_overlap(events)
    if pipe is not None:
        report["pipeline"] = {k: v for k, v in pipe.items()
                              if k != "schedule_events"}
    if args.request is not None:
        report["request_timeline"] = [
            e for e in events
            if e.get("kind") == "request"
            and e.get("rid") == args.request]

    inc_files: List[str] = []
    if args.incidents:
        inc_files, inc_records, inc_errors = load_incidents(
            args.incidents)
        errors.extend(inc_errors)
        kinds: Dict[str, int] = {}
        for r in inc_records:
            kinds[r.get("kind", "?")] = kinds.get(r.get("kind", "?"),
                                                  0) + 1
        report["incidents"] = {
            "files": inc_files,
            "count": len(inc_records),
            "by_kind": dict(sorted(kinds.items())),
            "last": inc_records[-5:],
        }

    # warnings/errors are live references: anything appended below
    # (e.g. an unwritable --chrome path) still lands in the report
    report["warnings"] = warnings
    report["errors"] = errors

    if args.chrome:
        try:
            with open(args.chrome, "w") as f:
                json.dump(
                    {"traceEvents": chrome_events(events),
                     "displayTimeUnit": "ms",
                     "metadata": {"producer": "tools/trace_report"}},
                    f, default=str)
            report["chrome_out"] = args.chrome
        except OSError as exc:
            errors.append(f"--chrome {args.chrome}: {exc}")
    if args.black_box:
        try:
            write_black_box(args.black_box, files, inc_files, report)
            report["black_box_out"] = args.black_box
        except OSError as exc:
            errors.append(f"--black-box {args.black_box}: {exc}")

    json.dump(report, sys.stdout, indent=2, sort_keys=True,
              default=str)
    sys.stdout.write("\n")
    if errors:
        return 2
    if warnings:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
