#!/usr/bin/env python3
"""perf_ledger — append, gate and report the repo's perf ledger.

Stdlib-only CLI over ``paddle_tpu/profiler/ledger.py``; loads that module
as a standalone file so it works on machines with no jax installed (same
convention as ``tpu_lint`` / ``trace_report``).

Subcommands:

  append  ARTIFACT.json [--ledger PATH] [--round N]
      Sniff an artifact (bench.py line, bench_serve.py line, pod_report
      verdict, fleet_sim report, driver BENCH/MULTICHIP wrapper) and
      append its normalized row(s).

  ingest  ARTIFACT.json... [--ledger PATH] [--reset]
      Deterministically normalize driver artifacts (BENCH_r0*.json,
      MULTICHIP_r0*.json, FLEET_r01.json) into the ledger.  --reset
      truncates first, so re-ingest is reproducible byte-for-byte.

  check   [--ledger PATH] [--tol F] [--stale-after N] [--proxies-only]
      Regression + staleness gate over the ledger trajectory.

  report  [--ledger PATH] [--format markdown|json]
      Per-series trajectory table with deltas.

Exit codes: 0 ok · 1 regression or stale ledger · 2 schema/usage error.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_DEFAULT_LEDGER = os.path.join(_REPO, "PERF_LEDGER.jsonl")


def _load_ledger_mod():
    path = os.path.join(_REPO, "paddle_tpu", "profiler", "ledger.py")
    spec = importlib.util.spec_from_file_location("perf_ledger_core", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod  # dataclasses resolves __module__ here
    spec.loader.exec_module(mod)
    return mod


def _sniff_rows(L, payload, path, rnd):
    """Route one artifact JSON to the right normalizer."""
    if isinstance(payload, dict):
        if "n_devices" in payload or ("rc" in payload and "cmd" in payload):
            return L.ingest_artifacts([path])
        if "recommended" in payload:
            return [L.from_fleet_report(payload, round=rnd)]
        if "predicted" in payload or payload.get("mode") == "serving":
            return [L.from_pod_report(payload, round=rnd)]
        metric = payload.get("metric", "")
        if metric.startswith("serve_"):
            return [L.from_bench_serve_result(payload, round=rnd)]
        if metric.startswith("llama_train") or "last_measured" in payload:
            return [L.from_bench_result(payload, round=rnd)]
    raise L.LedgerSchemaError(f"cannot determine artifact type of {path}")


def cmd_append(L, args) -> int:
    with open(args.artifact) as f:
        payload = json.load(f)
    rows = _sniff_rows(L, payload, args.artifact, args.round)
    for row in rows:
        L.append(args.ledger, row)
    print(f"perf_ledger: appended {len(rows)} row(s) to {args.ledger}")
    return 0


def cmd_ingest(L, args) -> int:
    rows = L.ingest_artifacts(args.artifacts)
    if args.reset and os.path.exists(args.ledger):
        os.remove(args.ledger)
    for row in rows:
        L.append(args.ledger, row)
    print(f"perf_ledger: ingested {len(rows)} row(s) from "
          f"{len(args.artifacts)} artifact(s) into {args.ledger}")
    return 0


def cmd_check(L, args) -> int:
    records = L.load(args.ledger)
    verdict = L.check(records, tol=args.tol, stale_after=args.stale_after,
                      proxies_only=args.proxies_only)
    print(json.dumps(verdict, indent=2, sort_keys=True))
    return 0 if verdict["ok"] else 1


def cmd_report(L, args) -> int:
    records = L.load(args.ledger)
    print(L.report(records, fmt=args.format))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="perf_ledger",
                                 description=__doc__.splitlines()[0])
    ap.add_argument("--ledger", default=_DEFAULT_LEDGER,
                    help="ledger JSONL path (default: PERF_LEDGER.jsonl)")
    sub = ap.add_subparsers(dest="command", required=True)

    p = sub.add_parser("append", help="normalize + append one artifact")
    p.add_argument("artifact")
    p.add_argument("--round", type=int, default=None)
    p.set_defaults(fn=cmd_append)

    p = sub.add_parser("ingest", help="normalize driver artifacts")
    p.add_argument("artifacts", nargs="+")
    p.add_argument("--reset", action="store_true",
                   help="truncate the ledger first (reproducible rebuild)")
    p.set_defaults(fn=cmd_ingest)

    p = sub.add_parser("check", help="regression + staleness gate")
    p.add_argument("--tol", type=float, default=0.05)
    p.add_argument("--stale-after", type=int, default=3)
    p.add_argument("--proxies-only", action="store_true",
                   help="gate only chip-free proxy metrics; skip staleness")
    p.set_defaults(fn=cmd_check)

    p = sub.add_parser("report", help="trajectory table")
    p.add_argument("--format", choices=("markdown", "json"),
                   default="markdown")
    p.set_defaults(fn=cmd_report)

    args = ap.parse_args(argv)
    L = _load_ledger_mod()
    try:
        return args.fn(L, args)
    except L.LedgerSchemaError as e:
        print(f"perf_ledger: schema error: {e}", file=sys.stderr)
        return 2
    except FileNotFoundError as e:
        print(f"perf_ledger: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
