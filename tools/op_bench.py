"""Op microbenchmark harness with a regression gate.

Reference analog: paddle/fluid/operators/benchmark/op_tester.cc (per-op
latency measurement from config) + tools/ci_op_benchmark.sh (the CI
gate that fails a PR when an op's time regresses against the recorded
baseline).

Usage:
  python tools/op_bench.py                 # measure, print table
  python tools/op_bench.py --record        # measure + write baseline
  python tools/op_bench.py --check         # measure + fail on >25% regr.
  python tools/op_bench.py --ops matmul,flash_attention

Baselines are stored per device kind (a CPU number never gates a TPU
run) in tools/op_bench_baseline.json. Timing uses the autotune module's
chained-execution timer so the measurement is device compute, not
host-transfer overhead.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "op_bench_baseline.json")
# fail --check when slower than baseline * this (overridable for noisy
# hosts / CI tiers)
THRESHOLD = float(os.environ.get("PTQ_OP_BENCH_THRESHOLD", "1.25"))


def _cases(quick=False):
    """name -> (build() -> (fn, args)); shapes sized for one chip."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    S = 512 if quick else 2048
    B = 1 if quick else 4
    H = 1024 if quick else 4096

    def matmul():
        k = jax.random.PRNGKey(0)
        a = jax.random.normal(k, (H, H), jnp.bfloat16)
        b = jax.random.normal(k, (H, H), jnp.bfloat16)
        return jax.jit(lambda x, y: x @ y), (a, b)

    def flash_attention():
        from paddle_tpu.ops import pallas_ops
        k = jax.random.split(jax.random.PRNGKey(0), 3)
        d, heads = 128, 8
        q, kk, v = (jax.random.normal(x, (B, S, heads, d), jnp.bfloat16)
                    for x in k)  # [B, S, H, D] — causal_attention layout

        def attn(q, k, v):
            return pallas_ops.causal_attention(q, k, v)
        return jax.jit(attn), (q, kk, v)

    def layernorm_residual():
        k = jax.random.PRNGKey(1)
        x = jax.random.normal(k, (B * S, H), jnp.bfloat16)
        g = jnp.ones((H,), jnp.float32)

        def f(x, g):
            m = jnp.mean(x.astype(jnp.float32), -1, keepdims=True)
            v = jnp.var(x.astype(jnp.float32), -1, keepdims=True)
            return ((x - m) * jax.lax.rsqrt(v + 1e-6) * g).astype(x.dtype) + x
        return jax.jit(f), (x, g)

    def embedding_gather():
        k = jax.random.PRNGKey(2)
        table = jax.random.normal(k, (32000, H), jnp.bfloat16)
        ids = jax.random.randint(k, (B * S,), 0, 32000)
        return jax.jit(lambda t, i: t[i]), (table, ids)

    def fused_adamw_update():
        import optax
        k = jax.random.PRNGKey(3)
        p = {"w": jax.random.normal(k, (H, H), jnp.float32)}
        opt = optax.adamw(1e-3)
        st = opt.init(p)
        g = {"w": jax.random.normal(k, (H, H), jnp.float32)}

        @jax.jit
        def upd(p, st, g):
            u, st = opt.update(g, st, p)
            return optax.apply_updates(p, u), st
        return upd, (p, st, g)

    def softmax_ce():
        k = jax.random.PRNGKey(4)
        logits = jax.random.normal(k, (B * S, 32000), jnp.float32)
        labels = jax.random.randint(k, (B * S,), 0, 32000)

        def f(lg, lb):
            ls = jax.nn.log_softmax(lg)
            return -jnp.mean(jnp.take_along_axis(ls, lb[:, None], 1))
        return jax.jit(f), (logits, labels)

    def llama_train_step():
        # End-to-end rung: the same smoke config bench.py runs off-TPU
        # (vocab 1024 / hidden 256 / 4 layers / S 256 / B 2). Gating this
        # one case catches gross train-step regressions even when the TPU
        # tunnel is down and bench.py cannot record a real-chip number.
        import functools

        import optax

        from paddle_tpu.models.llama import LlamaConfig, init_params, loss_fn

        cfg = LlamaConfig(
            vocab_size=1024, hidden_size=256, intermediate_size=512,
            num_hidden_layers=4, num_attention_heads=4,
            num_key_value_heads=4, max_position_embeddings=512,
            dtype=jnp.float32, use_remat=False)
        Bs, Ss = 2, 256
        params = init_params(cfg, jax.random.PRNGKey(0))
        opt = optax.adamw(3e-4, b1=0.9, b2=0.95, weight_decay=0.1)
        opt_state = opt.init(params)

        @jax.jit
        def step(params, opt_state, batch):
            (_, ce), grads = jax.value_and_grad(
                lambda p: loss_fn(cfg, p, batch), has_aux=True)(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, ce

        rng = np.random.default_rng(0)
        batch = {
            "input_ids": jnp.asarray(
                rng.integers(0, cfg.vocab_size, (Bs, Ss)), jnp.int32),
            "labels": jnp.asarray(
                rng.integers(0, cfg.vocab_size, (Bs, Ss)), jnp.int32),
        }
        return functools.partial(step, params, opt_state), (batch,)

    def llama_decode():
        # Generation rung: prefill + 16 greedy decode steps as the one
        # compiled scan models/decoding.py serves — gates KV-cache
        # decode throughput the way llama_train_step gates training.
        import functools

        from paddle_tpu.models.llama import (LlamaConfig, generate,
                                             init_params)

        cfg = LlamaConfig(
            vocab_size=1024, hidden_size=256, intermediate_size=512,
            num_hidden_layers=4, num_attention_heads=4,
            num_key_value_heads=4, max_position_embeddings=512,
            dtype=jnp.float32, use_remat=False)
        params = init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 32)),
                             jnp.int32)

        def decode(params, prompt):
            return generate(cfg, params, prompt, max_new_tokens=16)
        return functools.partial(decode, params), (prompt,)

    return {
        "matmul_bf16": matmul,
        "flash_attention": flash_attention,
        "layernorm_residual": layernorm_residual,
        "embedding_gather": embedding_gather,
        "fused_adamw_update": fused_adamw_update,
        "softmax_ce": softmax_ce,
        "llama_train_step": llama_train_step,
        "llama_decode": llama_decode,
    }


def measure(names=None, quick=False, iters=None):
    import jax

    from paddle_tpu.ops.autotune import time_callable
    from paddle_tpu.profiler import compile_tracker

    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "cpu")
    cases = _cases(quick=quick)
    names = names or list(cases)
    n_iter = iters or (2 if quick else 5)
    out = {}
    compile_info = {}
    for name in names:
        if name not in cases:
            raise SystemExit(f"unknown op case {name!r}; "
                             f"have {sorted(cases)}")
        # per-op compile attribution: a timing regression caused by a
        # recompile (vs a genuinely slower kernel) shows up as a compile
        # delta during the measured window
        pre = compile_tracker.stats()
        fn, args = cases[name]()
        t = time_callable(fn, args, warmup=1, iters=n_iter)
        post = compile_tracker.stats()
        out[name] = round(t * 1e3, 4)  # ms
        compile_info[name] = {
            "compiles": post["compile_count"] - pre["compile_count"],
            "compile_s": round(
                post["compile_seconds"] - pre["compile_seconds"], 4),
        }
        print(f"{name:24s} {out[name]:10.3f} ms   "
              f"[{compile_info[name]['compiles']} compiles, "
              f"{compile_info[name]['compile_s']:.2f} s]", flush=True)
    return kind, out, compile_info


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--record", action="store_true",
                    help="write measurements as the new baseline")
    ap.add_argument("--check", action="store_true",
                    help="fail (rc=1) on regression vs baseline")
    ap.add_argument("--ops", default=None,
                    help="comma-separated case subset")
    ap.add_argument("--quick", action="store_true",
                    help="small shapes / fewer iters (harness smoke)")
    ap.add_argument("--strict", action="store_true",
                    help="with --check: a measured op with no recorded "
                         "baseline FAILS instead of being skipped, so new "
                         "ops cannot slip past the gate un-recorded")
    ap.add_argument("--metrics-out", default=None, metavar="PATH",
                    help="write a JSON telemetry sidecar (per-op compile "
                         "count/seconds + wall ms) so a BENCH_*.json "
                         "regression can be attributed to recompiles")
    args = ap.parse_args(argv)

    names = args.ops.split(",") if args.ops else None

    book = {}
    if os.path.exists(BASELINE):
        with open(BASELINE) as f:
            book = json.load(f)

    import platform
    host = platform.node()

    if args.record:
        # refuse BEFORE the (potentially minutes-long) measurement:
        # every input to the check is already known
        import jax
        kind0 = getattr(jax.devices()[0], "device_kind", "cpu")
        key0 = f"{kind0}{'|quick' if args.quick else ''}"
        prev = book.get(key0, {})
        prev_host = prev.get("__host__")
        will_record = set(names or _cases(quick=args.quick))
        survivors = set(prev) - will_record - {"__host__"}
        if prev_host is not None and prev_host != host and survivors:
            # merging would relabel host-A wall-clocks as host-B's and
            # gate them at the strict same-host threshold
            raise SystemExit(
                f"refusing partial --record: {key0!r} was recorded on "
                f"{prev_host!r} and ops {sorted(survivors)} would keep "
                f"its numbers under this host's ({host!r}) label. "
                "Re-record ALL ops (drop --ops) or delete the key from "
                f"{BASELINE} first.")

    kind, results, compile_info = measure(names, quick=args.quick)
    key = f"{kind}{'|quick' if args.quick else ''}"

    if args.metrics_out:
        sidecar = {
            "device_kind": kind,
            "host": host,
            "ops": {n: {"ms": results[n], **compile_info[n]}
                    for n in results},
        }
        with open(args.metrics_out, "w") as f:
            json.dump(sidecar, f, indent=1, sort_keys=True)
        print(f"telemetry sidecar -> {args.metrics_out}")

    if args.record:
        book.setdefault(key, {}).update(results)
        book[key]["__host__"] = host
        with open(BASELINE, "w") as f:
            json.dump(book, f, indent=1, sort_keys=True)
        print(f"baseline recorded for {key!r} -> {BASELINE}")
        return 0

    if args.check:
        base = book.get(key, {})
        threshold = THRESHOLD
        rec_host = base.get("__host__")
        if rec_host is not None and rec_host != host:
            # a committed baseline from another machine still catches
            # GROSS regressions, but absolute wall-clock does not port
            # across hosts at the same-host threshold
            xf = float(os.environ.get("PTQ_OP_BENCH_XHOST_FACTOR", "3"))
            threshold *= xf
            print(f"baseline recorded on {rec_host!r}, running on "
                  f"{host!r}: threshold relaxed to {threshold:.2f}x")
        bad = []
        missing = []
        for name, ms in results.items():
            ref = base.get(name)
            if ref is None:
                missing.append(name)
                print(f"{name}: no baseline for {key!r} "
                      f"({'FAIL (--strict)' if args.strict else 'skipped'})")
                continue
            ratio = ms / ref
            status = "OK" if ratio <= threshold else "REGRESSION"
            print(f"{name:24s} {ms:10.3f} ms vs {ref:10.3f} ms "
                  f"({ratio:5.2f}x) {status}")
            if ratio > threshold:
                bad.append((name, ratio))
        if bad:
            print(f"FAILED: {len(bad)} op(s) regressed >"
                  f"{(threshold - 1) * 100:.0f}%: {bad}")
            return 1
        if args.strict and missing:
            print(f"FAILED (--strict): {len(missing)} op(s) have no "
                  f"baseline for {key!r}: {missing}; run --record first")
            return 1
        print("all ops within threshold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
