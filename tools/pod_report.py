#!/usr/bin/env python
"""Pod-fit reporter: will this model FIT on that pod, and how fast?

Compiles a named model preset's full training step on a *virtual* mesh
shaped like a real TPU pod (no hardware: JAX_PLATFORMS=cpu +
--xla_force_host_platform_device_count), lets the cost-model planner
choose the (dp, pp, sharding, mp) topology, and reads the answer
straight from XLA's compiled.memory_analysis() via profiler.xmem —
the same number the real pod would enforce. Parameters are never
materialized (jax.ShapeDtypeStruct throughout), so reporting on a 7B
model needs a laptop, not 64 chips.

    python tools/pod_report.py --preset llama7b --mesh v5p-64

emits a JSON report: per-device peak HBM, fits/doesn't-fit verdict
against the generation's HBM, the collective set XLA inserted, and the
cost-model-predicted step time / MFU / tokens-per-second.
"""
from __future__ import annotations

import argparse
import json
import math
import os
import re
import sys
import time

# TPU generation table: per-chip HBM, peak dense bf16 FLOP/s, and the
# per-core VMEM the Level-3 kernel verifier budgets Pallas blocks
# against (~16 MiB physical minus Mosaic spill/prologue headroom;
# double on v6e).
TPU_GENERATIONS = {
    "v4":  dict(hbm_gib=32.0,  peak_flops=275e12, ici_gbps=100.0,
                vmem_mib=12),
    "v5e": dict(hbm_gib=16.0,  peak_flops=197e12, ici_gbps=50.0,
                vmem_mib=12),
    "v5p": dict(hbm_gib=95.0,  peak_flops=459e12, ici_gbps=100.0,
                vmem_mib=12),
    "v6e": dict(hbm_gib=32.0,  peak_flops=918e12, ici_gbps=100.0,
                vmem_mib=24),
}

_MESH_RE = re.compile(r"^(?P<gen>[a-z0-9]+)-(?P<n>\d+)$")


def parse_mesh(spec: str):
    m = _MESH_RE.match(spec.strip().lower())
    if not m or m.group("gen") not in TPU_GENERATIONS:
        raise SystemExit(
            f"unrecognized --mesh {spec!r}; expected <gen>-<chips> with "
            f"gen in {sorted(TPU_GENERATIONS)} (e.g. v5p-64)")
    return m.group("gen"), int(m.group("n"))


def _parse_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("mode", nargs="?", default=None, choices=["serving"],
                    help="'serving' emits only the serving capacity "
                         "section (hardware-free arithmetic, no train-"
                         "step compile — seconds instead of minutes); "
                         "omit for the full pod-fit report")
    ap.add_argument("--preset", default="llama7b",
                    help="model preset from models.llama.PRESETS")
    ap.add_argument("--mesh", default="v5p-64",
                    help="pod shape <generation>-<chips>, e.g. v5p-64")
    ap.add_argument("--global-batch", type=int, default=16)
    ap.add_argument("--seq", type=int, default=None,
                    help="sequence length (default: preset max positions)")
    ap.add_argument("--page-size", type=int, default=128,
                    help="paged-KV tokens per pool page for the "
                         "serving capacity section")
    ap.add_argument("--kv-dtype", default="bf16",
                    choices=["bf16", "fp16", "int8", "fp8"],
                    help="paged-KV page dtype for the serving capacity "
                         "section; sub-2-byte dtypes include the "
                         "quantized-KV per-page scale-pool overhead "
                         "and report the capacity ratio vs the bf16 "
                         "baseline")
    ap.add_argument("--replicas", type=int, default=1,
                    help="engine replicas behind a serving.Router — "
                         "the serving section reports router-level "
                         "aggregate capacity (N x plan_capacity) "
                         "alongside the per-engine numbers")
    ap.add_argument("--fleet-workload", default="diurnal",
                    help="seeded arrival preset (serving.workloads) "
                         "the serving section's fleet block sizes "
                         "against; 'none' disables the block")
    ap.add_argument("--fleet-requests", type=int, default=200)
    ap.add_argument("--fleet-seed", type=int, default=0)
    ap.add_argument("--fleet-horizon-s", type=float, default=60.0)
    ap.add_argument("--fleet-prompt-len", type=int, default=12)
    ap.add_argument("--fleet-new-tokens", type=int, default=8)
    ap.add_argument("--max-running", type=int, default=8,
                    help="per-replica engine slots assumed by the "
                         "fleet block's service model")
    ap.add_argument("--chunk", type=int, default=16,
                    help="prefill chunk assumed by the fleet block's "
                         "service model")
    ap.add_argument("--prefix-hit-rate", type=float, default=None,
                    help="measured shared-prefix hit rate in [0, 1) "
                         "(e.g. the prefix_hit_rate from bench_serve "
                         "--workload shared-prefix) — the serving "
                         "section then also reports effective "
                         "blocks-per-request and concurrency with "
                         "that fraction of each request's pages "
                         "shared from the radix cache")
    ap.add_argument("--topology", default=None,
                    help="override the planner: dp,pp,sharding,mp")
    ap.add_argument("--ledger", nargs="?", metavar="PATH",
                    const="PERF_LEDGER.jsonl", default=None,
                    help="append the report's chip-free proxy verdict "
                         "(predicted step ms/MFU, plan capacity, KV "
                         "capacity ratio, fleet min replicas) as a "
                         "provenance-stamped row to the perf ledger at "
                         "PATH (default PERF_LEDGER.jsonl, relative to "
                         "the repo root)")
    ap.add_argument("--out", default="-",
                    help="output path for the JSON report (- = stdout)")
    ap.add_argument("--plan-out", default=None,
                    help="also write the winning topology as an "
                         "executable plan spec (distributed.plan.Plan "
                         "JSON: axes, schedule, microbatches, "
                         "per-param partition specs) — "
                         "Plan.from_report() / Plan.load() compile "
                         "exactly the config the planner scored")
    ap.add_argument("--list-presets", action="store_true")
    return ap.parse_args(argv)


# ---------------------------------------------------------------------------
# planner: enumerate (dp, pp, sharding, mp) factorizations, score with the
# alpha-beta cost model + an analytic memory estimate, pick the cheapest
# that fits. Only the winner is actually compiled.
# ---------------------------------------------------------------------------

def _candidate_topologies(cfg, n_dev, global_batch):
    L, H = cfg.num_hidden_layers, cfg.hidden_size
    nh, nkv = cfg.num_attention_heads, cfg.num_key_value_heads
    out = []
    for mp in range(1, n_dev + 1):
        if n_dev % mp or nh % mp or nkv % mp or H % mp:
            continue
        if cfg.intermediate_size % mp or cfg.vocab_size % mp:
            continue
        rest = n_dev // mp
        for pp in range(1, rest + 1):
            if rest % pp or L % pp:
                continue
            dpw = rest // pp          # data-parallel world = dp * sharding
            if global_batch % dpw:
                continue
            if pp > 1 and (global_batch // dpw) % pp:
                continue              # microbatch split (mb = pp)
            # sharding (ZeRO) axis: either fold the whole data world into
            # dp, or carve all of it out as a dedicated sharding axis
            for sharding in (1, dpw) if dpw > 1 else (1,):
                out.append(dict(dp=dpw // sharding, pp=pp,
                                sharding=sharding, mp=mp))
    return out


def _score_topology(cfg, topo, n_dev, global_batch, seq, n_params, gen,
                    model_flops):
    """(estimated per-device bytes, predicted step time in us, breakdown)."""
    from paddle_tpu.distributed.auto_parallel.cost_model import (
        CommContext, all_reduce_cost, p2p_cost)
    dp, pp, sharding, mp = (topo["dp"], topo["pp"], topo["sharding"],
                            topo["mp"])
    L, H, V = cfg.num_hidden_layers, cfg.hidden_size, cfg.vocab_size
    I = cfg.intermediate_size
    ctx = CommContext(ici_bandwidth_gbps=gen["ici_gbps"])
    dpw = dp * sharding
    b_loc = global_batch // dpw
    mb = pp if pp > 1 else 1
    zero_deg = sharding if sharding > 1 else dp

    # -- memory (analytic, for ranking only; verdict comes from XLA) --
    param_dev = 2 * n_params / (pp * mp)          # bf16 weights
    grad_dev = param_dev
    opt_dev = 2 * param_dev / max(1, zero_deg)    # adamw mu+nu, ZeRO-1
    act_slab = b_loc * seq * H * 2                # one bf16 activation
    # remat 'dots' keeps matmul outputs: ~2H + 2I floats/layer/token
    act_dev = (L / pp) * (b_loc / mb) * seq * (2 * H + 2 * I) * 2 * \
        min(mb, pp)
    logits_dev = b_loc * seq * V * 4 / mp         # fp32 logits + lse
    mem_dev = param_dev + grad_dev + opt_dev + act_dev + logits_dev

    # -- time (alpha-beta) --
    eff = 0.55                                    # matmul fraction of peak
    compute_us = model_flops / n_dev / (gen["peak_flops"] * eff) * 1e6
    act_mb = act_slab / mb
    mp_comm_us = 0.0
    if mp > 1:
        # 2 all-reduces/layer forward (attention out + mlp out), 2 backward
        mp_comm_us = (L / pp) * mb * 4 * all_reduce_cost(act_mb, mp, ctx)
    bubble = (pp - 1) / (mb + pp - 1) if pp > 1 else 0.0
    pipe_us = (compute_us + mp_comm_us) / (1.0 - bubble)
    p2p_us = 2 * (pp - 1) * mb * p2p_cost(act_mb, ctx) if pp > 1 else 0.0
    sync_us = all_reduce_cost(grad_dev, dpw, ctx) if dpw > 1 else 0.0
    step_us = pipe_us + p2p_us + sync_us
    return mem_dev, step_us, dict(
        compute_us=compute_us, mp_comm_us=mp_comm_us, p2p_us=p2p_us,
        dp_sync_us=sync_us, pp_bubble_fraction=bubble,
        est_mem_bytes=mem_dev)


def plan_topology(cfg, n_dev, global_batch, seq, n_params, gen,
                  model_flops):
    cands = _candidate_topologies(cfg, n_dev, global_batch)
    if not cands:
        raise SystemExit(
            f"no valid (dp,pp,sharding,mp) factorization of {n_dev} "
            f"devices for this preset/batch — adjust --global-batch")
    hbm = gen["hbm_gib"] * 2**30
    scored = []
    for t in cands:
        mem, step_us, detail = _score_topology(
            cfg, t, n_dev, global_batch, seq, n_params, gen, model_flops)
        penalty = 1e12 if mem > hbm else 0.0
        scored.append((step_us + penalty, step_us, mem, t, detail))
    scored.sort(key=lambda s: s[0])
    return scored


# ---------------------------------------------------------------------------

def _collectives_of(compiled):
    """The set of collective ops XLA inserted, from the optimized HLO."""
    try:
        hlo = compiled.as_text()
    except Exception:
        return []
    names = re.findall(
        r"\b(all-reduce|all-gather|reduce-scatter|all-to-all|"
        r"collective-permute|collective-broadcast)\b", hlo)
    return sorted(set(names))


def build_report(args):
    gen_name, n_dev = parse_mesh(args.mesh)
    gen = TPU_GENERATIONS[gen_name]

    import jax
    import jax.numpy as jnp
    import numpy as np
    from paddle_tpu.distributed.mesh import HybridTopology
    from paddle_tpu.models import llama
    from paddle_tpu.profiler import xmem

    cfg = llama.preset(args.preset)
    seq = args.seq or cfg.max_position_embeddings
    B = args.global_batch

    # abstract parameter census (no materialization)
    p_shapes = jax.eval_shape(lambda k: llama.init_params(cfg, k),
                              jax.ShapeDtypeStruct((2,), jnp.uint32))
    n_params = int(sum(np.prod(l.shape)
                       for l in jax.tree_util.tree_leaves(p_shapes)))
    tokens = B * seq
    # model FLOPs per step (fwd+bwd): 6N per token + attention term
    model_flops = 6.0 * n_params * tokens \
        + 12.0 * cfg.num_hidden_layers * cfg.hidden_size * seq * tokens

    scored = plan_topology(cfg, n_dev, B, seq, n_params, gen, model_flops)
    if args.topology:
        dp, pp, sharding, mp = (int(x) for x in args.topology.split(","))
        choice = dict(dp=dp, pp=pp, sharding=sharding, mp=mp)
        mem, step_us, detail = _score_topology(
            cfg, choice, n_dev, B, seq, n_params, gen, model_flops)
        chosen = (step_us, step_us, mem, choice, detail)
    else:
        chosen = scored[0]
    _, pred_step_us, est_mem, topo_dims, detail = chosen

    topo = HybridTopology(**topo_dims)
    # use_pp=False: the layer stack is still sharded over the 'pp' mesh
    # axis (param_specs leads with P("pp", ...)), but stage scheduling is
    # left to GSPMD instead of the shard_map pipeline — the installed jax
    # has no jax.shard_map, and for a fit verdict the GSPMD lowering is
    # the conservative one (same weights/optimizer placement, activations
    # not microbatched).
    step_fn, _init_fn = llama.build_train_step(cfg, topo, use_pp=False)
    p_abs, o_abs = step_fn.abstract_state()
    batch_abs = {
        k: jax.ShapeDtypeStruct((B, seq), jnp.int32, sharding=sh)
        for k, sh in step_fn.batch_shardings.items()}

    xmem.enable()
    # abstract compiles of 7B-scale steps take minutes; the persistent
    # XLA cache (FLAGS_tpu_persistent_cache) makes repeat reports warm
    from paddle_tpu.core import compile_cache
    compile_cache.ensure()
    t0 = time.perf_counter()
    with topo.mesh:
        profile, compiled = xmem.analyze(
            step_fn.jitted, p_abs, o_abs, batch_abs,
            source="pod_report", name=f"{args.preset}@{args.mesh}")
    compile_s = time.perf_counter() - t0
    if profile is None:
        raise SystemExit("backend returned no memory_analysis(); "
                         "cannot produce a pod-fit verdict")

    hbm_bytes = int(gen["hbm_gib"] * 2**30)
    peak = profile["peak_bytes"]
    pred_step_s = pred_step_us * 1e-6
    mfu = model_flops / (pred_step_s * n_dev * gen["peak_flops"])
    return {
        "preset": args.preset,
        "mesh": args.mesh,
        "generation": {"name": gen_name, "hbm_gib_per_chip": gen["hbm_gib"],
                       "peak_bf16_flops_per_chip": gen["peak_flops"]},
        "devices": n_dev,
        "model": {
            "n_params": n_params,
            "hidden_size": cfg.hidden_size,
            "num_layers": cfg.num_hidden_layers,
            "vocab_size": cfg.vocab_size,
            "seq_len": seq,
            "global_batch": B,
            "model_flops_per_step": model_flops,
        },
        "topology": dict(topo_dims,
                         n_microbatches=topo_dims["pp"]
                         if topo_dims["pp"] > 1 else 1,
                         zero_axis="sharding"
                         if topo_dims["sharding"] > 1 else "dp"),
        "planner": {
            "candidates_considered": len(scored),
            "top": [dict(rank=i + 1, **s[3],
                         predicted_step_ms=round(s[1] / 1e3, 3),
                         est_mem_gib=round(s[2] / 2**30, 2))
                    for i, s in enumerate(scored[:5])],
        },
        "memory": {
            "argument_bytes": profile["argument_bytes"],
            "output_bytes": profile["output_bytes"],
            "temp_bytes": profile["temp_bytes"],
            "alias_bytes": profile["alias_bytes"],
            "generated_code_bytes": profile["generated_code_bytes"],
            "per_device_peak_bytes": peak,
            "per_device_peak_gib": round(peak / 2**30, 3),
            "planner_estimate_gib": round(est_mem / 2**30, 3),
        },
        "fits": {
            "hbm_bytes_per_chip": hbm_bytes,
            "fits": peak <= hbm_bytes,
            "headroom_bytes": hbm_bytes - peak,
            "hbm_utilization": round(peak / hbm_bytes, 4),
        },
        "collectives": _collectives_of(compiled),
        "kernels": _kernel_section(gen),
        "serving": _serving_section(cfg, gen, args),
        "predicted": {
            "step_time_ms": round(pred_step_us / 1e3, 3),
            "mfu": round(mfu, 4),
            "tokens_per_second": round(tokens / pred_step_s, 1),
            "compute_ms": round(detail["compute_us"] / 1e3, 3),
            "mp_comm_ms": round(detail["mp_comm_us"] / 1e3, 3),
            "p2p_ms": round(detail["p2p_us"] / 1e3, 3),
            "dp_sync_ms": round(detail["dp_sync_us"] / 1e3, 3),
            "pp_bubble_fraction": round(detail["pp_bubble_fraction"], 4),
        },
        "xla": {
            "compile_seconds": round(compile_s, 2),
            "flops_reported": profile["flops"],
            "bytes_accessed": profile["bytes_accessed"],
        },
        "notes": _plan_notes(n_dev),
    }


def _kernel_section(gen):
    """Level-3 kernel verifier sweep for the report: trace the
    registered Pallas kernel library (CPU-only, nothing executes) with
    this generation's per-core VMEM budget and report per-kernel block
    footprints + verdicts. None when the analysis package is missing."""
    try:
        from paddle_tpu.analysis import kernel_checks
        from paddle_tpu.profiler import xmem
    except ImportError:
        return None
    budget = int(gen["vmem_mib"]) << 20
    try:
        findings = kernel_checks.verify_registered(
            config={"vmem_budget_bytes": budget})
        n_cases = len(kernel_checks.registered_cases())
    except Exception as e:  # a broken kernel library must not kill the fit report
        return {"error": f"{type(e).__name__}: {e}"}
    ests = xmem.kernel_estimates()
    return {
        "vmem_budget_mib": int(gen["vmem_mib"]),
        "cases_verified": n_cases,
        "estimates": [
            dict(kernel=e["kernel"],
                 vmem_bytes=e["vmem_bytes"],
                 vmem_mib=round(e["vmem_bytes"] / 2**20, 2),
                 within_budget=e["vmem_bytes"] <= budget)
            for e in ests[:16]],
        "findings": [f.to_dict() for f in findings],
        "ok": not any(f.severity == "error" for f in findings),
    }


def _serving_section(cfg, gen, args):
    """Paged-KV serving capacity on one chip of this generation —
    hardware-free arithmetic (serving.plan_capacity): how many pool
    pages fit beside the bf16 weights and how many concurrent
    max-length requests per chip that sustains.  The number an
    operator needs before sizing a serving fleet."""
    try:
        from paddle_tpu.serving import plan_capacity
    except ImportError:
        return None
    hbm = int(gen["hbm_gib"] * 2**30)
    seq = args.seq or cfg.max_position_embeddings
    kv_dtype = getattr(args, "kv_dtype", None) or "bf16"
    plan = plan_capacity(cfg, hbm_bytes=hbm,
                         page_size=int(args.page_size),
                         max_model_len=seq, kv_dtype=kv_dtype)
    plan["weights_gib"] = round(plan["weights_bytes"] / 2**30, 2)
    plan["usable_kv_gib"] = round(plan["usable_kv_bytes"] / 2**30, 2)
    plan["fits"] = plan["max_concurrent_requests"] > 0
    if kv_dtype != "bf16":
        # the --kv-dtype axis: same chip, same weights, only the page
        # format changes — the predicted capacity win of quantized KV
        base = plan_capacity(cfg, hbm_bytes=hbm,
                             page_size=int(args.page_size),
                             max_model_len=seq, kv_dtype="bf16")
        plan["baseline_bf16"] = {
            "num_pages": base["num_pages"],
            "page_bytes": base["page_bytes"],
            "max_concurrent_requests": base["max_concurrent_requests"],
        }
        if base["max_concurrent_requests"] > 0:
            plan["capacity_ratio_vs_bf16"] = round(
                plan["max_concurrent_requests"]
                / base["max_concurrent_requests"], 3)
    # measured prefix-hit-rate folds into capacity: a hit fraction h
    # means h of each request's pages come from the radix cache and
    # are shared, so only (1-h) of blocks_per_request are unique per
    # request.  Raw numbers stay in the report next to the effective
    # ones — the raw plan is the zero-reuse worst case
    hit = getattr(args, "prefix_hit_rate", None)
    if hit is not None:
        if not 0.0 <= hit < 1.0:
            raise SystemExit(
                f"--prefix-hit-rate {hit} out of range [0, 1)")
        raw_blocks = plan["blocks_per_request"]
        eff_blocks = max(int(math.ceil(raw_blocks * (1.0 - hit))), 1)
        n_pages = plan["num_pages"]
        eff_concurrent = (n_pages - 1) // eff_blocks if n_pages > 1 else 0
        plan["prefix_hit_rate"] = float(hit)
        plan["effective_blocks_per_request"] = eff_blocks
        plan["effective_max_concurrent_requests"] = int(eff_concurrent)
    # router-level view: N independent replicas behind serving.Router
    # multiply concurrency and pool pages linearly (each replica owns
    # its own chip and pool); per-request numbers are per-engine
    n = max(int(getattr(args, "replicas", 1) or 1), 1)
    plan["replicas"] = n
    plan["aggregate"] = {
        "max_concurrent_requests":
            n * plan["max_concurrent_requests"],
        "num_pages": n * plan["num_pages"],
        "usable_kv_bytes": n * plan["usable_kv_bytes"],
    }
    fleet = _fleet_block(plan, args)
    if fleet is not None:
        plan["fleet"] = fleet
    return plan


def _fleet_block(plan, args):
    """Analytic fleet sizing for this plan's page pool: the shared
    ``serving.autoscale.recommend_fleet`` arithmetic over the same
    seeded arrival stream ``tools/fleet_sim.py`` simulates — by
    construction the two tools return the same min-replica answer for
    the same knobs (the consistency test pins it)."""
    preset = getattr(args, "fleet_workload", None)
    if not preset or preset == "none":
        return None
    try:
        from paddle_tpu.serving import autoscale, workloads
    except ImportError:
        return None
    workloads.validate(preset)
    arrivals = workloads.generate(
        preset, int(args.fleet_requests), seed=int(args.fleet_seed),
        horizon_s=float(args.fleet_horizon_s),
        prompt_len=int(args.fleet_prompt_len),
        max_new_tokens=int(args.fleet_new_tokens))
    model = autoscale.ServiceModel(
        max_running=int(args.max_running), chunk=int(args.chunk),
        page_size=int(plan["page_size"]),
        num_pages=int(plan["num_pages"]),
        max_model_len=int(plan["max_model_len"]),
        max_queue=8 * int(args.max_running))
    rec = autoscale.recommend_fleet(model, arrivals)
    rec["workload"] = preset
    rec["seed"] = int(args.fleet_seed)
    rec["horizon_s"] = float(args.fleet_horizon_s)
    rec["service_model"] = model.to_dict()
    rec["note"] = ("uncalibrated step costs (shared defaults); feed a "
                   "measured trace or bench_serve fleet block through "
                   "tools/fleet_sim.py to validate under simulation")
    return rec


def build_serving_report(args):
    """The ``serving`` subcommand: just the capacity arithmetic —
    plan_capacity over the --kv-dtype axis, no train-step compile, so
    it answers "how many concurrent requests per chip" in seconds."""
    gen_name, n_dev = parse_mesh(args.mesh)
    gen = TPU_GENERATIONS[gen_name]
    from paddle_tpu.models import llama
    cfg = llama.preset(args.preset)
    return {
        "mode": "serving",
        "preset": args.preset,
        "mesh": args.mesh,
        "generation": {"name": gen_name,
                       "hbm_gib_per_chip": gen["hbm_gib"]},
        "serving": _serving_section(cfg, gen, args),
    }


def _plan_notes(n_dev):
    """Advisory lines attached to the report. A multi-host plan (more
    chips than one host carries — 8 on every supported generation)
    depends on DCN rendezvous and gang collectives, where a single hung
    rank stalls the whole job; flag it when the runtime health layer
    (FLAGS_tpu_watchdog) is off."""
    notes = []
    from paddle_tpu.core.flags import flag
    if n_dev > 8 and not flag("FLAGS_tpu_watchdog"):
        notes.append(
            f"multi-host plan ({n_dev} chips) with FLAGS_tpu_watchdog "
            "disabled: a hung rank in device init or a collective will "
            "stall the gang with no bounded-time recovery — set "
            "FLAGS_tpu_watchdog=1 (deadlines: FLAGS_tpu_watchdog_* ; "
            "see docs/robustness.md) to convert hangs into exit-101 "
            "elastic relaunches")
    return notes


def write_plan_spec(report, preset, path):
    """Serialize the report's winning topology as an executable
    ``distributed.plan.Plan`` spec: axes + schedule/microbatches from the
    report's ``topology`` section, plus the model's per-parameter
    partition specs in the portable ``reshard.spec_to_json`` form (keyed
    by '/'-joined parameter path). ``Plan.load(path)`` /
    ``Plan.from_report(path)`` then compile exactly the config the
    planner scored."""
    import dataclasses

    import jax
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed.plan import Plan
    from paddle_tpu.distributed.reshard import spec_to_json
    from paddle_tpu.models import llama

    cfg = llama.preset(preset)
    plan = Plan.from_report(report)
    flat, _ = jax.tree_util.tree_flatten_with_path(
        llama.param_specs(cfg), is_leaf=lambda s: isinstance(s, P))

    def key(p):
        return "/".join(str(getattr(k, "key", k)) for k in p)

    plan = dataclasses.replace(
        plan, param_specs={key(p): spec_to_json(s) for p, s in flat})
    plan.save(path)
    print(f"wrote plan spec {path}", file=sys.stderr)


def _ledger_append(repo_root, ledger_path, report):
    """Append the chip-free proxy verdict to the perf ledger.

    Loads profiler/ledger.py standalone (stdlib-only, no package import)
    so the fast hardware-free 'serving' mode stays fast."""
    import importlib.util
    src = os.path.join(repo_root, "paddle_tpu", "profiler", "ledger.py")
    spec = importlib.util.spec_from_file_location("perf_ledger_core", src)
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault(spec.name, mod)
    spec.loader.exec_module(mod)
    if not os.path.isabs(ledger_path):
        ledger_path = os.path.join(repo_root, ledger_path)
    cmd = "python " + " ".join(
        [os.path.basename(sys.argv[0] or "pod_report.py")] + sys.argv[1:])
    row = mod.from_pod_report(report, ts=time.time(), cmd=cmd)
    mod.append(ledger_path, row)
    print(f"pod_report: ledger row appended to {ledger_path}",
          file=sys.stderr)


def main(argv=None):
    args = _parse_args(argv)
    _, n_dev = parse_mesh(args.mesh)

    # environment BEFORE jax import: hardware-free virtual pod
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo_root)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import _xla_cpu_flags
    _xla_cpu_flags.ensure(device_count=n_dev)

    if args.list_presets:
        from paddle_tpu.models.llama import PRESETS
        print("\n".join(sorted(PRESETS)))
        return 0

    if args.mode == "serving":
        report = build_serving_report(args)
        payload = json.dumps(report, indent=2, sort_keys=False)
        if args.out == "-":
            print(payload)
        else:
            with open(args.out, "w") as f:
                f.write(payload + "\n")
            print(f"wrote {args.out}", file=sys.stderr)
        if args.ledger:
            _ledger_append(repo_root, args.ledger, report)
        return 0

    report = build_report(args)
    if args.plan_out:
        write_plan_spec(report, args.preset, args.plan_out)
    if args.ledger:
        _ledger_append(repo_root, args.ledger, report)
    payload = json.dumps(report, indent=2, sort_keys=False)
    if args.out == "-":
        print(payload)
    else:
        with open(args.out, "w") as f:
            f.write(payload + "\n")
        print(f"wrote {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
