#!/usr/bin/env python
"""nan_hunt: offline first-bad-op localization for a saved repro.

Takes a pickled repro payload (typically dumped from a failing run),
re-runs the function under ``profiler.numerics.localize`` — which
re-interprets the jaxpr equation by equation — and reports the FIRST
primitive whose output goes non-finite while its inputs were still
finite, with the user source file:line that emitted it.

    python tools/nan_hunt.py --repro failing_step.pkl
    python tools/nan_hunt.py --repro failing_step.pkl --out report.json

The payload is a dict with:

    fn      dotted import path "pkg.module:callable" of the function
            to hunt, OR
    src     python source text defining it, with
    entry   the callable's name inside ``src``
    args    list of arrays / array-likes (positional inputs)
    kwargs  optional dict of keyword inputs

Exit status: 0 = everything finite, 2 = non-finite found (JSON report
on stdout / --out), 1 = bad payload or usage error.
"""
from __future__ import annotations

import argparse
import importlib
import json
import os
import pickle
import sys


def _parse_args(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--repro", required=True,
                    help="pickled payload: {fn|src+entry, args, kwargs}")
    ap.add_argument("--out", default="-",
                    help="output path for the JSON report (- = stdout)")
    return ap.parse_args(argv)


def _load_fn(payload):
    if "fn" in payload:
        spec = payload["fn"]
        if ":" in spec:
            mod_name, attr = spec.split(":", 1)
        else:
            mod_name, attr = spec.rsplit(".", 1)
        fn = importlib.import_module(mod_name)
        for part in attr.split("."):
            fn = getattr(fn, part)
        return fn
    if "src" in payload:
        entry = payload.get("entry")
        if not entry:
            raise SystemExit("payload with 'src' must also name 'entry'")
        ns: dict = {}
        exec(compile(payload["src"], "<nan_hunt repro>", "exec"), ns)
        if entry not in ns:
            raise SystemExit(f"entry {entry!r} not defined by payload src")
        return ns[entry]
    raise SystemExit("payload must carry 'fn' (import path) or "
                     "'src' + 'entry'")


def main(argv=None):
    ns = _parse_args(argv)
    try:
        with open(ns.repro, "rb") as f:
            payload = pickle.load(f)
    except (OSError, pickle.UnpicklingError) as e:
        raise SystemExit(f"cannot load repro {ns.repro!r}: {e}")
    if not isinstance(payload, dict):
        raise SystemExit("repro payload must be a dict")

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    fn = _load_fn(payload)
    args = payload.get("args", [])
    kwargs = payload.get("kwargs", {}) or {}

    from paddle_tpu.profiler import numerics

    report = numerics.localize(fn, *args, **kwargs)
    doc = {"repro": ns.repro, "finite": report is None, "report": report}
    text = json.dumps(doc, indent=2, default=str)
    if ns.out == "-":
        print(text)
    else:
        with open(ns.out, "w") as f:
            f.write(text + "\n")
        print(f"report written to {ns.out}")
    if report is not None:
        where = report.get("where") or "?"
        print(f"FIRST BAD OP: {report.get('primitive')} at {where}",
              file=sys.stderr)
        return 2
    print("all outputs finite", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
