#!/usr/bin/env python
"""tpu_lint: static-analysis CLI for paddle_tpu (AST rule family).

Runs the paddle_tpu.analysis AST checks over Python sources and compares
against the checked-in baseline (tools/tpu_lint_baseline.json) so new
violations fail while the known backlog is tracked, not silenced.

Usage:
    python tools/tpu_lint.py paddle_tpu/                # lint vs baseline
    python tools/tpu_lint.py paddle_tpu/ --baseline-update
    python tools/tpu_lint.py some_file.py --no-baseline
    python tools/tpu_lint.py paddle_tpu/ --rules except-pass
    python tools/tpu_lint.py paddle_tpu/ --kernels      # + Level-3 sweep
    python tools/tpu_lint.py paddle_tpu/ --format=github

Output: a JSON document on stdout — every finding carries severity,
rule id, and file:line (``--format=github`` emits ::error/::warning
workflow annotations instead). Exit codes: 0 clean against the
baseline, 1 new warning-level findings, 2 new error-level findings.

``--kernels`` additionally runs the Level-3 kernel verifier over the
registered kernel library (ops/pallas_ops.py) and over any given .py
path exposing a ``kernel_verify_cases()`` hook. This is the one mode
that imports jax (kernels are traced, never executed — CPU is enough).

The jaxpr rule family runs at trace time instead — enable it with
``to_static(..., lint=True)`` or ``FLAGS_tpu_lint=1`` (see
docs/static_analysis.md). Without ``--kernels`` this CLI stays
jax-free so it starts in milliseconds: the analysis package is loaded
standalone.
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(REPO_ROOT, "tools",
                                "tpu_lint_baseline.json")


def _load_analysis():
    """Load paddle_tpu.analysis WITHOUT importing paddle_tpu (or jax):
    the AST rules are stdlib-only, and a lint CLI should start fast."""
    pkg_dir = os.path.join(REPO_ROOT, "paddle_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        "tpu_lint_analysis", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["tpu_lint_analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu_lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="+",
                    help="files or directories to lint")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: {DEFAULT_BASELINE} "
                         "when it exists)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline: every finding is new")
    ap.add_argument("--baseline-update", action="store_true",
                    help="regenerate the baseline from current findings "
                         "(deterministic: sorted, repo-relative paths) "
                         "and exit 0")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--root", default=REPO_ROOT,
                    help="root for baseline-relative paths "
                         "(default: the repo root)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    ap.add_argument("--kernels", action="store_true",
                    help="also run the Level-3 kernel verifier: the "
                         "registered kernel library plus any given .py "
                         "path exposing a kernel_verify_cases() hook "
                         "(imports jax; kernels are traced on CPU, "
                         "never executed)")
    ap.add_argument("--format", choices=("json", "github"),
                    default="json",
                    help="output format: the JSON document (default) or "
                         "GitHub workflow ::error/::warning annotations "
                         "for the NEW findings")
    args = ap.parse_args(argv)

    analysis = _load_analysis()

    if args.list_rules:
        catalogue = {rid: {"severity": sev, "doc": doc, "level": "ast"}
                     for rid, (sev, doc) in analysis.AST_RULES.items()}
        catalogue.update(
            {rid: {"severity": sev, "doc": doc, "level": "jaxpr"}
             for rid, (sev, fn, doc) in analysis.JAXPR_RULES.items()})
        catalogue.update(
            {rid: {"severity": sev, "doc": doc, "level": "spmd"}
             for rid, (sev, doc) in analysis.SPMD_RULES.items()})
        catalogue.update(
            {rid: {"severity": sev, "doc": doc, "level": "kernel"}
             for rid, (sev, doc) in analysis.KERNEL_RULES.items()})
        print(json.dumps(catalogue, indent=2, sort_keys=True))
        return 0

    rules = [r.strip() for r in args.rules.split(",")] if args.rules \
        else None
    findings = list(analysis.check_paths(args.paths, rules=rules))

    kernel_cases = 0
    if args.kernels:
        # the one jax-paying mode: repo root on sys.path so the real
        # paddle_tpu package (and its kernel registry) is importable
        if REPO_ROOT not in sys.path:
            sys.path.insert(0, REPO_ROOT)
        kc = analysis.kernel_checks
        findings.extend(kc.verify_registered(rules=rules))
        kernel_cases = len(kc.registered_cases())
        for p in args.paths:
            if p.endswith(".py") and os.path.isfile(p):
                fs, n = kc.verify_module(p, rules=rules)
                findings.extend(fs)
                kernel_cases += n

    baseline_path = args.baseline or (
        DEFAULT_BASELINE if os.path.isfile(DEFAULT_BASELINE) else None)

    if args.baseline_update:
        path = args.baseline or DEFAULT_BASELINE
        analysis.core.write_baseline(path, findings, args.root)
        print(json.dumps({"baseline": path, "entries": len(findings),
                          "updated": True}, indent=2))
        return 0

    if args.no_baseline or baseline_path is None:
        new, fixed = findings, []
        baseline_path = None
    else:
        baseline = analysis.core.load_baseline(baseline_path)
        new, fixed = analysis.core.diff_baseline(findings, baseline,
                                                 args.root)

    counts: dict = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    new_errors = [f for f in new if f.severity == "error"]
    doc = {
        "tool": "tpu_lint",
        "paths": args.paths,
        "baseline": baseline_path,
        "total_findings": len(findings),
        "counts": dict(sorted(counts.items())),
        "new": [f.to_dict() for f in new],
        "fixed": fixed,
        "ok": not new,
    }
    if args.kernels:
        doc["kernel_cases"] = kernel_cases
    if args.format == "github":
        for line in _github_annotations(new, fixed, args.root):
            print(line)
    else:
        print(json.dumps(doc, indent=2))
    if new_errors:
        return 2
    if new:
        return 1
    return 0


def _gh_escape(s: str, data: bool = True) -> str:
    """GitHub workflow-command escaping: %, CR, LF always; , and : only
    in property values."""
    s = s.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")
    if not data:
        s = s.replace(",", "%2C").replace(":", "%3A")
    return s


def _github_annotations(new, fixed, root):
    """``::error file=...,line=...::[rule] message`` lines for the NEW
    findings (what a CI run should flag on the PR), plus one summary
    ::notice."""
    lines = []
    for f in new:
        level = "error" if f.severity == "error" else "warning"
        props = []
        if f.file:
            path = f.file
            try:
                rel = os.path.relpath(path, root)
                if not rel.startswith(".."):
                    path = rel
            except ValueError:
                pass
            props.append("file=" + _gh_escape(path, data=False))
            if f.line:
                props.append(f"line={int(f.line)}")
        head = f"::{level} " + ",".join(props) if props else f"::{level}"
        lines.append(f"{head}::" + _gh_escape(f"[{f.rule}] {f.message}"))
    lines.append("::notice::" + _gh_escape(
        f"tpu_lint: {len(new)} new finding(s), {len(fixed)} fixed "
        "vs baseline"))
    return lines


if __name__ == "__main__":
    sys.exit(main())
