#!/usr/bin/env python
"""tpu_lint: static-analysis CLI for paddle_tpu (AST rule family).

Runs the paddle_tpu.analysis AST checks over Python sources and compares
against the checked-in baseline (tools/tpu_lint_baseline.json) so new
violations fail while the known backlog is tracked, not silenced.

Usage:
    python tools/tpu_lint.py paddle_tpu/                # lint vs baseline
    python tools/tpu_lint.py paddle_tpu/ --baseline-update
    python tools/tpu_lint.py some_file.py --no-baseline
    python tools/tpu_lint.py paddle_tpu/ --rules except-pass

Output: a JSON document on stdout — every finding carries severity,
rule id, and file:line. Exit codes: 0 clean against the baseline,
1 new warning-level findings, 2 new error-level findings.

The jaxpr rule family runs at trace time instead — enable it with
``to_static(..., lint=True)`` or ``FLAGS_tpu_lint=1`` (see
docs/static_analysis.md). This CLI stays jax-free so it starts in
milliseconds: the analysis package is loaded standalone.
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(REPO_ROOT, "tools",
                                "tpu_lint_baseline.json")


def _load_analysis():
    """Load paddle_tpu.analysis WITHOUT importing paddle_tpu (or jax):
    the AST rules are stdlib-only, and a lint CLI should start fast."""
    pkg_dir = os.path.join(REPO_ROOT, "paddle_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        "tpu_lint_analysis", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["tpu_lint_analysis"] = mod
    spec.loader.exec_module(mod)
    return mod


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu_lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="+",
                    help="files or directories to lint")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: {DEFAULT_BASELINE} "
                         "when it exists)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore any baseline: every finding is new")
    ap.add_argument("--baseline-update", action="store_true",
                    help="regenerate the baseline from current findings "
                         "(deterministic: sorted, repo-relative paths) "
                         "and exit 0")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--root", default=REPO_ROOT,
                    help="root for baseline-relative paths "
                         "(default: the repo root)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    args = ap.parse_args(argv)

    analysis = _load_analysis()

    if args.list_rules:
        catalogue = {rid: {"severity": sev, "doc": doc, "level": "ast"}
                     for rid, (sev, doc) in analysis.AST_RULES.items()}
        catalogue.update(
            {rid: {"severity": sev, "doc": doc, "level": "jaxpr"}
             for rid, (sev, fn, doc) in analysis.JAXPR_RULES.items()})
        print(json.dumps(catalogue, indent=2, sort_keys=True))
        return 0

    rules = [r.strip() for r in args.rules.split(",")] if args.rules \
        else None
    findings = analysis.check_paths(args.paths, rules=rules)

    baseline_path = args.baseline or (
        DEFAULT_BASELINE if os.path.isfile(DEFAULT_BASELINE) else None)

    if args.baseline_update:
        path = args.baseline or DEFAULT_BASELINE
        analysis.core.write_baseline(path, findings, args.root)
        print(json.dumps({"baseline": path, "entries": len(findings),
                          "updated": True}, indent=2))
        return 0

    if args.no_baseline or baseline_path is None:
        new, fixed = findings, []
        baseline_path = None
    else:
        baseline = analysis.core.load_baseline(baseline_path)
        new, fixed = analysis.core.diff_baseline(findings, baseline,
                                                 args.root)

    counts: dict = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    new_errors = [f for f in new if f.severity == "error"]
    doc = {
        "tool": "tpu_lint",
        "paths": args.paths,
        "baseline": baseline_path,
        "total_findings": len(findings),
        "counts": dict(sorted(counts.items())),
        "new": [f.to_dict() for f in new],
        "fixed": fixed,
        "ok": not new,
    }
    print(json.dumps(doc, indent=2))
    if new_errors:
        return 2
    if new:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
