"""Shared XLA_FLAGS composition for virtual-CPU-mesh entry points.

stdlib-only and importable BEFORE jax (XLA reads the env at backend
init). Single source for the collective-watchdog timeouts: the CPU
in-process collective rendezvous ABORTS the process ("Termination
timeout ... Expected N threads to join") when virtual-device threads
are slow to arrive — which on an oversubscribed CI host is load, not
deadlock. That abort was round 3's flagship-example SIGABRT.

The watchdog flags do not exist in every jaxlib, and XLA fatally
aborts the process on *unknown* XLA_FLAGS — the cure must not be
worse than the disease. So before injecting them we scan the
installed jaxlib's xla_extension shared object for the flag name:
the registered flag string is embedded in the binary iff the flag is
parseable. The verdict is cached in the environment so subprocesses
(and re-imports) skip the scan.
"""
from __future__ import annotations

import os

_TIMEOUT_FLAGS = (
    " --xla_cpu_collective_call_warn_stuck_timeout_seconds=300"
    " --xla_cpu_collective_call_terminate_timeout_seconds=1200")

_PROBE_CACHE_VAR = "PADDLE_TPU_XLA_WATCHDOG_FLAGS_OK"
_PROBE_NEEDLE = b"xla_cpu_collective_call_terminate_timeout_seconds"


def _watchdog_flags_supported() -> bool:
    cached = os.environ.get(_PROBE_CACHE_VAR)
    if cached in ("0", "1"):
        return cached == "1"
    ok = False
    try:
        import importlib.util
        import mmap

        spec = importlib.util.find_spec("jaxlib")
        so = os.path.join(os.path.dirname(spec.origin), "xla_extension.so")
        with open(so, "rb") as f:
            mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
            try:
                ok = mm.find(_PROBE_NEEDLE) != -1
            finally:
                mm.close()
    except Exception:
        # Can't find/scan the binary (different layout, no jaxlib):
        # don't risk an unknown-flag abort.
        ok = False
    os.environ[_PROBE_CACHE_VAR] = "1" if ok else "0"
    return ok


def ensure(device_count: int | None = None) -> None:
    """Idempotently add the watchdog timeouts (and optionally the
    virtual device count) to XLA_FLAGS. Call before importing jax."""
    flags = os.environ.get("XLA_FLAGS", "")
    if device_count and "host_platform_device_count" not in flags:
        flags += f" --xla_force_host_platform_device_count={device_count}"
    if ("collective_call_terminate_timeout" not in flags
            and _watchdog_flags_supported()):
        flags += _TIMEOUT_FLAGS
    os.environ["XLA_FLAGS"] = flags.strip()
