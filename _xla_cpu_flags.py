"""Shared XLA_FLAGS composition for virtual-CPU-mesh entry points.

stdlib-only and importable BEFORE jax (XLA reads the env at backend
init). Single source for the collective-watchdog timeouts: the CPU
in-process collective rendezvous ABORTS the process ("Termination
timeout ... Expected N threads to join") when virtual-device threads
are slow to arrive — which on an oversubscribed CI host is load, not
deadlock. That abort was round 3's flagship-example SIGABRT.
"""
from __future__ import annotations

import os

_TIMEOUT_FLAGS = (
    " --xla_cpu_collective_call_warn_stuck_timeout_seconds=300"
    " --xla_cpu_collective_call_terminate_timeout_seconds=1200")


def ensure(device_count: int | None = None) -> None:
    """Idempotently add the watchdog timeouts (and optionally the
    virtual device count) to XLA_FLAGS. Call before importing jax."""
    flags = os.environ.get("XLA_FLAGS", "")
    if device_count and "host_platform_device_count" not in flags:
        flags += f" --xla_force_host_platform_device_count={device_count}"
    if "collective_call_terminate_timeout" not in flags:
        flags += _TIMEOUT_FLAGS
    os.environ["XLA_FLAGS"] = flags.strip()
