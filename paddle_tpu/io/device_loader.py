"""Host→device staging with overlap: the host-pinned-buffer analog.

Reference analog: the buffered reader behind DataLoader's
``use_buffer_reader=True`` (python/paddle/fluid/reader.py:391 — batches are
staged into pinned host memory and copied to the device ahead of
consumption) and the `places` argument that pins loader output to a
device.

TPU-native shape: there is no user-managed pinned memory under PJRT — the
equivalent of "pin + async H2D" is ``jax.device_put``, whose transfer is
dispatched asynchronously and runs the DMA off the python thread. Staging
``buffer_size`` batches ahead therefore overlaps host collate + H2D copy
of batch N+1 with device compute on batch N, which is exactly the pinned
double-buffering the reference implements in C++
(paddle/fluid/operators/reader/buffered_reader.cc).
"""
from __future__ import annotations

from collections import deque
from typing import Iterable, Optional

import jax

__all__ = ["DeviceDataLoader", "stage_to_device"]


def _resolve_device(place):
    if place is None:
        return None
    if isinstance(place, jax.Device):
        return place
    if hasattr(place, "device"):  # core.place.Place
        return place.device()
    raise TypeError(f"cannot resolve device from {place!r}")


def stage_to_device(batch, device=None):
    """device_put every array leaf of a batch (Tensor facades rewrapped),
    preserving structure. Dispatch is async: returns immediately."""
    from ..core.tensor import Tensor

    def stage(leaf):
        if isinstance(leaf, Tensor):
            return Tensor(jax.device_put(leaf._array, device))
        if hasattr(leaf, "shape") or hasattr(leaf, "__array__"):
            return jax.device_put(leaf, device)
        return leaf

    if isinstance(batch, tuple) and hasattr(batch, "_fields"):
        return type(batch)(*(stage_to_device(b, device) for b in batch))
    if isinstance(batch, (list, tuple)):
        return type(batch)(stage_to_device(b, device) for b in batch)
    if isinstance(batch, dict):
        return {k: stage_to_device(v, device) for k, v in batch.items()}
    return stage(batch)


class DeviceDataLoader:
    """Wraps any batch iterable; yields batches already resident (or in
    flight) on ``place``, keeping ``buffer_size`` batches dispatched
    ahead of the consumer."""

    def __init__(self, loader: Iterable, place=None, buffer_size: int = 2):
        if buffer_size < 1:
            raise ValueError("buffer_size must be >= 1")
        self._loader = loader
        self._device = _resolve_device(place)
        self._buffer_size = buffer_size

    def __len__(self):
        return len(self._loader)

    def __iter__(self):
        buf: deque = deque()
        for batch in self._loader:
            buf.append(stage_to_device(batch, self._device))
            if len(buf) > self._buffer_size:
                yield buf.popleft()
        while buf:
            yield buf.popleft()
