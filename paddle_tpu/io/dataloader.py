"""DataLoader.

Reference analog: python/paddle/fluid/reader.py:311 (DataLoader) +
dataloader_iter.py:162/:370 (single/multi-process iterators with worker
processes and shared-memory LoDTensor transport over a C++ blocking queue).

TPU-native: workers are multiprocessing processes producing numpy batches
into an mp.Queue (kernel shared memory transport); a prefetch thread keeps
`prefetch_factor` batches decoded ahead. Batches convert to Tensors on
yield; XLA transfers them on first use.
"""
from __future__ import annotations

import itertools
import queue as queue_mod
import threading
import multiprocessing as mp
from typing import Callable, Optional

import numpy as np

from ..core.tensor import Tensor, to_tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

_worker_info = threading.local()


class WorkerInfo:
    def __init__(self, id, num_workers, dataset, seed):  # noqa: A002
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


def get_worker_info():
    return getattr(_worker_info, "info", None)


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (np.ndarray, np.generic)):
        return np.stack(batch)
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s._array) for s in batch])
    if isinstance(sample, (int, float)):
        return np.asarray(batch)
    if isinstance(sample, (str, bytes)):
        return batch
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return type(sample)(default_collate_fn(list(t)) for t in transposed)
    return batch


def _to_tensor_tree(data):
    if isinstance(data, np.ndarray):
        return to_tensor(data)
    if isinstance(data, dict):
        return {k: _to_tensor_tree(v) for k, v in data.items()}
    if isinstance(data, (list, tuple)):
        return type(data)(_to_tensor_tree(v) for v in data)
    return data


def _worker_loop(dataset, index_queue, data_queue, collate_fn, worker_id,
                 num_workers, base_seed, init_fn=None, shm_cfg=None):
    _worker_info.info = WorkerInfo(worker_id, num_workers, dataset,
                                   base_seed + worker_id)
    np.random.seed(base_seed + worker_id)
    if init_fn is not None:
        init_fn(worker_id)
    shm = None
    slot_bytes = 0
    if shm_cfg is not None:
        from ..core.native import ShmQueue
        name, slot_bytes, n_slots = shm_cfg
        try:
            shm = ShmQueue(name, n_slots=n_slots, slot_bytes=slot_bytes,
                           owner=False)
        except Exception:
            shm = None

    def emit(payload):
        # native shm ring when attached; batches bigger than a slot take
        # the mp.Queue path behind a marker so pop order stays defined
        if shm is not None:
            import pickle
            raw = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
            if len(raw) <= slot_bytes:
                shm.put(raw)
                return
            shm.put(pickle.dumps(("__big__", payload[0])))
        data_queue.put(payload)

    while True:
        item = index_queue.get()
        if item is None:
            break
        batch_id, indices = item
        try:
            samples = [dataset[i] for i in indices]
            emit((batch_id, collate_fn(samples), None))
        except Exception as e:  # propagate worker errors
            emit((batch_id, None, e))


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.return_list = return_list
        # `places` pins output batches to a device; with use_buffer_reader
        # the transfer double-buffers ahead of the consumer (the pinned
        # buffered_reader analog — see io/device_loader.py)
        self.places = places if isinstance(places, (list, tuple, type(None))) \
            else [places]
        self.use_buffer_reader = use_buffer_reader
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.use_shared_memory = use_shared_memory
        self._is_iterable = isinstance(dataset, IterableDataset)
        # sample-exact resume bookkeeping: the sampler state at epoch
        # start plus a consumer-side yield count (see state_dict)
        self._active_state = None
        self._yielded = 0
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
        elif not self._is_iterable:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)
            self.batch_size = batch_size
        else:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last

    def __len__(self):
        if self._is_iterable:
            raise TypeError("IterableDataset has no fixed length")
        return len(self.batch_sampler)

    def __iter__(self):
        if self.batch_sampler is not None and \
                hasattr(self.batch_sampler, "state_dict"):
            # snapshot BEFORE any dispatch: _iter_multi materializes the
            # whole sampler upfront for its prefetch workers, which runs
            # the sampler's own cursor to epoch end immediately
            self._active_state = dict(self.batch_sampler.state_dict())
            self._yielded = 0
        if self._is_iterable:
            it = self._iter_iterable()
        elif self.num_workers == 0:
            it = self._iter_single()
        else:
            it = self._iter_multi()
        if self.places:
            if len(self.places) > 1:
                raise ValueError(
                    "multi-place DataLoader output is not supported: one "
                    "jax client owns all local chips, so in-host data "
                    "parallelism is expressed by sharding the batch over "
                    "a mesh (device_put with a distributed.NamedSharding "
                    "over the 'dp' axis), not by per-place feeding")
            from .device_loader import DeviceDataLoader
            buf = self.prefetch_factor if self.use_buffer_reader else 1
            it = iter(DeviceDataLoader(it, self.places[0], buffer_size=buf))
        return self._instrumented(it)

    def _instrumented(self, it):
        """Telemetry around next-batch: a host span when a profiler is
        live, and fetch-latency histogram + batch counter when
        FLAGS_tpu_metrics is on. Fetch time here is consumer-side stall
        — with prefetch ahead of the consumer it should stay near zero;
        a hot dataloader_next_seconds histogram means input-bound.

        Also the resume cursor's counting point: a batch counts as
        consumed the moment it is handed to the consumer (who will train
        on it before checkpointing), NOT when a prefetch worker decodes
        it — so ``state_dict`` stays exact however far prefetch ran
        ahead."""
        import time as _time
        from ..profiler import _record_span, metrics as _metrics
        try:
            while True:
                rec = _metrics.enabled()
                t0 = _time.perf_counter() if rec else None
                try:
                    with _record_span("dataloader_next"):
                        batch = next(it)
                except StopIteration:
                    self._active_state = None  # epoch drained cleanly
                    return
                if rec:
                    _metrics.counter("dataloader_batches_total",
                                     "Batches yielded by DataLoader").inc()
                    _metrics.histogram(
                        "dataloader_next_seconds",
                        "Consumer-side wait per batch").observe(
                            _time.perf_counter() - t0)
                self._yielded += 1
                yield batch
        finally:
            # an early consumer break must tear down worker processes
            # now, not at GC time (the inner generator's finally owns
            # the worker/shm cleanup)
            close = getattr(it, "close", None)
            if close is not None:
                close()

    # -- sample-exact resume ------------------------------------------------
    def state_dict(self) -> dict:
        """The resume cursor (epoch + consumed GLOBAL sample offset +
        shuffle RNG derivation), exact mid-epoch: the sampler state
        snapshotted at epoch start advanced by the batches actually
        handed to the consumer. Requires a batch_sampler with
        ``state_dict`` (DistributedBatchSampler); CheckpointManager
        embeds this in every commit manifest via ``attach_data`` and
        replays it on restore."""
        bs = self.batch_sampler
        if bs is None or not hasattr(bs, "state_dict"):
            raise TypeError(
                "DataLoader.state_dict needs a batch_sampler exposing "
                "state_dict/load_state_dict (io.DistributedBatchSampler); "
                f"got {type(bs).__name__}")
        if self._active_state is None:
            return dict(bs.state_dict())
        st = dict(self._active_state)
        gbs = int(st.get("global_batch_size",
                         getattr(bs, "global_batch_size", self.batch_size)))
        st["offset"] = int(st.get("offset", 0)) + self._yielded * gbs
        return st

    def load_state_dict(self, state: dict):
        """Resume the underlying sampler from a cursor — valid across an
        elastic dp resize because offsets are in global sample order."""
        bs = self.batch_sampler
        if bs is None or not hasattr(bs, "load_state_dict"):
            raise TypeError(
                "DataLoader.load_state_dict needs a batch_sampler exposing "
                "state_dict/load_state_dict (io.DistributedBatchSampler); "
                f"got {type(bs).__name__}")
        bs.load_state_dict(dict(state))
        self._active_state = None
        self._yielded = 0

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield _to_tensor_tree(self.collate_fn(batch))
                batch = []
        if batch and not getattr(self, "drop_last", False):
            yield _to_tensor_tree(self.collate_fn(batch))

    def _iter_single(self):
        for indices in self.batch_sampler:
            samples = [self.dataset[i] for i in indices]
            yield _to_tensor_tree(self.collate_fn(samples))

    def _start_context(self):
        """Pick the worker start method (cached after the first call —
        picklability of the payload cannot change between epochs).

        spawn by default: the parent holds a live multithreaded XLA/PJRT
        client, and forking it risks the TSL "Expected N threads to join"
        abort at shutdown (reference analog keeps fork because its C++
        runtime is fork-aware; ours is not). NOTE: spawn re-imports
        __main__ in each worker, so scripts that iterate a
        num_workers>0 DataLoader at module top level need the standard
        ``if __name__ == "__main__"`` guard. Fork remains a fallback for
        datasets/collate_fns that cannot pickle (e.g. defined in a local
        scope), with a warning.
        """
        if getattr(self, "_mp_ctx", None) is not None:
            return self._mp_ctx
        import os
        import pickle
        import sys
        import warnings

        class _NullWriter:
            def write(self, _):
                pass  # probe picklability without materializing bytes

        reason = None
        # spawn re-executes __main__: piped/stdin scripts have no real
        # file to re-run and every worker would die at startup
        main_file = getattr(sys.modules.get("__main__"), "__file__", None)
        if main_file is not None and not os.path.exists(main_file):
            reason = (f"__main__ has no importable file ({main_file!r}; "
                      "stdin/exec script)")
        if reason is None:
            try:
                pickle.Pickler(_NullWriter(), pickle.HIGHEST_PROTOCOL).dump(
                    (self.dataset, self.collate_fn, self.worker_init_fn))
            except Exception:
                reason = "worker payload is not picklable"
        if reason is None:
            self._mp_ctx = mp.get_context("spawn")
        else:
            warnings.warn(
                f"DataLoader: {reason}; falling back to fork workers. "
                "Forking a process with a live JAX client can deadlock or "
                "abort at shutdown — run from a real script file with the "
                "dataset/collate_fn at module scope to enable spawn "
                "workers.", RuntimeWarning, stacklevel=3)
            self._mp_ctx = mp.get_context("fork")
        return self._mp_ctx

    @staticmethod
    def _worker_child_env():
        """Env overrides for worker children: workers only produce numpy
        batches, so they must never initialize a TPU backend — strip the
        axon tunnel registration (sitecustomize re-runs in spawned
        children and can hang when the tunnel is down) and pin jax to
        cpu in case anything imports it."""
        return {"PALLAS_AXON_POOL_IPS": None, "AXON_POOL_SVC_OVERRIDE": None,
                "JAX_PLATFORMS": "cpu"}

    def _iter_multi(self):
        import os as _os
        ctx = self._start_context()
        index_queues = []
        data_queue = ctx.Queue()
        workers = []
        base_seed = np.random.randint(0, 2 ** 31 - 1)

        # native shared-memory transport (reference: C++ blocking_queue +
        # shared-mem tensor transport) when built; mp.Queue otherwise
        shm = None
        shm_cfg = None
        if self.use_shared_memory:
            from ..core import native
            if native.available():
                import os as _os
                name = f"/ptq_dl_{_os.getpid()}_{id(self) & 0xffffff}"
                slot_bytes = 32 << 20
                n_slots = max(4, self.num_workers * self.prefetch_factor)
                try:
                    shm = native.ShmQueue(name, n_slots=n_slots,
                                          slot_bytes=slot_bytes, owner=True)
                    shm_cfg = (name, slot_bytes, n_slots)
                except Exception:
                    shm = None

        # apply child-env overrides around start(): both fork and spawn
        # children inherit os.environ as of start() time. Snapshot the
        # environment ONCE before the loop (a per-key environ.get is an
        # env lookup per iteration).
        env_before = dict(_os.environ)
        saved_env = {}
        for k, v in self._worker_child_env().items():
            saved_env[k] = env_before.get(k)
            if v is None:
                _os.environ.pop(k, None)
            else:
                _os.environ[k] = v
        try:
            for wid in range(self.num_workers):
                iq = ctx.Queue()
                w = ctx.Process(
                    target=_worker_loop,
                    args=(self.dataset, iq, data_queue, self.collate_fn, wid,
                          self.num_workers, base_seed, self.worker_init_fn,
                          shm_cfg),
                    daemon=True)
                w.start()
                workers.append(w)
                index_queues.append(iq)
        finally:
            for k, old in saved_env.items():
                if old is None:
                    _os.environ.pop(k, None)
                else:
                    _os.environ[k] = old

        def recv():
            # Poll with short sleeps instead of blocking indefinitely in
            # the transport: a worker that died (bad __main__ under
            # spawn, OOM-killed, segfault) must surface as an error, not
            # an eternal hang on an empty queue. Reads next_yield/
            # next_dispatch/reorder from the enclosing scope to decide
            # whether a dead worker actually stalls the pipeline.
            import time
            deadline = (time.monotonic() + self.timeout) if self.timeout \
                else None
            wait = 1e-4
            want_big = None  # batch id promised on data_queue via marker
            while True:
                if shm is None or want_big is not None:
                    try:
                        return data_queue.get(timeout=0.2)
                    except queue_mod.Empty:
                        pass
                elif shm.qsize() > 0:
                    import pickle
                    payload = pickle.loads(shm.get())
                    if isinstance(payload, tuple) and len(payload) == 2 \
                            and payload[0] == "__big__":
                        want_big = payload[1]
                        continue
                    return payload
                dead = {i for i, w in enumerate(workers)
                        if not w.is_alive()}
                if dead:
                    # stall = some batch we still need is owned by a dead
                    # worker (round-robin: batch i -> worker i % N); an
                    # idle worker dying after finishing its share must
                    # not abort an epoch the others can complete
                    if want_big is not None:
                        stalled = (want_big % self.num_workers) in dead
                    else:
                        stalled = any(
                            (i % self.num_workers) in dead
                            for i in range(next_yield, next_dispatch)
                            if i not in reorder)
                    if stalled and (shm is None or shm.qsize() == 0):
                        # grace drain: the dying worker may have flushed
                        # its batch into the pipe first. A large batch
                        # (or a loaded host) can take several seconds to
                        # land, so drain over a window — a single 1s get
                        # aborted recoverable epochs. The user's timeout
                        # stays authoritative: the window never extends
                        # past `deadline`.
                        grace_end = time.monotonic() + min(
                            self.timeout or 5.0, 10.0)
                        if deadline is not None:
                            grace_end = min(grace_end, deadline)
                        while True:
                            try:
                                return data_queue.get(timeout=0.5)
                            except queue_mod.Empty:
                                if time.monotonic() < grace_end:
                                    continue
                                if deadline is not None and \
                                        time.monotonic() > deadline:
                                    raise TimeoutError(
                                        f"DataLoader timed out after "
                                        f"{self.timeout}s waiting for a "
                                        "worker batch (worker(s) "
                                        f"{sorted(dead)} dead)") from None
                                dw = [workers[i] for i in sorted(dead)]
                                raise RuntimeError(
                                    "DataLoader worker(s) "
                                    f"{[w.pid for w in dw]} exited "
                                    "unexpectedly (exitcodes "
                                    f"{[w.exitcode for w in dw]}) "
                                    "with batches still pending") from None
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(
                        f"DataLoader timed out after {self.timeout}s "
                        "waiting for a worker batch")
                if shm is not None and want_big is None:
                    time.sleep(wait)
                    wait = min(wait * 2, 0.005)

        try:
            batches = list(self.batch_sampler)
            # dispatch round-robin with bounded in-flight count
            inflight = 0
            next_dispatch = 0
            reorder = {}
            next_yield = 0
            max_inflight = self.num_workers * self.prefetch_factor
            while next_yield < len(batches):
                while next_dispatch < len(batches) and inflight < max_inflight:
                    index_queues[next_dispatch % self.num_workers].put(
                        (next_dispatch, batches[next_dispatch]))
                    next_dispatch += 1
                    inflight += 1
                bid, data, err = recv()
                if err is not None:
                    raise err
                inflight -= 1
                reorder[bid] = data
                while next_yield in reorder:
                    yield _to_tensor_tree(reorder.pop(next_yield))
                    next_yield += 1
        finally:
            for iq in index_queues:
                iq.put(None)
            for w in workers:
                w.join(timeout=1)
                if w.is_alive():
                    w.terminate()
            if shm is not None:
                shm.close()
                shm.free()


def default_convert_fn(batch):
    """Convert without batching — the DataLoader's collate when
    batch_size=None (reference: fluid/dataloader/collate.py
    default_convert_fn)."""
    import numpy as _np
    from ..core.tensor import Tensor as _T
    if isinstance(batch, _T):
        return batch
    if isinstance(batch, _np.ndarray):
        return _T(jnp.asarray(batch))
    if isinstance(batch, (list, tuple)):
        return type(batch)(default_convert_fn(b) for b in batch)
    if isinstance(batch, dict):
        return {k: default_convert_fn(v) for k, v in batch.items()}
    return batch
