"""Data pipeline.

Reference analog: paddle.io (python/paddle/fluid/reader.py:311 DataLoader,
fluid/dataloader/: Dataset/IterableDataset/BatchSampler/worker processes +
shared-memory transport over a C++ blocking queue in operators/reader/).

TPU-native: the multiprocess worker pool feeds a prefetch queue of numpy
batches; `DataLoader(..., return_list=True)` yields Tensors. Device
transfer happens lazily on first op (jax.device_put under the hood), and
double-buffering to the chip is handled by the trainer utilities
(hapi.Model / distributed shard loaders) rather than per-loader threads.
"""
from .dataset import (Dataset, IterableDataset, TensorDataset, ComposeDataset,
                      ChainDataset, Subset, ConcatDataset, random_split)
from .sampler import (Sampler, SequenceSampler, RandomSampler,
                      WeightedRandomSampler, BatchSampler,
                      DistributedBatchSampler, SubsetRandomSampler)
from .dataloader import DataLoader, default_collate_fn, get_worker_info
from .device_loader import DeviceDataLoader, stage_to_device

__all__ = ["DeviceDataLoader", "stage_to_device",
           "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
           "ChainDataset", "Subset", "ConcatDataset", "random_split",
           "Sampler", "SequenceSampler", "RandomSampler",
           "WeightedRandomSampler", "BatchSampler",
           "DistributedBatchSampler", "SubsetRandomSampler", "DataLoader",
           "default_collate_fn", "get_worker_info"]
