"""Samplers (reference: python/paddle/fluid/dataloader/batch_sampler.py,
sampler.py; DistributedBatchSampler from distributed/fleet/utils)."""
from __future__ import annotations

import math

import numpy as np


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    def __init__(self, indices):
        super().__init__(None)
        self.indices = list(indices)

    def __iter__(self):
        return iter(np.random.permutation(self.indices).tolist())

    def __len__(self):
        return len(self.indices)


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        super().__init__(None)
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        super().__init__(dataset)
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Per-rank sharded batches (reference:
    python/paddle/fluid/dataloader/batch_sampler.py:DistributedBatchSampler).
    On TPU a "rank" is a data-parallel host process (jax.process_index).

    Partitioning is defined in GLOBAL sample order: epoch ``e``'s order
    is ``permutation(seed + e)`` (or arange), chunked into global
    batches of ``nranks * batch_size``, and rank ``r`` takes the
    contiguous slice ``[r*batch_size : (r+1)*batch_size]`` of each
    chunk. The resume cursor (``state_dict``) is therefore a single
    *global* sample offset — the consumed prefix of the epoch's order —
    which stays exact when a checkpoint written at world size N resumes
    at world size M (elastic dp resize): no sample is replayed or
    skipped as long as the global batch size is preserved.
    """

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False, seed=0):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None:
            import jax
            num_replicas = jax.process_count()
        if rank is None:
            import jax
            rank = jax.process_index()
        self.nranks = num_replicas
        self.local_rank = rank
        self.seed = int(seed)
        self.epoch = 0
        self._offset = 0  # global samples consumed in the current epoch
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    @property
    def global_batch_size(self):
        return self.batch_size * self.nranks

    def _global_order(self, epoch):
        """Epoch ``epoch``'s global sample order, padded (wrapping) to a
        whole number of global batches — or truncated under drop_last."""
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.seed + int(epoch))
            order = rng.permutation(n).tolist()
        else:
            order = list(range(n))
        gbs = self.global_batch_size
        if self.drop_last:
            return order[:(n // gbs) * gbs]
        pad = (-n) % gbs
        while pad > 0:
            take = min(pad, n)
            order += order[:take]
            pad -= take
        return order

    def __iter__(self):
        epoch = self.epoch
        order = self._global_order(epoch)
        gbs = self.global_batch_size
        lo = self.local_rank * self.batch_size
        g0 = self._offset
        while g0 < len(order):
            chunk = order[g0:g0 + gbs]
            g0 = min(g0 + gbs, len(order))
            # the cursor advances as batches are handed out: a state_dict
            # captured after training on batch b resumes at b+1
            self._offset = g0
            batch = chunk[lo:lo + self.batch_size]
            if batch:
                yield batch
        self._offset = 0
        if self.shuffle:
            self.epoch = epoch + 1

    def __len__(self):
        n = len(self.dataset)
        gbs = self.global_batch_size
        if self.drop_last:
            return n // gbs
        return (n + gbs - 1) // gbs

    def set_epoch(self, epoch):
        self.epoch = int(epoch)
        self._offset = 0

    # -- sample-exact resume ------------------------------------------------
    def state_dict(self):
        """The resume cursor: epoch, consumed GLOBAL sample offset, and
        the shuffle RNG derivation (seed; the permutation is a pure
        function of ``seed + epoch``). JSON-able — CheckpointManager
        embeds it in the commit manifest (``attach_data``)."""
        return {"epoch": int(self.epoch), "offset": int(self._offset),
                "seed": int(self.seed), "shuffle": bool(self.shuffle),
                "global_batch_size": int(self.global_batch_size)}

    def load_state_dict(self, state):
        """Resume from a cursor — possibly written at a different world
        size: the offset is global, so only ``global_batch_size`` needs
        to be preserved across the resize for sample-exactness."""
        self.epoch = int(state.get("epoch", 0))
        self._offset = int(state.get("offset", 0))
        if "seed" in state:
            self.seed = int(state["seed"])
