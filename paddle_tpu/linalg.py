"""paddle.linalg namespace (reference: python/paddle/linalg.py)."""
from .tensor.linalg import (matmul, bmm, dot, mv, t, norm, dist, cond, cross,
                            cholesky, cholesky_solve, qr, svd, inv, det,
                            slogdet, solve, triangular_solve, eig, eigh,
                            eigvals, eigvalsh, matrix_power, matrix_rank,
                            pinv, lstsq, lu, multi_dot, corrcoef, cov,
                            householder_product)
from .tensor.math import trace

from .tensor.extras import lu_unpack  # noqa: E402,F401
