"""LLMEngine: the user-facing serving front end.

``add_request()`` enqueues, ``step()`` runs one continuous-batching
iteration (schedule -> one jitted forward_paged call -> commit), and
streaming happens through per-request ``on_token`` callbacks.  The
engine owns the device-side page pools and threads them through the
compiled step; the scheduler and PagedKVCache own all host-side state.

Compilation discipline: the batch is always [max_running, Tc] with
Tc in {1, chunk}, so a serving process compiles at most two step
executables per pool signature regardless of traffic.  Greedy decode
only — sampling lives in models/decoding.py for the offline path; the
serving acceptance bar is stream-for-stream parity with
``forward_with_cache`` greedy decode.

Observability: ``serve_*`` metrics (queue depth, running batch,
prefill/decode token counters, TTFT and request-latency histograms)
behind ``FLAGS_tpu_metrics`` — one dict lookup when disabled — plus a
module-level stats dict that backs the Profiler "Serving" section and
an xmem reservation for the pool HBM.
"""
from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..profiler import metrics as _metrics
from ..profiler import xmem as _xmem
from .kv_cache import PagedKVCache, _cdiv, kv_bytes_per_token
from .scheduler import Request, Scheduler

__all__ = ["LLMEngine", "serving_stats", "reset_stats", "summary_lines"]

# process-wide serving stats (Profiler "Serving" section). Plain dict,
# updated by every engine in the process; cheap enough to keep
# unconditionally.
_STATS: Dict[str, float] = {}


def _stats_zero() -> Dict[str, float]:
    return {
        "engines": 0, "requests_added": 0, "requests_finished": 0,
        "requests_preempted": 0, "steps": 0, "prefill_tokens": 0,
        "decode_tokens": 0, "peak_running": 0, "pool_bytes": 0,
        "compiled_buckets": 0,
    }


_STATS.update(_stats_zero())


def serving_stats() -> Dict[str, float]:
    return dict(_STATS)


def reset_stats() -> None:
    _STATS.clear()
    _STATS.update(_stats_zero())


def summary_lines() -> List[str]:
    """The "Serving" block of Profiler.summary_table()."""
    s = _STATS
    lines = ["Serving"]
    if not s["engines"]:
        lines.append("  (no LLMEngine instantiated)")
        return lines
    lines.append(
        f"  requests: {int(s['requests_added'])} added  "
        f"{int(s['requests_finished'])} finished  "
        f"{int(s['requests_preempted'])} preempted")
    lines.append(
        f"  steps: {int(s['steps'])}  "
        f"tokens: {int(s['prefill_tokens'])} prefill  "
        f"{int(s['decode_tokens'])} decode  "
        f"peak batch: {int(s['peak_running'])}")
    lines.append(
        f"  kv pools: {s['pool_bytes'] / 2**20:.1f} MiB  "
        f"compiled buckets: {int(s['compiled_buckets'])}")
    return lines


class LLMEngine:
    """Continuous-batching serving engine over ``models/llama.py``.

    Parameters mirror the capacity plan: ``page_size`` tokens per pool
    page, ``num_pages`` pool pages per layer (default: enough for every
    slot at ``max_model_len``, +1 for the reserved null page),
    ``chunk`` the prefill chunk length (also the prefill bucket Tc),
    ``max_running`` the fixed batch width.
    """

    def __init__(self, cfg, params, *, max_running: int = 8,
                 chunk: int = 16, page_size: int = 16,
                 num_pages: Optional[int] = None,
                 max_model_len: Optional[int] = None,
                 kv_dtype=None, donate_pools: Optional[bool] = None):
        from ..models import llama as _llama

        self.cfg = cfg
        self.params = params
        self._forward_paged = _llama.forward_paged
        self.max_running = int(max_running)
        self.chunk = int(chunk)
        self.page_size = int(page_size)
        self.max_model_len = int(
            min(max_model_len or cfg.max_position_embeddings,
                cfg.max_position_embeddings))
        self.max_blocks = _cdiv(self.max_model_len, self.page_size)
        if num_pages is None:
            num_pages = self.max_running * self.max_blocks + 1
        self.num_pages = int(num_pages)

        self.kv = PagedKVCache(self.num_pages, self.page_size,
                               self.max_blocks)
        self.scheduler = Scheduler(self.kv, max_running=self.max_running,
                                   chunk=self.chunk,
                                   max_model_len=self.max_model_len)

        kv_dtype = kv_dtype or cfg.dtype
        L, nkv, d = (cfg.num_hidden_layers, cfg.num_key_value_heads,
                     cfg.head_dim)
        shape = (L, nkv, self.num_pages, self.page_size, d)
        self._kp = jnp.zeros(shape, kv_dtype)
        self._vp = jnp.zeros(shape, kv_dtype)
        pool_bytes = 2 * int(np.prod(shape)) * jnp.dtype(kv_dtype).itemsize
        _xmem.record_reservation(
            "serving.kv_pages", pool_bytes, pages=self.num_pages,
            page_size=self.page_size,
            bytes_per_token=kv_bytes_per_token(
                cfg, jnp.dtype(kv_dtype).itemsize))
        self._pool_bytes = pool_bytes

        if donate_pools is None:
            donate_pools = jax.default_backend() in ("tpu", "axon")
        self._donate = bool(donate_pools)
        self._step_fns: Dict[int, Callable] = {}
        self._requests: Dict[int, Request] = {}

        _STATS["engines"] += 1
        _STATS["pool_bytes"] += pool_bytes

    # -- request intake --------------------------------------------------
    def add_request(self, prompt, max_new_tokens: int,
                    eos_token_id: Optional[int] = None,
                    on_token: Optional[Callable] = None) -> int:
        """Enqueue one request; returns its id.  ``on_token(rid, token,
        finished)`` streams every generated token from the step that
        produced it."""
        req = Request(prompt=[int(t) for t in prompt],
                      max_new_tokens=int(max_new_tokens),
                      eos_token_id=eos_token_id, on_token=on_token,
                      arrival_s=time.monotonic())
        self.scheduler.add(req)
        self._requests[req.rid] = req
        _STATS["requests_added"] += 1
        if _metrics.enabled():
            _metrics.gauge("serve_queue_depth",
                           "Requests waiting for admission").set(
                self.scheduler.num_waiting)
        return req.rid

    def output_of(self, rid: int) -> List[int]:
        return list(self._requests[rid].output)

    def has_work(self) -> bool:
        return self.scheduler.has_work()

    # -- the compiled step ----------------------------------------------
    def _step_fn(self, Tc: int):
        fn = self._step_fns.get(Tc)
        if fn is not None:
            return fn
        cfg, fwd = self.cfg, self._forward_paged

        def step(params, tokens, kp, vp, tbl, lens, qlens):
            logits, (kp, vp) = fwd(cfg, params, tokens, kp, vp, tbl,
                                   lens, qlens)
            last = jnp.clip(qlens - 1, 0, tokens.shape[1] - 1)
            rows = jnp.take_along_axis(
                logits, last[:, None, None], axis=1)[:, 0]   # [R, V]
            return jnp.argmax(rows, axis=-1).astype(jnp.int32), kp, vp

        fn = jax.jit(step, donate_argnums=(2, 3) if self._donate else ())
        self._step_fns[Tc] = fn
        _STATS["compiled_buckets"] += 1
        return fn

    def step(self) -> List[int]:
        """One continuous-batching iteration.  Returns the request ids
        that finished at this step boundary (empty list when idle or
        still mid-flight)."""
        plan = self.scheduler.schedule()
        if not plan.seqs:
            return []
        R, Tc = self.max_running, plan.bucket
        Bmax = self.max_blocks
        tokens = np.zeros((R, Tc), np.int32)
        tbl = np.zeros((R, Bmax), np.int32)
        lens = np.zeros((R,), np.int32)
        qlens = np.zeros((R,), np.int32)
        prefill = decode = 0
        for s in plan.seqs:
            req = s.request
            tokens[s.slot, :s.q_len] = req.known[req.fed:req.fed + s.q_len]
            tbl[s.slot] = self.kv.block_row(req.rid)
            lens[s.slot] = s.seq_len
            qlens[s.slot] = s.q_len
            if s.q_len == 1 and s.produces:
                decode += 1
            else:
                prefill += s.q_len

        nxt, self._kp, self._vp = self._step_fn(Tc)(
            self.params, jnp.asarray(tokens), self._kp, self._vp,
            jnp.asarray(tbl), jnp.asarray(lens), jnp.asarray(qlens))
        nxt = np.asarray(nxt)

        now = time.monotonic()
        finished = self.scheduler.apply(
            plan, {s.slot: nxt[s.slot] for s in plan.seqs if s.produces},
            now_s=now)

        _STATS["steps"] += 1
        _STATS["prefill_tokens"] += prefill
        _STATS["decode_tokens"] += decode
        _STATS["requests_preempted"] += len(plan.preempted)
        _STATS["requests_finished"] += len(finished)
        _STATS["peak_running"] = max(_STATS["peak_running"],
                                     len(plan.seqs))
        if _metrics.enabled():
            _metrics.gauge("serve_queue_depth",
                           "Requests waiting for admission").set(
                self.scheduler.num_waiting)
            _metrics.gauge("serve_running_batch",
                           "Requests in the running batch").set(
                self.scheduler.num_running + len(finished))
            _metrics.counter("serve_prefill_tokens_total",
                             "Prompt tokens fed to the model").inc(prefill)
            _metrics.counter("serve_decode_tokens_total",
                             "Decode tokens generated").inc(decode)
            if plan.preempted:
                _metrics.counter(
                    "serve_preemptions_total",
                    "Requests preempted for pool pressure").inc(
                    len(plan.preempted))
            for req in plan.seqs:
                r = req.request
                if (r.first_token_s is not None
                        and r.first_token_s == now):
                    _metrics.histogram(
                        "serve_ttft_seconds",
                        "Time to first token").observe(
                        now - r.arrival_s)
            for r in finished:
                _metrics.histogram(
                    "serve_request_latency_seconds",
                    "Request arrival to completion").observe(
                    now - r.arrival_s)
        return [r.rid for r in finished]

    # -- convenience -----------------------------------------------------
    def run(self, max_steps: Optional[int] = None) -> Dict[int, List[int]]:
        """Step until all queued/running work completes (or max_steps);
        returns rid -> generated tokens for every finished request."""
        steps = 0
        while self.has_work():
            if max_steps is not None and steps >= max_steps:
                break
            self.step()
            steps += 1
        return {rid: list(r.output) for rid, r in self._requests.items()
                if not r.state.value == "waiting"}

    def shutdown(self) -> None:
        """Drop the pools and their xmem reservation."""
        _STATS["pool_bytes"] -= self._pool_bytes
        _xmem.record_reservation("serving.kv_pages", 0)
        self._kp = self._vp = None
        self._step_fns.clear()
