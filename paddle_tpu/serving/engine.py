"""LLMEngine: the user-facing serving front end.

``add_request()`` enqueues, ``step()`` runs one continuous-batching
iteration (schedule -> one jitted forward_paged call -> commit), and
streaming happens through per-request ``on_token`` callbacks.  The
engine owns the device-side page pools and threads them through the
compiled step; the scheduler and PagedKVCache own all host-side state.

Compilation discipline: the batch is always [max_running, Tc] with
Tc in {1, chunk}, so a serving process compiles at most two step
executables per pool signature regardless of traffic.  Greedy decode
only — sampling lives in models/decoding.py for the offline path; the
serving acceptance bar is stream-for-stream parity with
``forward_with_cache`` greedy decode.

Resilience (the fault story):

  * **Admission control** — a bounded queue with watermark hysteresis:
    at ``max_queue`` waiting requests admission sheds with the typed,
    retriable :class:`~paddle_tpu.serving.errors.AdmissionRejected`
    and stays shedding until the queue drains below half.  Bounded
    host memory under any open-loop load.
  * **Deadlines/SLOs** — per-request absolute deadlines on the
    engine's injectable monotonic clock; expiry at a step boundary is
    a terminal FAILED with
    :class:`~paddle_tpu.serving.errors.DeadlineExceeded`.  TTFT and
    request-latency samples back ``slo_report()``.
  * **Crash recovery** — ``step()`` runs under the ``serve.step``
    watchdog phase and a same-named chaos point.  Any step failure
    (device error, injected fault, hung call past the deadline,
    non-finite logits via the PR-3 numerics checks) is classified,
    the *suspect donated pools are discarded* and rebuilt from
    host-side scheduler state, and every in-flight request replays
    its full history through the unified fed/known path — greedy
    decode makes the replay bit-identical.  A poison-pill request is
    found by bisecting the failed batch on scratch pools and
    quarantined (:class:`~paddle_tpu.serving.errors
    .RequestQuarantined`) so the other streams survive it.

Observability: ``serve_*`` metrics (queue depth, running batch,
prefill/decode token counters, TTFT and request-latency histograms,
shed/recovery/quarantine counters) behind ``FLAGS_tpu_metrics`` — one
dict lookup when disabled — plus a module-level stats dict that backs
the Profiler "Serving" section and an xmem reservation for the pool
HBM.
"""
from __future__ import annotations

import dataclasses
import logging
import time
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..profiler import exporter as _exporter
from ..profiler import metrics as _metrics
from ..profiler import numerics as _numerics
from ..profiler import trace as _trace
from ..profiler import xmem as _xmem
from ..runtime.watchdog import (PhaseTimeout, Watchdog, global_watchdog,
                                record_incident)
from ..testing.chaos import ChaosError, ReplicaKilled, chaos_point
from .errors import (AdmissionRejected, DeadlineExceeded,
                     RequestQuarantined)
from .kv_cache import PagedKVCache, _cdiv, kv_bytes_per_token
from .scheduler import (AdmissionGate, Request, RequestState, Scheduler,
                        StepPlan)
from .spec_decode import DraftModel, SpecDecodeConfig, greedy_accept
from . import stats as _stats

__all__ = ["LLMEngine", "SLOConfig", "serving_stats", "reset_stats",
           "summary_lines"]

_LOG = logging.getLogger("paddle_tpu.serving")

# process-wide serving stats (Profiler "Serving" section).  The dict
# itself lives in serving/stats.py (stdlib-only, shared with the
# router and the jax-free fleet tools); this module keeps the public
# serving_stats/reset_stats names.
_STATS = _stats.STATS
serving_stats = _stats.serving_stats
reset_stats = _stats.reset_stats


def summary_lines() -> List[str]:
    """The "Serving" block of Profiler.summary_table()."""
    s = _STATS
    lines = ["Serving"]
    if not s["engines"]:
        lines.append("  (no LLMEngine instantiated)")
        return lines
    lines.append(
        f"  requests: {int(s['requests_added'])} added  "
        f"{int(s['requests_finished'])} finished  "
        f"{int(s['requests_preempted'])} preempted")
    lines.append(
        f"  steps: {int(s['steps'])}  "
        f"tokens: {int(s['prefill_tokens'])} prefill  "
        f"{int(s['decode_tokens'])} decode  "
        f"peak batch: {int(s['peak_running'])}")
    lines.append(
        f"  kv pools: {s['pool_bytes'] / 2**20:.1f} MiB  "
        f"compiled buckets: {int(s['compiled_buckets'])}")
    if s["prefix_hit_tokens"] or s["spec_proposed"]:
        lines.append(
            f"  reuse: {int(s['prefix_hit_tokens'])} prefix-hit tokens "
            f"({int(s['prefix_evicted_pages'])} pages evicted)  "
            f"spec: {int(s['spec_accepted'])}/{int(s['spec_proposed'])} "
            f"drafts accepted")
    lines.append(
        f"  resilience: {int(s['recoveries'])} recoveries  "
        f"{int(s['quarantined'])} quarantined  "
        f"{int(s['shed'])} shed  "
        f"{int(s['deadline_expired'])} deadline-expired  "
        f"{int(s['cancelled'])} cancelled")
    lines.append(
        f"  replicas: {int(s['failovers'])} failovers  "
        f"{int(s['replicas_dead'])} dead  "
        f"{int(s['drains'])} drains  "
        f"callback errors: {int(s['callback_errors'])}")
    from . import router as _router  # function-local: router imports us
    lines.extend(_router.replica_summary_lines())
    return lines


@dataclasses.dataclass
class SLOConfig:
    """Service-level objectives for one engine (or router).  All in
    seconds; None leaves that objective unset.  ``deadline_s`` is the
    default per-request deadline applied at admission when the caller
    passes none."""

    ttft_p95_s: Optional[float] = None
    latency_p95_s: Optional[float] = None
    deadline_s: Optional[float] = None


class _SafeCallback:
    """Isolates a raising user ``on_token`` callback from the step
    loop: the first exception is logged once and counted in
    ``serve_callback_errors_total``, the callback is disarmed, and the
    request's stream (decode, kv pages, completion) stays alive."""

    def __init__(self, fn: Callable):
        self._fn = fn
        self._dead = False

    def __call__(self, rid, token, finished):
        if self._dead:
            return
        try:
            self._fn(rid, token, finished)
        except Exception as exc:  # noqa: BLE001 — isolation is the point
            self._dead = True
            _STATS["callback_errors"] += 1
            _LOG.warning(
                "on_token callback for request %s raised %r; disarming "
                "the callback, stream continues", rid, exc)
            if _metrics.enabled():
                _metrics.counter(
                    "serve_callback_errors_total",
                    "User on_token callbacks that raised").inc()


class LLMEngine:
    """Continuous-batching serving engine over ``models/llama.py``.

    Parameters mirror the capacity plan: ``page_size`` tokens per pool
    page, ``num_pages`` pool pages per layer (default: enough for every
    slot at ``max_model_len``, +1 for the reserved null page),
    ``chunk`` the prefill chunk length (also the prefill bucket Tc),
    ``max_running`` the fixed batch width.

    Resilience knobs: ``clock`` is the engine's monotonic time source
    (injectable for tests; never wall time, so NTP steps cannot corrupt
    latency histograms), ``max_queue`` bounds the admission queue
    (default ``8 * max_running``), ``slo`` carries TTFT/latency targets
    and the default per-request deadline, ``watchdog`` overrides the
    flag-gated global watchdog for the ``serve.step`` phase.

    Work-reuse knobs (both default off; outputs stay bit-identical to
    plain greedy decode either way): ``prefix_cache=True`` turns on
    shared-prefix KV reuse — admission matches each prompt against the
    radix cache and only prefills the uncached tail
    (``serving/prefix_cache.py``); ``spec=SpecDecodeConfig(...)``
    attaches a draft model for speculative decoding — every decode row
    widens to a 1+k verify chunk through the prefill bucket
    (``serving/spec_decode.py``).
    """

    def __init__(self, cfg, params, *, max_running: int = 8,
                 chunk: int = 16, page_size: int = 16,
                 num_pages: Optional[int] = None,
                 max_model_len: Optional[int] = None,
                 kv_dtype=None, donate_pools: Optional[bool] = None,
                 clock: Callable[[], float] = time.monotonic,
                 max_queue: Optional[int] = None,
                 slo: Optional[SLOConfig] = None,
                 watchdog: Optional[Watchdog] = None,
                 prefix_cache: bool = False,
                 spec: Optional["SpecDecodeConfig"] = None):
        from ..models import llama as _llama

        self.cfg = cfg
        if _llama._quantized_mode(cfg):
            # int8 weight path (cfg.quantized / FLAGS_tpu_quantized):
            # PTQ the serving weights once at engine build; forward
            # bodies dispatch through the int8 matmul kernels
            params = _llama.quantize_params(cfg, params)
        self.params = params
        self._forward_paged = _llama.forward_paged
        self.max_running = int(max_running)
        self.chunk = int(chunk)
        self.page_size = int(page_size)
        self.max_model_len = int(
            min(max_model_len or cfg.max_position_embeddings,
                cfg.max_position_embeddings))
        self.max_blocks = _cdiv(self.max_model_len, self.page_size)
        if num_pages is None:
            num_pages = self.max_running * self.max_blocks + 1
        self.num_pages = int(num_pages)

        self._clock = clock
        self.max_queue = int(max_queue if max_queue is not None
                             else 8 * self.max_running)
        self.slo = slo
        self._watchdog = watchdog
        self._gate = AdmissionGate(self.max_queue)
        # per-bucket step wall times (engine clock) — the measured
        # service model behind service_model()/fleet_sim calibration
        self._step_wall_s: Dict[int, List[float]] = {}
        self._ttft_s: List[float] = []
        self._latency_s: List[float] = []
        # TTFT/latency decomposition (engine clock; queue + prefill
        # sums to TTFT by construction, + decode to latency)
        self._queue_s: List[float] = []
        self._prefill_s: List[float] = []
        self._decode_s: List[float] = []

        self.kv = PagedKVCache(self.num_pages, self.page_size,
                               self.max_blocks)
        self.scheduler = Scheduler(self.kv, max_running=self.max_running,
                                   chunk=self.chunk,
                                   max_model_len=self.max_model_len)

        kv_dtype = kv_dtype or cfg.dtype
        if isinstance(kv_dtype, str):
            kv_dtype = {"bf16": jnp.bfloat16,
                        "int8": jnp.int8}.get(kv_dtype, kv_dtype)
        L, nkv, d = (cfg.num_hidden_layers, cfg.num_key_value_heads,
                     cfg.head_dim)
        self._kv_dtype = kv_dtype
        # int8 pages select the quantized-KV path: a parallel per-page
        # scale pool (f32 [L, nkv, P], indexed by the same block
        # tables) rides every step — quantize-on-write in
        # forward_paged, dequant-on-read inside ragged_paged_attention
        self._quant_kv = jnp.dtype(kv_dtype) == jnp.dtype(jnp.int8)
        self._pool_shape = (L, nkv, self.num_pages, self.page_size, d)
        self._scale_shape = (L, nkv, self.num_pages)
        self._kp = jnp.zeros(self._pool_shape, kv_dtype)
        self._vp = jnp.zeros(self._pool_shape, kv_dtype)
        self._ks = self._vs = None
        scale_bytes = 0
        if self._quant_kv:
            # scale 1.0 everywhere: untouched (all-zero) pages dequant
            # to exact zeros, matching the dense pools' init state
            self._ks = jnp.ones(self._scale_shape, jnp.float32)
            self._vs = jnp.ones(self._scale_shape, jnp.float32)
            scale_bytes = 2 * int(np.prod(self._scale_shape)) * 4
        pool_bytes = (2 * int(np.prod(self._pool_shape))
                      * jnp.dtype(kv_dtype).itemsize) + scale_bytes
        _xmem.record_reservation(
            "serving.kv_pages", pool_bytes, pages=self.num_pages,
            page_size=self.page_size, kv_dtype=str(jnp.dtype(kv_dtype)),
            scale_pool_bytes=scale_bytes,
            bytes_per_token=kv_bytes_per_token(
                cfg, jnp.dtype(kv_dtype).itemsize))
        self._pool_bytes = pool_bytes
        self._scale_bytes = scale_bytes

        if donate_pools is None:
            donate_pools = jax.default_backend() in ("tpu", "axon")
        self._donate = bool(donate_pools)
        self._step_fns: Dict[int, Callable] = {}
        self._requests: Dict[int, Request] = {}
        self._steps = 0
        # rids scheduled in the previous step — the edge detector for
        # per-request "admitted" trace events (incl. re-admissions)
        self._sched_rids: set = set()

        # -- work reuse: shared-prefix KV cache + speculative decoding
        self._prefix_enabled = bool(prefix_cache)
        if self._prefix_enabled:
            self.kv.enable_prefix_cache()
        self._copy_fn = None           # COW page copy on the target pools
        self._evicted_seen = 0
        self._draft: Optional[DraftModel] = None
        self._spec_k = 0
        if spec is not None:
            if spec.cfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {spec.cfg.vocab_size} != target vocab "
                    f"{cfg.vocab_size}")
            if not 1 <= spec.k < self.chunk:
                raise ValueError(
                    f"spec.k={spec.k} must satisfy 1 <= k < chunk="
                    f"{self.chunk} (the verify chunk 1+k rides the "
                    "prefill bucket)")
            self._draft = DraftModel(
                spec.cfg, spec.params, num_pages=self.num_pages,
                page_size=self.page_size, donate=self._donate)
            self._spec_k = int(spec.k)
            self.scheduler.spec_k = self._spec_k

        _STATS["engines"] += 1
        _STATS["pool_bytes"] += pool_bytes

        _exporter.maybe_serve("engine", self)

    # -- request intake --------------------------------------------------
    def add_request(self, prompt, max_new_tokens: int,
                    eos_token_id: Optional[int] = None,
                    on_token: Optional[Callable] = None,
                    deadline_s: Optional[float] = None) -> int:
        """Enqueue one request; returns its id.  ``on_token(rid, token,
        finished)`` streams every generated token from the step that
        produced it (isolated — a raising callback cannot kill the
        engine).  ``deadline_s`` is relative to now on the engine
        clock; default comes from ``slo.deadline_s``.

        Raises :class:`AdmissionRejected` (retriable) when the bounded
        queue is shedding."""
        depth = self.scheduler.num_waiting
        if self._gate.check(depth):
            _STATS["shed"] += 1
            if _metrics.enabled():
                _metrics.counter(
                    "serve_shed_total",
                    "Requests rejected by admission control").inc()
            # rid -1: the request was never created, but the shed event
            # still belongs in the flight recorder's serving timeline
            _trace.request_event("shed", -1, t=self._clock(),
                                 queue_depth=depth)
            raise AdmissionRejected(
                f"admission queue at {depth}/{self.max_queue}; "
                f"shedding until it drains below {self.max_queue // 2} "
                f"— retry with backoff")
        if deadline_s is None and self.slo is not None:
            deadline_s = self.slo.deadline_s
        now = self._clock()
        req = Request(prompt=[int(t) for t in prompt],
                      max_new_tokens=int(max_new_tokens),
                      eos_token_id=eos_token_id,
                      on_token=(_SafeCallback(on_token)
                                if on_token is not None else None),
                      arrival_s=now,
                      deadline_s=(None if deadline_s is None
                                  else now + float(deadline_s)))
        self.scheduler.add(req)
        self._requests[req.rid] = req
        _trace.request_event("queued", req.rid, t=now,
                             prompt_len=len(req.prompt),
                             max_new_tokens=req.max_new_tokens,
                             deadline_s=req.deadline_s)
        _STATS["requests_added"] += 1
        if _metrics.enabled():
            _metrics.gauge("serve_queue_depth",
                           "Requests waiting for admission").set(
                self.scheduler.num_waiting)
        return req.rid

    def output_of(self, rid: int) -> List[int]:
        return list(self._requests[rid].output)

    def state_of(self, rid: int) -> RequestState:
        return self._requests[rid].state

    def error_of(self, rid: int) -> Optional[BaseException]:
        """Terminal error for a FAILED request (DeadlineExceeded,
        RequestQuarantined), else None."""
        return self._requests[rid].error

    def has_work(self) -> bool:
        return self.scheduler.has_work()

    def cancel(self, rid: int) -> bool:
        """Cooperative cancellation: takes effect immediately at the
        host level (pages freed, slot opened, queue entry dropped).
        Returns False when the request is already terminal."""
        req = self._requests.get(rid)
        if req is None or req.state not in (RequestState.WAITING,
                                            RequestState.RUNNING):
            return False
        self.scheduler.remove(req, now_s=self._clock(),
                              state=RequestState.CANCELLED)
        _STATS["cancelled"] += 1
        if _metrics.enabled():
            _metrics.counter("serve_cancelled_total",
                             "Requests cancelled by the caller").inc()
        return True

    # -- the compiled step ----------------------------------------------
    def _step_fn(self, Tc: int):
        fn = self._step_fns.get(Tc)
        if fn is not None:
            return fn
        cfg, fwd = self.cfg, self._forward_paged

        def _sample(logits, tokens, qlens):
            last = jnp.clip(qlens - 1, 0, tokens.shape[1] - 1)
            rows = jnp.take_along_axis(
                logits, last[:, None, None], axis=1)[:, 0]   # [R, V]
            # argmax at EVERY fed position [R, Tc]: position q_len-1 is
            # the sampled token (same value the old per-row argmax
            # gave); the earlier positions are what spec-decode
            # verification reads — multi-token verify needs the
            # target's choice after each draft token
            # chk: one float per row (max logit) — a cheap [R] transfer
            # the numerics watchdog scans for NaN/Inf poisoning
            return (jnp.argmax(logits, axis=-1).astype(jnp.int32),
                    jnp.max(rows, axis=-1))

        if self._quant_kv:
            def step(params, tokens, kp, vp, ks, vs, tbl, lens, qlens):
                logits, (kp, vp, ks, vs) = fwd(
                    cfg, params, tokens, kp, vp, tbl, lens, qlens,
                    k_scales=ks, v_scales=vs)
                nxt, chk = _sample(logits, tokens, qlens)
                return nxt, chk, kp, vp, ks, vs

            fn = jax.jit(step, donate_argnums=(
                (2, 3, 4, 5) if self._donate else ()))
        else:
            def step(params, tokens, kp, vp, tbl, lens, qlens):
                logits, (kp, vp) = fwd(cfg, params, tokens, kp, vp, tbl,
                                       lens, qlens)
                nxt, chk = _sample(logits, tokens, qlens)
                return nxt, chk, kp, vp

            fn = jax.jit(step, donate_argnums=(
                (2, 3) if self._donate else ()))
        self._step_fns[Tc] = fn
        _STATS["compiled_buckets"] += 1
        return fn

    @staticmethod
    def _batch_arrays(seqs, R: int, Tc: int, Bmax: int, kv,
                      drafts: Optional[Dict[int, List[int]]] = None):
        """Host-side input assembly for one step over ``seqs``.  A
        spec row feeds its one known token followed by the draft's
        proposals (the verify chunk)."""
        tokens = np.zeros((R, Tc), np.int32)
        tbl = np.zeros((R, Bmax), np.int32)
        lens = np.zeros((R,), np.int32)
        qlens = np.zeros((R,), np.int32)
        for s in seqs:
            req = s.request
            if getattr(s, "spec", 0) and drafts is not None:
                row = (req.known[req.fed:req.fed + 1]
                       + drafts[s.slot][:s.q_len - 1])
            else:
                row = req.known[req.fed:req.fed + s.q_len]
            tokens[s.slot, :s.q_len] = row
            tbl[s.slot] = kv.block_row(req.rid)
            lens[s.slot] = s.seq_len
            qlens[s.slot] = s.q_len
        return tokens, tbl, lens, qlens

    def _apply_copies(self, pairs) -> None:
        """Execute COW page forks on device, target pools and (when
        speculative decoding is on) draft pools — the same page pair,
        so a donated page always carries both models' kv.  One compile:
        src/dst are traced scalars, not baked constants."""
        if self._copy_fn is None:
            if self._quant_kv:
                def cp(kp, vp, ks, vs, s, d):
                    # a COW fork copies the page AND its dequant scale
                    return (kp.at[:, :, d].set(kp[:, :, s]),
                            vp.at[:, :, d].set(vp[:, :, s]),
                            ks.at[:, :, d].set(ks[:, :, s]),
                            vs.at[:, :, d].set(vs[:, :, s]))

                self._copy_fn = jax.jit(
                    cp, donate_argnums=(
                        (0, 1, 2, 3) if self._donate else ()))
            else:
                def cp(kp, vp, s, d):
                    return (kp.at[:, :, d].set(kp[:, :, s]),
                            vp.at[:, :, d].set(vp[:, :, s]))

                self._copy_fn = jax.jit(
                    cp, donate_argnums=(0, 1) if self._donate else ())
        for src, dst in pairs:
            if self._quant_kv:
                self._kp, self._vp, self._ks, self._vs = self._copy_fn(
                    self._kp, self._vp, self._ks, self._vs,
                    jnp.int32(src), jnp.int32(dst))
            else:
                self._kp, self._vp = self._copy_fn(
                    self._kp, self._vp, jnp.int32(src), jnp.int32(dst))
            if self._draft is not None:
                self._draft.copy_page(src, dst)

    def _wd(self) -> Optional[Watchdog]:
        if self._watchdog is not None:
            return self._watchdog
        from ..core.flags import flag
        if flag("FLAGS_tpu_watchdog"):
            return global_watchdog()
        return None

    def _expire_deadlines(self, now: float) -> None:
        active = [r for r in self.scheduler.slots if r is not None]
        active.extend(self.scheduler.waiting)
        for req in active:
            if req.deadline_s is None or now <= req.deadline_s:
                continue
            _trace.request_event("deadline_expired", req.rid, t=now,
                                 overrun_s=now - req.deadline_s)
            self.scheduler.remove(
                req, now_s=now, state=RequestState.FAILED,
                error=DeadlineExceeded(
                    f"request {req.rid} missed its deadline by "
                    f"{now - req.deadline_s:.3f}s "
                    f"({len(req.output)} tokens streamed)"))
            _STATS["deadline_expired"] += 1
            if _trace.enabled():
                # post-mortem: the expired request's full lifecycle
                # rides into the incident buffer (and, via
                # persist_incidents, the incident sidecar)
                record_incident(
                    "serve_deadline_expired", rid=int(req.rid),
                    overrun_s=float(now - req.deadline_s),
                    timeline=self.request_timeline(req.rid)[-32:])
            if _metrics.enabled():
                _metrics.counter(
                    "serve_deadline_expired_total",
                    "Requests failed at their deadline").inc()

    def step(self) -> List[int]:
        """One continuous-batching iteration.  Returns the request ids
        that finished at this step boundary (empty list when idle,
        still mid-flight, or after a recovered step failure)."""
        now = self._clock()
        self._expire_deadlines(now)
        plan = self.scheduler.schedule()
        tracing = _trace.enabled()
        if tracing:
            for req in plan.preempted:
                _trace.request_event("preempted", req.rid, t=now)
        for s in plan.seqs:
            req = s.request
            if tracing and req.rid not in self._sched_rids:
                _trace.request_event(
                    "admitted", req.rid, t=now, slot=s.slot,
                    prefix_hit=req.fed,
                    readmission=req.admitted_s is not None)
            if req.admitted_s is None:
                # first admission only: preemption replay keeps the
                # original stamp so queue time stays arrival->admission
                req.admitted_s = now
        self._sched_rids = {s.request.rid for s in plan.seqs}
        if plan.admission_blocked:
            # the pool (not the slot array) is the bottleneck: the
            # head-of-line request stays queued, never dropped
            _STATS["admission_waits"] += 1
            if _metrics.enabled():
                _metrics.counter(
                    "serve_admission_wait_total",
                    "Steps where free slots waited on pool pages").inc(
                    )
        # COW forks from this schedule's prefix matches must land on
        # device before any forward reads (or the allocator recycles)
        # the pages involved
        pairs = self.kv.drain_copies()
        if pairs:
            self._apply_copies(pairs)
        if not plan.seqs:
            return []
        R, Tc = self.max_running, plan.bucket
        drafts: Optional[Dict[int, List[int]]] = None
        if self._draft is not None:
            spec_rows = [
                (s.slot, s.request.known[s.request.fed], s.request.fed,
                 self.kv.block_row(s.request.rid))
                for s in plan.seqs if s.spec]
            if spec_rows:
                drafts = self._draft.propose(
                    spec_rows, self._spec_k, R, self.max_blocks)
        tokens, tbl, lens, qlens = self._batch_arrays(
            plan.seqs, R, Tc, self.max_blocks, self.kv, drafts)

        t_fwd = self._clock()
        try:
            with _trace.span("serve/step", step=self._steps,
                             batch=len(plan.seqs), bucket=Tc):
                nxt = self._guarded_forward(plan, tokens, tbl, lens,
                                            qlens, Tc)
        except ReplicaKilled:
            # whole-replica death is the router's failure domain, not a
            # step-recoverable fault — propagate
            raise
        except Exception as exc:  # noqa: BLE001 — classified in _recover
            return self._recover(plan, exc)

        if self._draft is not None:
            # mirror: the draft ingests the exact same feed, so its kv
            # tracks the target's fed counter in lockstep (donated
            # pages then carry valid draft kv for future borrowers)
            self._draft.forward(tokens, tbl, lens, qlens)

        now = self._clock()
        self._step_wall_s.setdefault(Tc, []).append(now - t_fwd)
        out: Dict[int, object] = {}
        prefill = decode = 0
        spec_proposed = spec_accepted = 0
        for s in plan.seqs:
            if s.spec:
                row = [int(t) for t in nxt[s.slot, :s.q_len]]
                emitted = greedy_accept(drafts[s.slot], row)
                out[s.slot] = emitted
                spec_proposed += s.spec
                spec_accepted += len(emitted) - 1
                decode += len(emitted)
                if tracing:
                    _trace.request_event(
                        "spec", s.request.rid, t=now, proposed=s.spec,
                        accepted=len(emitted) - 1)
            elif s.produces:
                out[s.slot] = int(nxt[s.slot, s.q_len - 1])
                if s.q_len == 1:
                    decode += 1
                    if tracing:
                        _trace.request_event("decode", s.request.rid,
                                             t=now, tokens=1)
                else:
                    prefill += s.q_len
                    if tracing:
                        _trace.request_event(
                            "prefill", s.request.rid, t=now,
                            tokens=s.q_len, last_chunk=True)
            else:
                prefill += s.q_len
                if tracing:
                    _trace.request_event(
                        "prefill", s.request.rid, t=now,
                        tokens=s.q_len, last_chunk=False)
        finished = self.scheduler.apply(plan, out, now_s=now)
        self._steps += 1

        _STATS["steps"] += 1
        _STATS["prefill_tokens"] += prefill
        _STATS["decode_tokens"] += decode
        _STATS["requests_preempted"] += len(plan.preempted)
        _STATS["requests_finished"] += len(finished)
        _STATS["peak_running"] = max(_STATS["peak_running"],
                                     len(plan.seqs))
        _STATS["prefix_hit_tokens"] += plan.prefix_hit_tokens
        _STATS["spec_proposed"] += spec_proposed
        _STATS["spec_accepted"] += spec_accepted
        if self._prefix_enabled:
            ev = self.kv.prefix.stats.evicted_pages
            _STATS["prefix_evicted_pages"] += ev - self._evicted_seen
            self._evicted_seen = ev
        for s in plan.seqs:
            r = s.request
            if r.first_token_s is not None and r.first_token_s == now:
                self._ttft_s.append(now - r.arrival_s)
                if r.admitted_s is not None:
                    self._queue_s.append(r.admitted_s - r.arrival_s)
                    self._prefill_s.append(now - r.admitted_s)
        for r in finished:
            self._latency_s.append(now - r.arrival_s)
            if r.first_token_s is not None:
                self._decode_s.append(now - r.first_token_s)
        if _metrics.enabled():
            _metrics.gauge("serve_queue_depth",
                           "Requests waiting for admission").set(
                self.scheduler.num_waiting)
            _metrics.gauge("serve_running_batch",
                           "Requests in the running batch").set(
                self.scheduler.num_running + len(finished))
            _metrics.counter("serve_prefill_tokens_total",
                             "Prompt tokens fed to the model").inc(prefill)
            _metrics.counter("serve_decode_tokens_total",
                             "Decode tokens generated").inc(decode)
            if plan.preempted:
                _metrics.counter(
                    "serve_preemptions_total",
                    "Requests preempted for pool pressure").inc(
                    len(plan.preempted))
            if plan.prefix_hit_tokens:
                _metrics.counter(
                    "serve_prefix_hit_tokens_total",
                    "Prompt tokens served from the prefix cache").inc(
                    plan.prefix_hit_tokens)
            if spec_proposed:
                _metrics.counter(
                    "serve_spec_proposed_total",
                    "Draft tokens proposed for verification").inc(
                    spec_proposed)
                _metrics.counter(
                    "serve_spec_accepted_total",
                    "Draft tokens accepted by the target").inc(
                    spec_accepted)
            for s in plan.seqs:
                r = s.request
                if (r.first_token_s is not None
                        and r.first_token_s == now):
                    _metrics.histogram(
                        "serve_ttft_seconds",
                        "Time to first token").observe(
                        now - r.arrival_s)
            for r in finished:
                _metrics.histogram(
                    "serve_request_latency_seconds",
                    "Request arrival to completion").observe(
                    now - r.arrival_s)
        return [r.rid for r in finished]

    def _guarded_forward(self, plan: StepPlan, tokens, tbl, lens, qlens,
                         Tc: int) -> np.ndarray:
        """The device call under the serve.step watchdog phase, chaos
        point, and numerics check.  Returns the sampled tokens [R]."""
        wd = self._wd()
        if wd is not None:
            wd.begin("serve.step")
        try:
            chaos_point("serve.step", step=self._steps,
                        rids=[s.request.rid for s in plan.seqs],
                        pool=self.kv.allocator, engine=self)
            if self._quant_kv:
                (nxt, chk, self._kp, self._vp, self._ks,
                 self._vs) = self._step_fn(Tc)(
                    self.params, jnp.asarray(tokens), self._kp,
                    self._vp, self._ks, self._vs, jnp.asarray(tbl),
                    jnp.asarray(lens), jnp.asarray(qlens))
            else:
                nxt, chk, self._kp, self._vp = self._step_fn(Tc)(
                    self.params, jnp.asarray(tokens), self._kp,
                    self._vp, jnp.asarray(tbl), jnp.asarray(lens),
                    jnp.asarray(qlens))
            nxt = np.asarray(nxt)
            if _numerics.enabled():
                rows = np.asarray(chk)[[s.slot for s in plan.seqs]]
                _numerics.check_array(rows, "serve.step.logits",
                                      action="raise")
            if wd is not None:
                # synchronous expiry: a device call that *eventually*
                # returned past its deadline is still a hang — convert
                # it to PhaseTimeout here (poll records dump/metric/
                # incident), same recovery as a ticker-detected hang
                for exc in wd.poll(raise_on_expire=False):
                    if exc.phase == "serve.step":
                        raise exc
            return nxt
        finally:
            if wd is not None:
                wd.end("serve.step")

    # -- crash recovery --------------------------------------------------
    @staticmethod
    def _classify(exc: BaseException) -> str:
        if isinstance(exc, PhaseTimeout):
            return "hang"
        if isinstance(exc, _numerics.NonFiniteError):
            return "non_finite"
        if isinstance(exc, ChaosError):
            return "injected"
        if isinstance(exc, (RuntimeError, OSError)):
            return "device_error"
        return "unknown"

    def _rebuild(self) -> List[Request]:
        """Discard the (suspect, possibly donated-away) device pools
        and all host page state; rebuild both from scratch and demote
        every running request to the front of the queue with fed=0 —
        the unified fed/known path then replays prompt + generated
        tokens, bit-identical under greedy decode.  The prefix trie is
        rebuilt empty (its pages lived in the suspect pools) and the
        draft pools reset with it — replays re-prefill and re-mirror
        from scratch, so the reuse machinery cannot alter the replayed
        streams."""
        self.kv = PagedKVCache(self.num_pages, self.page_size,
                               self.max_blocks)
        if self._prefix_enabled:
            self.kv.enable_prefix_cache()
            self._evicted_seen = 0
        self.scheduler.kv = self.kv
        self._kp = jnp.zeros(self._pool_shape, self._kv_dtype)
        self._vp = jnp.zeros(self._pool_shape, self._kv_dtype)
        if self._quant_kv:
            self._ks = jnp.ones(self._scale_shape, jnp.float32)
            self._vs = jnp.ones(self._scale_shape, jnp.float32)
        if self._draft is not None:
            self._draft.reset()
        demoted = self.scheduler.reset_running()
        self.scheduler.requeue_front(demoted)
        self._sched_rids.clear()
        if _trace.enabled():
            now = self._clock()
            for req in demoted:
                _trace.request_event("replay", req.rid, t=now,
                                     replayed_tokens=req.num_known)
        return demoted

    def _probe(self, group: List[Request]) -> bool:
        """Replay ``group``'s first chunks on scratch pools; True when
        the step is clean.  Fires the serve.step chaos point with the
        group's rids, so a ``rid=``-scoped rule keeps blaming its
        target and bisection converges on it deterministically."""
        kv = PagedKVCache(self.num_pages, self.page_size,
                          self.max_blocks)
        seqs = []
        for slot, req in enumerate(group):
            q = min(self.chunk, req.num_known)
            kv.grow(req.rid, q)
            seqs.append(_ProbeSeq(req, slot, q))
        Tc = self.chunk if any(s.q_len > 1 for s in seqs) else 1
        tokens, tbl, lens, qlens = self._batch_arrays(
            seqs, self.max_running, Tc, self.max_blocks, kv)
        try:
            chaos_point("serve.step", step=self._steps,
                        rids=[r.rid for r in group],
                        pool=kv.allocator, engine=self, probe=True)
            if self._quant_kv:
                _, chk, *_rest = self._step_fn(Tc)(
                    self.params, jnp.asarray(tokens),
                    jnp.zeros(self._pool_shape, self._kv_dtype),
                    jnp.zeros(self._pool_shape, self._kv_dtype),
                    jnp.ones(self._scale_shape, jnp.float32),
                    jnp.ones(self._scale_shape, jnp.float32),
                    jnp.asarray(tbl), jnp.asarray(lens),
                    jnp.asarray(qlens))
            else:
                _, chk, _, _ = self._step_fn(Tc)(
                    self.params, jnp.asarray(tokens),
                    jnp.zeros(self._pool_shape, self._kv_dtype),
                    jnp.zeros(self._pool_shape, self._kv_dtype),
                    jnp.asarray(tbl), jnp.asarray(lens),
                    jnp.asarray(qlens))
            if _numerics.enabled():
                rows = np.asarray(chk)[[s.slot for s in seqs]]
                _numerics.check_array(rows, "serve.step.probe",
                                      action="raise")
            return True
        except Exception:  # noqa: BLE001 — a dirty probe IS the signal
            return False

    def _bisect(self, suspects: List[Request]) -> Optional[Request]:
        """Binary-search the failed batch for a single poison request
        on scratch pools (at most ``1 + 2*ceil(log2 R)`` probes).
        None means the failure did not reproduce in isolation —
        transient, everyone replays."""
        group = list(suspects)
        if not group or self._probe(group):
            return None
        while len(group) > 1:
            mid = len(group) // 2
            if not self._probe(group[:mid]):
                group = group[:mid]
            elif not self._probe(group[mid:]):
                group = group[mid:]
            else:
                return None  # only fails in combination — transient
        return group[0]

    def _recover(self, plan: StepPlan, exc: Exception) -> List[int]:
        """A failed/hung/poisoned step: classify, rebuild the pools
        from host-side state, quarantine a bisected culprit, replay the
        rest.  Always returns [] — no request finishes at a failed
        step boundary."""
        failure = self._classify(exc)
        suspects = [s.request for s in plan.seqs]
        self._rebuild()
        culprit = None
        if failure != "hang":
            # probing a genuinely hung fault would hang recovery too;
            # hangs replay wholesale instead
            culprit = self._bisect(suspects)
        _trace.event("serve/recovery", kind="engine", failure=failure,
                     step=int(self._steps), batch=len(suspects))
        if culprit is not None:
            _trace.request_event("quarantine", culprit.rid,
                                 t=self._clock(), failure=failure)
            self.scheduler.remove(
                culprit, now_s=self._clock(),
                state=RequestState.FAILED,
                error=RequestQuarantined(
                    f"request {culprit.rid} quarantined: bisection "
                    f"blamed it for a {failure} step failure ({exc})"))
            _STATS["quarantined"] += 1
            if _metrics.enabled():
                _metrics.counter(
                    "serve_quarantined_total",
                    "Requests quarantined by step-failure "
                    "bisection").inc()
        _STATS["recoveries"] += 1
        record_incident(
            "serve_step_failure", failure=failure, step=int(self._steps),
            batch=len(suspects),
            culprit=(None if culprit is None else int(culprit.rid)),
            replayed=len(suspects) - (culprit is not None),
            error=str(exc)[:200])
        if _metrics.enabled():
            _metrics.counter(
                "serve_recoveries_total",
                "Engine step failures recovered via pool-rebuild "
                "replay", failure=failure).inc()
        _LOG.warning(
            "serve.step failure (%s) at step %d: rebuilt pools, "
            "replaying %d request(s)%s", failure, self._steps,
            len(suspects) - (culprit is not None),
            "" if culprit is None
            else f", quarantined request {culprit.rid}")
        return []

    # -- SLO reporting ----------------------------------------------------
    def slo_report(self) -> Dict[str, Optional[float]]:
        """Observed TTFT/latency p95 against the configured SLOs; the
        ``*_ok`` entries are None when no target is set.  ``breakdown``
        decomposes where the time went: per-request queue
        (arrival → first admission) and prefill (admission → first
        token) components sum to that request's TTFT by construction,
        and decode (first token → finish) extends the sum to its full
        latency."""

        def _p95(xs):
            return float(np.percentile(xs, 95)) if xs else None

        ttft, lat = _p95(self._ttft_s), _p95(self._latency_s)
        slo = self.slo or SLOConfig()
        rep: Dict[str, Optional[float]] = {
            "ttft_p95_s": ttft, "latency_p95_s": lat,
            "ttft_slo_s": slo.ttft_p95_s,
            "latency_slo_s": slo.latency_p95_s,
            "ttft_ok": None, "latency_ok": None,
        }
        if slo.ttft_p95_s is not None and ttft is not None:
            rep["ttft_ok"] = ttft <= slo.ttft_p95_s
        if slo.latency_p95_s is not None and lat is not None:
            rep["latency_ok"] = lat <= slo.latency_p95_s
        rep["breakdown"] = {
            "queue_p95_s": _p95(self._queue_s),
            "prefill_p95_s": _p95(self._prefill_s),
            "decode_p95_s": _p95(self._decode_s),
            "queue_mean_s": (float(np.mean(self._queue_s))
                             if self._queue_s else None),
            "prefill_mean_s": (float(np.mean(self._prefill_s))
                               if self._prefill_s else None),
            "decode_mean_s": (float(np.mean(self._decode_s))
                              if self._decode_s else None),
            "samples": len(self._queue_s),
        }
        return rep

    def service_model(self):
        """Measured per-replica service model for fleet planning
        (:class:`~paddle_tpu.serving.autoscale.ServiceModel`): median
        step wall time per compiled bucket — warmup/compile steps are
        excluded by the median — plus this engine's capacity knobs.
        The same record ``tools/fleet_sim.py`` calibrates from trace
        sidecars; here it comes straight off the live engine clock."""
        from .autoscale import ServiceModel
        return ServiceModel.from_step_samples(
            self._step_wall_s, max_running=self.max_running,
            chunk=self.chunk, page_size=self.page_size,
            num_pages=self.num_pages, max_model_len=self.max_model_len,
            max_queue=self.max_queue)

    def request_timeline(self, rid: int) -> List[dict]:
        """Every flight-recorder event for one request (requires
        FLAGS_tpu_trace; empty list otherwise) — the post-mortem view
        dumped into the incident buffer on deadline expiry."""
        return _trace.request_timeline(rid)

    # -- convenience -----------------------------------------------------
    def run(self, max_steps: Optional[int] = None) -> Dict[int, List[int]]:
        """Step until all queued/running work completes (or max_steps);
        returns rid -> generated tokens for every request that left the
        WAITING state (including cancelled/failed partials)."""
        steps = 0
        while self.has_work():
            if max_steps is not None and steps >= max_steps:
                break
            self.step()
            steps += 1
        return {rid: list(r.output) for rid, r in self._requests.items()
                if not r.state.value == "waiting"}

    def prefix_lookup(self, prompt) -> int:
        """How many tokens of ``prompt`` this engine's prefix cache
        would serve without prefill (0 when the cache is off).  Side-
        effect free — the router's locality-placement signal."""
        if self.kv.prefix is None:
            return 0
        return self.kv.prefix.peek([int(t) for t in prompt])

    def shutdown(self) -> None:
        """Drop the pools and their xmem reservation."""
        _STATS["pool_bytes"] -= self._pool_bytes
        _xmem.record_reservation("serving.kv_pages", 0)
        self._kp = self._vp = self._ks = self._vs = None
        self._step_fns.clear()
        self._copy_fn = None
        if self._draft is not None:
            self._draft.shutdown()


@dataclasses.dataclass
class _ProbeSeq:
    """Minimal ScheduledSeq stand-in for ``_batch_arrays`` during
    bisection probes (fed is always 0 — probes replay first chunks)."""

    request: Request
    slot: int
    q_len: int
    spec: int = 0

    @property
    def seq_len(self) -> int:
        return self.q_len

    @property
    def produces(self) -> bool:
        return self.q_len == self.request.num_known
