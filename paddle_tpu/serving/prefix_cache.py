"""Shared-prefix KV reuse: a radix (trie) index over refcounted pages.

Reference analog: the vLLM automatic-prefix-caching / SGLang RadixAttention
lineage, reshaped for the paged-pool serving engine (PR 10).  The Ragged
Paged Attention paper's block-table indirection (PAPERS.md) is what makes
sharing *free at the kernel level*: ``ragged_paged_attention`` reads kv
through per-request block tables, so two requests whose tables point at
the same pool page cost exactly one page of HBM and zero extra compute.
This module supplies the host-side index that finds those pages.

The trie is keyed on token-id sequences at **page granularity**: every
node holds one *full* pool page and the ``page_size`` token ids whose kv
it contains.  ``match()`` walks full-page chunks, then finishes with a
partial match against the children of the deepest node — a prompt whose
shared prefix ends mid-page still reuses that page's leading tokens
(shared system prompts rarely end on a page boundary).  A partially
matched page is **copy-on-write**: the caller forks it into a private
page before any request writes into it, so the cached copy is immutable
for future matchers.

Reference counting (``BlockAllocator`` in kv_cache.py) is the ownership
model: the trie holds exactly one reference per cached page, every
borrowing request holds one more, and a page returns to the free list
only when the last reference drops.  A cached page whose only reference
is the trie's ("refcount 0" from the requests' point of view) is
evictable; ``evict()`` sweeps those in LRU order when the scheduler's
admission watermark comes under pressure.  Completed requests *donate*
their full pages into the trie instead of freeing them — the cache
populates itself from real traffic, no warmup pass.

Invariant (asserted by tests/test_prefix_spec.py): every pool page is in
exactly one of three states — free, uniquely owned by one request
(non-cached), or cached (trie-held, with zero or more borrowers) — and
``free + uniquely-owned + cached == capacity``.
"""
from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

__all__ = ["PrefixCache", "PrefixStats"]


@dataclasses.dataclass
class PrefixStats:
    """Cumulative counters for one cache (the engine mirrors these into
    the ``serve_prefix_*`` metrics and the Profiler Serving section)."""

    lookups: int = 0
    hits: int = 0              # lookups that matched >= 1 token
    hit_tokens: int = 0        # tokens served from cached pages
    forks: int = 0             # copy-on-write forks of partial pages
    inserted_pages: int = 0    # pages donated into the trie
    deduped_pages: int = 0     # donations dropped as duplicates
    evicted_pages: int = 0     # cached pages reclaimed under pressure


class _Node:
    """One cached full page: ``chunk`` is the page_size token ids whose
    kv the pool page holds, ``children`` keys the next full chunk."""

    __slots__ = ("chunk", "page", "children", "parent")

    def __init__(self, chunk: Tuple[int, ...], page: int,
                 parent: Optional["_Node"]):
        self.chunk = chunk
        self.page = page
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}


def _common_prefix_len(a: Tuple[int, ...], b: List[int]) -> int:
    n = min(len(a), len(b))
    i = 0
    while i < n and a[i] == b[i]:
        i += 1
    return i


class PrefixCache:
    """Radix index from token-id sequences to pool pages.

    The cache never allocates pages itself — donated pages arrive with
    the donor's reference, which the trie inherits; matches hand out
    extra references via ``allocator.incref``.  The allocator is shared
    with the engine's ``PagedKVCache``, so the admission math stays
    exact: a cached page is "held" to the allocator whether zero or ten
    requests borrow it.
    """

    def __init__(self, allocator, page_size: int):
        self.allocator = allocator
        self.page_size = int(page_size)
        self._root = _Node((), 0, None)
        # LRU over nodes: oldest first; match/insert touch to the end
        self._lru: "OrderedDict[_Node, None]" = OrderedDict()
        self.stats = PrefixStats()

    # -- introspection ---------------------------------------------------
    @property
    def num_nodes(self) -> int:
        return len(self._lru)

    def cached_pages(self) -> List[int]:
        return [n.page for n in self._lru]

    def num_unreferenced(self) -> int:
        """Cached pages whose only reference is the trie's — the
        "cached(ref=0)" term of the capacity invariant, and exactly the
        pages ``evict()`` may reclaim."""
        return sum(1 for n in self._lru
                   if self.allocator.refcount(n.page) == 1)

    # -- lookup ----------------------------------------------------------
    def _touch(self, node: _Node) -> None:
        if node in self._lru:
            self._lru.move_to_end(node)

    def _walk(self, tokens: List[int], cap: int):
        """Longest full-page descent, then the best partial child.
        Returns (full_nodes, partial_node, partial_len)."""
        p = self.page_size
        node, full = self._root, []
        n = 0
        while n + p <= cap:
            child = node.children.get(tuple(tokens[n:n + p]))
            if child is None:
                break
            full.append(child)
            node = child
            n += p
        best, best_len = None, 0
        rest = tokens[n:cap]
        if rest:
            for child in node.children.values():
                m = _common_prefix_len(child.chunk, rest)
                if m > best_len:
                    best, best_len = child, m
        return full, best, best_len

    def peek(self, tokens: List[int]) -> int:
        """Dry-run match length (no refs taken, no LRU touch) — the
        router's placement signal: how many of ``tokens`` this replica
        would serve from cache."""
        cap = max(len(tokens) - 1, 0)
        full, _best, best_len = self._walk(tokens, cap)
        return len(full) * self.page_size + best_len

    def match(self, tokens: List[int], cap: Optional[int] = None):
        """Longest cached prefix of ``tokens``, capped at ``cap`` tokens
        (default ``len(tokens) - 1`` — at least one token must always be
        fed so the step can sample).

        Returns ``(pages, matched, partial)``: ``pages`` are the fully
        matched pool pages (one reference taken on each), ``matched``
        counts their tokens, and ``partial`` is ``None`` or
        ``(src_page, plen)`` — a cached page whose first ``plen`` tokens
        extend the match but which the caller must FORK (copy-on-write)
        before writing; one reference is taken on ``src_page`` and the
        caller releases it once the fork copy has executed."""
        if cap is None:
            cap = max(len(tokens) - 1, 0)
        self.stats.lookups += 1
        full, best, best_len = self._walk(tokens, cap)
        pages = []
        for node in full:
            self.allocator.incref([node.page])
            self._touch(node)
            pages.append(node.page)
        partial = None
        if best is not None and best_len > 0:
            self.allocator.incref([best.page])
            self._touch(best)
            partial = (best.page, best_len)
        matched = len(pages) * self.page_size
        if matched or partial:
            self.stats.hits += 1
            self.stats.hit_tokens += matched + best_len
        return pages, matched, partial

    def release_partial(self, src_page: int) -> None:
        """Drop the reference ``match`` took on a partial page (fork
        aborted, or the fork copy has been applied)."""
        self.allocator.decref([src_page])

    # -- donation --------------------------------------------------------
    def insert(self, tokens: List[int], pages: List[int]) -> None:
        """Donate full pages: ``pages[i]`` holds the kv of
        ``tokens[i*p:(i+1)*p]``.  The trie inherits the donor's one
        reference per page; a chunk already cached keeps the existing
        page and the donated duplicate is released instead."""
        p = self.page_size
        node = self._root
        for i, page in enumerate(pages):
            chunk = tuple(int(t) for t in tokens[i * p:(i + 1) * p])
            if len(chunk) < p:
                # defensive: never index partial chunks
                self.allocator.decref([page])
                continue
            child = node.children.get(chunk)
            if child is not None:
                # duplicate content (or the donor was borrowing this
                # very page): the trie keeps its copy, the donor's
                # reference is dropped
                self.allocator.decref([page])
                if child.page != page:
                    self.stats.deduped_pages += 1
                self._touch(child)
                node = child
                continue
            child = _Node(chunk, page, node)
            node.children[chunk] = child
            self._lru[child] = None
            self.stats.inserted_pages += 1
            node = child

    # -- eviction --------------------------------------------------------
    def _evict_node(self, node: _Node) -> List[int]:
        del node.parent.children[node.chunk]
        del self._lru[node]
        freed = self.allocator.decref([node.page])
        self.stats.evicted_pages += 1
        return freed

    def evict(self, num_pages: int) -> int:
        """LRU sweep: reclaim up to ``num_pages`` cached pages whose
        only reference is the trie's.  Only leaves are evicted (an
        interior node still anchors its children's token prefix);
        repeated passes let a freed leaf expose its parent.  Returns
        the number of pages actually returned to the free list."""
        freed = 0
        while freed < num_pages:
            progressed = False
            for node in list(self._lru):
                if node.children:
                    continue
                if self.allocator.refcount(node.page) != 1:
                    continue  # borrowed by a live request — never freed
                freed += len(self._evict_node(node))
                progressed = True
                if freed >= num_pages:
                    break
            if not progressed:
                break
        return freed
