"""Typed serving errors: the request-visible failure taxonomy.

Reference analog: the serving front ends in the vLLM lineage return
typed, retriable-or-not errors (HTTP 429 vs 500) rather than letting a
pool-exhaustion or device fault surface as a bare RuntimeError.  The
router and engine raise these so callers can branch on ``retriable``
without string-matching messages:

  * retriable (the client should back off and resend — nothing about
    the request itself is wrong): :class:`AdmissionRejected` (bounded
    queue shed the request under load), :class:`ReplicaUnavailable`
    (no live replica could place it);
  * terminal (resending the same request will fail the same way):
    :class:`DeadlineExceeded` (its SLO deadline passed while queued or
    decoding), :class:`RequestQuarantined` (bisection blamed it for a
    step failure — the poison-pill request).
"""
from __future__ import annotations

__all__ = ["ServingError", "RetriableError", "AdmissionRejected",
           "DeadlineExceeded", "RequestQuarantined",
           "ReplicaUnavailable"]


class ServingError(RuntimeError):
    """Base of every typed serving failure."""

    retriable = False


class RetriableError(ServingError):
    """The request itself is fine — the serving side was overloaded or
    degraded.  Clients should retry with backoff."""

    retriable = True


class AdmissionRejected(RetriableError):
    """Bounded admission queue shed the request (watermark load
    shedding).  The 429 of this stack."""


class DeadlineExceeded(ServingError):
    """The request's deadline passed before it finished; partial
    output (if any) was streamed but the request is terminal."""


class RequestQuarantined(ServingError):
    """Step-failure bisection blamed this request; it is quarantined
    so the remaining streams can recover via replay."""


class ReplicaUnavailable(RetriableError):
    """No live, non-draining replica could accept the request."""
