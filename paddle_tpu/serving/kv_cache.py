"""Paged KV cache: fixed-size token blocks in preallocated HBM pools.

Reference analog: vLLM's PagedAttention block manager, rebuilt for the
TPU execution model (PAPERS.md "Ragged Paged Attention").  The pools
are allocated ONCE per engine — [L, nkv, num_pages, page, d] stacked
arrays that live for the engine's lifetime and flow through the jitted
step function as donated carries — and requests own *pages* of them
via a host-side block table.  Admission control is therefore pure
bookkeeping: a request fits iff the allocator has enough free pages
for its worst case, no device allocation ever happens mid-serve.

Page 0 is reserved as the **null page**: the allocator never hands it
out, every unused block-table slot points at it, and the model's
scatter of padding-token k/v lands on it.  The ragged kernel masks by
sequence length, so the null page's contents are never read — but the
reservation means an out-of-range *table* entry is always a bug the
Level-3 verifier can catch, never a silently-aliased live page.

HBM accounting goes through ``profiler/xmem.record_reservation`` so
the capacity math (pool bytes + model weights + executable peaks) is
available to ``Profiler.summary_table()`` and ``tools/pod_report.py``
before a chip is touched — ``plan_capacity()`` is that budget as a
function.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

__all__ = ["BlockAllocator", "PagedKVCache", "kv_bytes_per_token",
           "plan_capacity"]


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


class BlockAllocator:
    """Refcounted free-list allocator over ``num_pages`` pool pages.

    Pages start single-owner (``alloc`` hands them out at refcount 1)
    and become shared through ``incref`` — the prefix cache borrows a
    cached page for every request reading it, plus one reference for
    the trie itself.  A page returns to the free list only when the
    last reference drops.

    Invariants (asserted by tests/test_serving.py and
    tests/test_prefix_spec.py):
      * page 0 is never allocated (the null page),
      * no page is freed while its refcount is > 1 (``free`` raises;
        ``decref`` only recycles at zero),
      * capacity == num_pages - 1, and free + allocated == capacity,
        where allocated counts distinct pages with refcount >= 1.
    """

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is reserved)")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        # LIFO free list: recently-freed pages are reused first, which
        # keeps the working set of pool pages small
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._owner: Dict[int, object] = {}   # allocating owner (debug)
        self._ref: Dict[int, int] = {}

    @property
    def capacity(self) -> int:
        return self.num_pages - 1

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_allocated(self) -> int:
        return len(self._ref)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def refcount(self, page: int) -> int:
        return self._ref.get(page, 0)

    def is_held(self, page: int) -> bool:
        return page in self._ref

    def alloc(self, n: int, owner=None) -> Optional[List[int]]:
        """Pop n pages at refcount 1, or None (and no change) if fewer
        are free."""
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._owner[p] = owner
            self._ref[p] = 1
        return pages

    def incref(self, pages: List[int]) -> None:
        for p in pages:
            if p == 0 or p not in self._ref:
                raise ValueError(f"incref of page {p} not allocated")
            self._ref[p] += 1

    def decref(self, pages: List[int]) -> List[int]:
        """Drop one reference per page; pages whose count reaches zero
        go back to the free list.  Returns the pages actually freed."""
        freed: List[int] = []
        for p in pages:
            if p == 0 or p not in self._ref:
                raise ValueError(f"decref of page {p} not allocated")
            self._ref[p] -= 1
            if self._ref[p] == 0:
                del self._ref[p]
                del self._owner[p]
                self._free.append(p)
                freed.append(p)
        return freed

    def free(self, pages: List[int]) -> None:
        """Single-owner release: refuses shared pages outright, so a
        caller that never took extra references keeps the old exact
        semantics (and a double free still raises)."""
        for p in pages:
            if p == 0 or p not in self._ref:
                raise ValueError(f"freeing page {p} not allocated")
            if self._ref[p] != 1:
                raise ValueError(
                    f"freeing page {p} with refcount {self._ref[p]} — "
                    "shared pages must be released via decref")
            del self._ref[p]
            del self._owner[p]
            self._free.append(p)


@dataclasses.dataclass
class _Entry:
    pages: List[int]           # pool pages, in logical-block order
    num_tokens: int = 0        # kv tokens written so far
    shared: int = 0            # leading pages borrowed from the trie


class PagedKVCache:
    """Host-side page bookkeeping for one engine: request id -> block
    list, plus the [R, Bmax] block-table assembly the kernel consumes.
    The device pools themselves are owned by the engine (they thread
    through the jitted step as donated arrays); this class never holds
    device memory.

    With a ``PrefixCache`` attached (``enable_prefix_cache``),
    ``match_prefix`` seeds a new request's block list from the trie —
    full cached pages are borrowed (one reference each), a partially
    matching page is forked copy-on-write into a private page whose
    device copy the engine drains before the next forward — and
    ``donate`` retires a finished request's full pages into the trie
    instead of freeing them."""

    def __init__(self, num_pages: int, page_size: int, max_blocks: int):
        self.allocator = BlockAllocator(num_pages, page_size)
        self.page_size = int(page_size)
        self.max_blocks = int(max_blocks)    # Bmax of the block table
        self._table: Dict[object, _Entry] = {}
        self.prefix = None                   # Optional[PrefixCache]
        # COW forks awaiting their device copy: (src_page, dst_page);
        # one src reference is held per pending pair until drained
        self._pending_copies: List[tuple] = []

    def enable_prefix_cache(self):
        from .prefix_cache import PrefixCache
        self.prefix = PrefixCache(self.allocator, self.page_size)
        return self.prefix

    # -- allocation ------------------------------------------------------
    def pages_needed(self, rid, target_tokens: int) -> int:
        """Extra pages required to grow request rid to target_tokens."""
        have = len(self._table[rid].pages) if rid in self._table else 0
        return max(_cdiv(target_tokens, self.page_size) - have, 0)

    def grow(self, rid, target_tokens: int) -> bool:
        """Ensure rid owns pages covering target_tokens.  All-or-
        nothing: returns False (state unchanged) when the pool cannot
        cover it."""
        need = self.pages_needed(rid, target_tokens)
        if _cdiv(target_tokens, self.page_size) > self.max_blocks:
            return False
        if need:
            got = self.allocator.alloc(need, owner=rid)
            if got is None:
                return False
            self._table.setdefault(rid, _Entry([])).pages.extend(got)
        self._table.setdefault(rid, _Entry([]))
        return True

    def commit(self, rid, num_tokens: int) -> None:
        """Record that rid's kv is written up to num_tokens."""
        self._table[rid].num_tokens = num_tokens

    # -- prefix cache ----------------------------------------------------
    def match_prefix(self, rid, tokens: List[int]) -> int:
        """Seed rid's block list from the prefix cache: borrow every
        fully matching cached page, fork a partially matching one
        copy-on-write.  Returns the number of tokens whose kv the
        request inherits (0 when the cache is off, rid already has
        pages, or nothing matches); the request must re-feed everything
        past that point."""
        if self.prefix is None or rid in self._table:
            return 0
        pages, matched, partial = self.prefix.match(tokens)
        entry_pages = list(pages)
        total = matched
        if partial is not None:
            src, plen = partial
            got = self.allocator.alloc(1, owner=rid)
            if got is None:
                # no private page for the fork — keep the full-page hit
                self.prefix.release_partial(src)
            else:
                # the src reference taken by match() is held until the
                # engine drains this pair (drain_copies) or the request
                # is released before the copy ran
                self._pending_copies.append((src, got[0]))
                entry_pages.append(got[0])
                total += plen
                self.prefix.stats.forks += 1
        if not entry_pages:
            return 0
        self._table[rid] = _Entry(pages=entry_pages, num_tokens=total,
                                  shared=len(pages))
        return total

    def drain_copies(self) -> List[tuple]:
        """Hand the engine the (src_page, dst_page) COW pairs to copy
        on device, dropping the src references.  The caller MUST apply
        the copies before the next forward pass or allocation — after
        this call a src page may be evicted or recycled."""
        pairs, self._pending_copies = self._pending_copies, []
        for src, _dst in pairs:
            self.allocator.decref([src])
        return pairs

    def donate(self, rid, tokens: List[int], valid_tokens: int) -> int:
        """Completion path with the cache on: full pages covering the
        first ``valid_tokens`` of ``tokens`` (the kv actually written —
        speculative scratch past it is never donated) move into the
        trie; the remainder is released.  Returns pages donated."""
        entry = self._table.pop(rid, None)
        if entry is None:
            return 0
        self._drop_pending_for(entry)
        full = min(valid_tokens // self.page_size, len(entry.pages))
        donated = entry.pages[:full]
        if self.prefix is not None and donated:
            self.prefix.insert(tokens[:full * self.page_size], donated)
        else:
            self.allocator.decref(donated)
        self.allocator.decref(entry.pages[full:])
        return len(donated)

    def evict_cached(self, num_pages: int) -> int:
        """Ask the trie to reclaim up to num_pages unreferenced cached
        pages (LRU).  No-op without a cache."""
        if self.prefix is None:
            return 0
        return self.prefix.evict(num_pages)

    def _drop_pending_for(self, entry: _Entry) -> None:
        """Cancel COW copies whose destination belongs to a request
        being torn down before the copy ran; their src refs drop."""
        if not self._pending_copies:
            return
        mine = set(entry.pages)
        keep: List[tuple] = []
        for src, dst in self._pending_copies:
            if dst in mine:
                self.allocator.decref([src])
            else:
                keep.append((src, dst))
        self._pending_copies = keep

    def release(self, rid) -> List[int]:
        """Drop all of rid's references (completion without donation,
        preemption, cancel).  Shared pages stay alive for the trie and
        any sibling readers; uniquely-owned pages return to the pool."""
        entry = self._table.pop(rid, None)
        if entry is None:
            return []
        self._drop_pending_for(entry)
        self.allocator.decref(entry.pages)
        return entry.pages

    def num_tokens(self, rid) -> int:
        return self._table[rid].num_tokens if rid in self._table else 0

    def block_row(self, rid) -> List[int]:
        """One block-table row, padded with the null page to Bmax."""
        pages = self._table[rid].pages if rid in self._table else []
        return (pages + [0] * self.max_blocks)[:self.max_blocks]

    def audit(self) -> dict:
        """Snapshot of the capacity invariant: every allocated page is
        either uniquely owned by one request, shared between requests
        and the trie, or cached with only the trie's reference — and
        ``free + unique_owned + shared + cached_idle == capacity``.
        ``ok`` is False when pages leak outside those states (e.g. a
        foreign owner holds pool pages)."""
        held = set()
        for e in self._table.values():
            held.update(e.pages)
        cached = set(self.prefix.cached_pages()) if self.prefix else set()
        free = self.allocator.num_free
        unique = len(held - cached)
        sharedc = len(held & cached)
        idle = len(cached - held)
        return {
            "free": free,
            "unique_owned": unique,
            "shared": sharedc,
            "cached_idle": idle,
            "capacity": self.allocator.capacity,
            "ok": (free + unique + sharedc + idle
                   == self.allocator.capacity
                   and self.allocator.num_allocated
                   == unique + sharedc + idle),
        }


# ---------------------------------------------------------------------------
# capacity planning (hardware-free — pod_report's serving section)
# ---------------------------------------------------------------------------

def kv_bytes_per_token(cfg, dtype_bytes: int = 2) -> int:
    """Paged-KV bytes one token costs across all layers (k and v)."""
    return (2 * cfg.num_hidden_layers * cfg.num_key_value_heads
            * cfg.head_dim * dtype_bytes)


def _param_count(cfg) -> int:
    """Dense llama parameter count from the config (embed + L blocks +
    final norm + lm_head), the number that dominates serving HBM."""
    H, I = cfg.hidden_size, cfg.intermediate_size
    nh, nkv, d = (cfg.num_attention_heads, cfg.num_key_value_heads,
                  cfg.head_dim)
    per_layer = (H * nh * d + 2 * H * nkv * d + nh * d * H  # attn
                 + 3 * H * I                                 # gated mlp
                 + 2 * H)                                    # norms
    return (cfg.vocab_size * H * 2                           # embed+head
            + cfg.num_hidden_layers * per_layer + H)


#: --kv-dtype axis of the capacity plan: page itemsize in bytes
KV_DTYPE_BYTES = {"bf16": 2, "fp16": 2, "int8": 1, "fp8": 1}


def plan_capacity(cfg, *, hbm_bytes: int, page_size: int = 128,
                  max_model_len: Optional[int] = None,
                  kv_dtype: Optional[str] = None,
                  kv_dtype_bytes: int = 2, weights_dtype_bytes: int = 2,
                  headroom_fraction: float = 0.10,
                  runtime_bytes: int = 0) -> dict:
    """HBM budget for one chip: how many pool pages fit after weights,
    and how many concurrent max-length requests that sustains.  Pure
    arithmetic — safe on a CPU-only host, used by pod_report's
    ``serving`` section and by the engine's default pool sizing.

    ``kv_dtype`` ("bf16"/"int8"/...) overrides ``kv_dtype_bytes`` and,
    for sub-2-byte pages, adds the quantized-KV path's per-page scale
    overhead: two f32 scales per (layer, kv head, page) — the parallel
    scale pools the engine allocates next to int8 page pools."""
    max_len = int(max_model_len or cfg.max_position_embeddings)
    if kv_dtype is not None:
        if kv_dtype not in KV_DTYPE_BYTES:
            raise ValueError(f"unknown kv_dtype {kv_dtype!r}; "
                             f"choose from {sorted(KV_DTYPE_BYTES)}")
        kv_dtype_bytes = KV_DTYPE_BYTES[kv_dtype]
    weights = _param_count(cfg) * weights_dtype_bytes
    usable = int(hbm_bytes * (1.0 - headroom_fraction)) - weights \
        - int(runtime_bytes)
    page_bytes = kv_bytes_per_token(cfg, kv_dtype_bytes) * page_size
    scale_bytes_per_page = 0
    if kv_dtype_bytes < 2:
        # k + v scale-pool entries across layers, f32 each
        scale_bytes_per_page = 2 * cfg.num_hidden_layers \
            * cfg.num_key_value_heads * 4
        page_bytes += scale_bytes_per_page
    num_pages = max(usable // page_bytes, 0)
    blocks_per_req = _cdiv(max_len, page_size)
    max_concurrent = (num_pages - 1) // blocks_per_req \
        if num_pages > 1 else 0
    return {
        "hbm_bytes": int(hbm_bytes),
        "weights_bytes": int(weights),
        "usable_kv_bytes": max(int(usable), 0),
        "page_size": int(page_size),
        "page_bytes": int(page_bytes),
        "kv_dtype": kv_dtype or f"{kv_dtype_bytes}B",
        "scale_bytes_per_page": int(scale_bytes_per_page),
        "num_pages": int(num_pages),
        "kv_bytes_per_token": kv_bytes_per_token(cfg, kv_dtype_bytes),
        "max_model_len": max_len,
        "blocks_per_request": int(blocks_per_req),
        "max_concurrent_requests": int(max_concurrent),
    }
