"""Paged KV cache: fixed-size token blocks in preallocated HBM pools.

Reference analog: vLLM's PagedAttention block manager, rebuilt for the
TPU execution model (PAPERS.md "Ragged Paged Attention").  The pools
are allocated ONCE per engine — [L, nkv, num_pages, page, d] stacked
arrays that live for the engine's lifetime and flow through the jitted
step function as donated carries — and requests own *pages* of them
via a host-side block table.  Admission control is therefore pure
bookkeeping: a request fits iff the allocator has enough free pages
for its worst case, no device allocation ever happens mid-serve.

Page 0 is reserved as the **null page**: the allocator never hands it
out, every unused block-table slot points at it, and the model's
scatter of padding-token k/v lands on it.  The ragged kernel masks by
sequence length, so the null page's contents are never read — but the
reservation means an out-of-range *table* entry is always a bug the
Level-3 verifier can catch, never a silently-aliased live page.

HBM accounting goes through ``profiler/xmem.record_reservation`` so
the capacity math (pool bytes + model weights + executable peaks) is
available to ``Profiler.summary_table()`` and ``tools/pod_report.py``
before a chip is touched — ``plan_capacity()`` is that budget as a
function.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

__all__ = ["BlockAllocator", "PagedKVCache", "kv_bytes_per_token",
           "plan_capacity"]


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


class BlockAllocator:
    """Free-list allocator over ``num_pages`` pool pages.

    Invariants (asserted by tests/test_serving.py):
      * page 0 is never allocated (the null page),
      * a page is owned by at most one request,
      * capacity == num_pages - 1, and free + allocated == capacity.
    """

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError("need >= 2 pages (page 0 is reserved)")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        # LIFO free list: recently-freed pages are reused first, which
        # keeps the working set of pool pages small
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._owner: Dict[int, object] = {}

    @property
    def capacity(self) -> int:
        return self.num_pages - 1

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_allocated(self) -> int:
        return len(self._owner)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int, owner=None) -> Optional[List[int]]:
        """Pop n pages, or None (and no change) if fewer are free."""
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._owner[p] = owner
        return pages

    def free(self, pages: List[int]) -> None:
        for p in pages:
            if p == 0 or p not in self._owner:
                raise ValueError(f"freeing page {p} not allocated")
            del self._owner[p]
            self._free.append(p)


@dataclasses.dataclass
class _Entry:
    pages: List[int]           # pool pages, in logical-block order
    num_tokens: int = 0        # kv tokens written so far


class PagedKVCache:
    """Host-side page bookkeeping for one engine: request id -> block
    list, plus the [R, Bmax] block-table assembly the kernel consumes.
    The device pools themselves are owned by the engine (they thread
    through the jitted step as donated arrays); this class never holds
    device memory."""

    def __init__(self, num_pages: int, page_size: int, max_blocks: int):
        self.allocator = BlockAllocator(num_pages, page_size)
        self.page_size = int(page_size)
        self.max_blocks = int(max_blocks)    # Bmax of the block table
        self._table: Dict[object, _Entry] = {}

    # -- allocation ------------------------------------------------------
    def pages_needed(self, rid, target_tokens: int) -> int:
        """Extra pages required to grow request rid to target_tokens."""
        have = len(self._table[rid].pages) if rid in self._table else 0
        return max(_cdiv(target_tokens, self.page_size) - have, 0)

    def grow(self, rid, target_tokens: int) -> bool:
        """Ensure rid owns pages covering target_tokens.  All-or-
        nothing: returns False (state unchanged) when the pool cannot
        cover it."""
        need = self.pages_needed(rid, target_tokens)
        if _cdiv(target_tokens, self.page_size) > self.max_blocks:
            return False
        if need:
            got = self.allocator.alloc(need, owner=rid)
            if got is None:
                return False
            self._table.setdefault(rid, _Entry([])).pages.extend(got)
        self._table.setdefault(rid, _Entry([]))
        return True

    def commit(self, rid, num_tokens: int) -> None:
        """Record that rid's kv is written up to num_tokens."""
        self._table[rid].num_tokens = num_tokens

    def release(self, rid) -> List[int]:
        """Free all of rid's pages (completion or preemption)."""
        entry = self._table.pop(rid, None)
        if entry is None:
            return []
        self.allocator.free(entry.pages)
        return entry.pages

    def num_tokens(self, rid) -> int:
        return self._table[rid].num_tokens if rid in self._table else 0

    def block_row(self, rid) -> List[int]:
        """One block-table row, padded with the null page to Bmax."""
        pages = self._table[rid].pages if rid in self._table else []
        return (pages + [0] * self.max_blocks)[:self.max_blocks]


# ---------------------------------------------------------------------------
# capacity planning (hardware-free — pod_report's serving section)
# ---------------------------------------------------------------------------

def kv_bytes_per_token(cfg, dtype_bytes: int = 2) -> int:
    """Paged-KV bytes one token costs across all layers (k and v)."""
    return (2 * cfg.num_hidden_layers * cfg.num_key_value_heads
            * cfg.head_dim * dtype_bytes)


def _param_count(cfg) -> int:
    """Dense llama parameter count from the config (embed + L blocks +
    final norm + lm_head), the number that dominates serving HBM."""
    H, I = cfg.hidden_size, cfg.intermediate_size
    nh, nkv, d = (cfg.num_attention_heads, cfg.num_key_value_heads,
                  cfg.head_dim)
    per_layer = (H * nh * d + 2 * H * nkv * d + nh * d * H  # attn
                 + 3 * H * I                                 # gated mlp
                 + 2 * H)                                    # norms
    return (cfg.vocab_size * H * 2                           # embed+head
            + cfg.num_hidden_layers * per_layer + H)


def plan_capacity(cfg, *, hbm_bytes: int, page_size: int = 128,
                  max_model_len: Optional[int] = None,
                  kv_dtype_bytes: int = 2, weights_dtype_bytes: int = 2,
                  headroom_fraction: float = 0.10,
                  runtime_bytes: int = 0) -> dict:
    """HBM budget for one chip: how many pool pages fit after weights,
    and how many concurrent max-length requests that sustains.  Pure
    arithmetic — safe on a CPU-only host, used by pod_report's
    ``serving`` section and by the engine's default pool sizing."""
    max_len = int(max_model_len or cfg.max_position_embeddings)
    weights = _param_count(cfg) * weights_dtype_bytes
    usable = int(hbm_bytes * (1.0 - headroom_fraction)) - weights \
        - int(runtime_bytes)
    page_bytes = kv_bytes_per_token(cfg, kv_dtype_bytes) * page_size
    num_pages = max(usable // page_bytes, 0)
    blocks_per_req = _cdiv(max_len, page_size)
    max_concurrent = (num_pages - 1) // blocks_per_req \
        if num_pages > 1 else 0
    return {
        "hbm_bytes": int(hbm_bytes),
        "weights_bytes": int(weights),
        "usable_kv_bytes": max(int(usable), 0),
        "page_size": int(page_size),
        "page_bytes": int(page_bytes),
        "num_pages": int(num_pages),
        "kv_bytes_per_token": kv_bytes_per_token(cfg, kv_dtype_bytes),
        "max_model_len": max_len,
        "blocks_per_request": int(blocks_per_req),
        "max_concurrent_requests": int(max_concurrent),
    }
