"""Process-wide serving stats — deliberately stdlib-only.

One mutable dict, updated by every engine and router in the process,
backing the Profiler "Serving" section.  It lives apart from
``engine.py`` so the router (and the jax-free tools built on top of
it, ``tools/fleet_sim.py`` in particular) can bump the shared
counters without importing the engine's jax stack.  ``engine.py``
re-exports ``serving_stats``/``reset_stats`` unchanged, so callers of
``paddle_tpu.serving.serving_stats()`` see no difference.
"""
from __future__ import annotations

from typing import Dict

__all__ = ["STATS", "stats_zero", "serving_stats", "reset_stats"]


def stats_zero() -> Dict[str, float]:
    return {
        "engines": 0, "requests_added": 0, "requests_finished": 0,
        "requests_preempted": 0, "steps": 0, "prefill_tokens": 0,
        "decode_tokens": 0, "peak_running": 0, "pool_bytes": 0,
        "compiled_buckets": 0,
        # work reuse (prefix cache + speculative decoding)
        "prefix_hit_tokens": 0, "prefix_evicted_pages": 0,
        "spec_proposed": 0, "spec_accepted": 0,
        # resilience counters (engine.py + router.py)
        "shed": 0, "admission_waits": 0, "callback_errors": 0,
        "recoveries": 0, "quarantined": 0, "deadline_expired": 0,
        "cancelled": 0, "failovers": 0, "replicas_dead": 0, "drains": 0,
    }


STATS: Dict[str, float] = stats_zero()


def serving_stats() -> Dict[str, float]:
    return dict(STATS)


def reset_stats() -> None:
    STATS.clear()
    STATS.update(stats_zero())
