"""Fleet service model + online SLO burn-rate autoscaler (stdlib-only).

Closes the loop between the offline fleet simulator
(``tools/fleet_sim.py``) and the live router: both consume the same
:class:`ServiceModel` — a per-replica record of measured step cost and
capacity knobs — and the same analytic :func:`replicas_for` /
:func:`recommend_fleet` arithmetic, so the min-replica answer printed
by ``pod_report serving`` is *the same computation* the simulator
validates and the live :class:`AutoscalePolicy` acts on.

The online side follows the SRE multi-window burn-rate pattern: an
error budget (fraction of requests allowed to miss the TTFT SLO) is
"burning at rate 1.0" when violations exactly spend it.  A fast
window catches spikes, a slow window confirms they are real; scale-up
fires when both burn, or earlier when the arrival-rate EWMA forecast
says the current fleet cannot clear the projected load — that is the
point of forecasting: add capacity *before* the SLO is violated, and
drain ahead of a predicted trough instead of reacting to one.

The policy only ever *recommends*.  The router surfaces the
recommendation (``Router(autoscaler=...)``, ``serve_fleet_*``
metrics, Profiler "Fleet" section) and, with ``autoscale_apply=True``,
applies the one action that needs no new hardware: draining a replica
on scale-down.  Scale-up provisioning stays with the operator.
"""
from __future__ import annotations

import dataclasses
import math
import time
from collections import deque
from typing import (Callable, Deque, Dict, List, Optional, Sequence,
                    Tuple)

__all__ = ["ServiceModel", "SLOBurnGauge", "ArrivalForecast",
           "AutoscalePolicy", "Recommendation", "replicas_for",
           "recommend_fleet", "fleet_stats", "reset_fleet_stats",
           "fleet_summary_lines", "DEFAULT_PREFILL_CHUNK_S",
           "DEFAULT_DECODE_STEP_S"]

# Uncalibrated step-cost defaults (seconds).  Shared verbatim by
# fleet_sim and pod_report so an uncalibrated sweep and an
# uncalibrated capacity report agree exactly.
DEFAULT_PREFILL_CHUNK_S = 0.020
DEFAULT_DECODE_STEP_S = 0.005


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


@dataclasses.dataclass(frozen=True)
class ServiceModel:
    """Per-replica service model: the two compiled step costs (the
    engine runs exactly two buckets — Tc=1 decode, Tc=chunk prefill)
    plus the capacity knobs that bound concurrency.  Everything fleet
    planning needs, nothing device-shaped."""

    max_running: int
    chunk: int
    page_size: int
    num_pages: int
    max_model_len: int
    max_queue: int
    prefill_chunk_s: float = DEFAULT_PREFILL_CHUNK_S
    decode_step_s: float = DEFAULT_DECODE_STEP_S
    calibrated: bool = False

    @classmethod
    def from_step_samples(cls, samples: Dict[int, Sequence[float]],
                          *, max_running: int, chunk: int,
                          page_size: int, num_pages: int,
                          max_model_len: int,
                          max_queue: int) -> "ServiceModel":
        """Calibrate from per-bucket step wall times (engine's
        ``_step_wall_s`` or trace ``serve/step`` span durations
        grouped by their ``bucket`` field).  Medians, so the one-off
        compile steps don't poison the model."""
        prefill = _median(samples.get(chunk))
        decode = _median(samples.get(1))
        return cls(
            max_running=int(max_running), chunk=int(chunk),
            page_size=int(page_size), num_pages=int(num_pages),
            max_model_len=int(max_model_len), max_queue=int(max_queue),
            prefill_chunk_s=(prefill if prefill is not None
                             else DEFAULT_PREFILL_CHUNK_S),
            decode_step_s=(decode if decode is not None
                           else DEFAULT_DECODE_STEP_S),
            calibrated=(prefill is not None or decode is not None))

    @classmethod
    def from_breakdown(cls, breakdown: Dict[str, Optional[float]], *,
                       prompt_len: int, new_tokens: int,
                       max_running: int, chunk: int, page_size: int,
                       num_pages: int, max_model_len: int,
                       max_queue: int) -> "ServiceModel":
        """Calibrate from ``slo_report()["breakdown"]`` means: prefill
        mean covers ceil(prompt/chunk) chunk steps, decode mean covers
        the remaining tokens."""
        pre = breakdown.get("prefill_mean_s")
        dec = breakdown.get("decode_mean_s")
        n_chunks = max(_cdiv(max(int(prompt_len), 1), int(chunk)), 1)
        n_decode = max(int(new_tokens) - 1, 1)
        return cls(
            max_running=int(max_running), chunk=int(chunk),
            page_size=int(page_size), num_pages=int(num_pages),
            max_model_len=int(max_model_len), max_queue=int(max_queue),
            prefill_chunk_s=(pre / n_chunks if pre
                             else DEFAULT_PREFILL_CHUNK_S),
            decode_step_s=(dec / n_decode if dec
                           else DEFAULT_DECODE_STEP_S),
            calibrated=bool(pre or dec))

    # -- capacity arithmetic ---------------------------------------------
    @property
    def blocks_per_request(self) -> int:
        return _cdiv(self.max_model_len, self.page_size)

    @property
    def concurrency(self) -> int:
        """Concurrent requests one replica sustains: slot-limited or
        page-pool-limited, whichever binds (page 0 is the reserved
        null page)."""
        pool = (self.num_pages - 1) // max(self.blocks_per_request, 1)
        return max(min(self.max_running, pool), 1)

    def steps_per_request(self, prompt_len: int,
                          new_tokens: int) -> int:
        """Slot-occupancy in engine steps: chunked prefill (the last
        chunk samples the first token), then one decode step per
        remaining token."""
        return (_cdiv(max(int(prompt_len), 1), self.chunk)
                + max(int(new_tokens) - 1, 0))

    def mean_step_s(self, prompt_len: int, new_tokens: int) -> float:
        """Expected cost of one engine step under steady load: a step
        compiles to the chunk bucket when *any* of the ``concurrency``
        rows is mid-prefill, so the prefill fraction is amortised
        across the batch, not per-row."""
        total = self.steps_per_request(prompt_len, new_tokens)
        pre = _cdiv(max(int(prompt_len), 1), self.chunk)
        row_frac = pre / max(total, 1)
        any_prefill = 1.0 - (1.0 - row_frac) ** self.concurrency
        return (any_prefill * self.prefill_chunk_s
                + (1.0 - any_prefill) * self.decode_step_s)

    def request_service_s(self, prompt_len: int,
                          new_tokens: int) -> float:
        """Unloaded end-to-end service time for one request (no queue
        wait): the TTFT/latency floor the SLO must sit above."""
        pre = _cdiv(max(int(prompt_len), 1), self.chunk)
        return (pre * self.prefill_chunk_s
                + max(int(new_tokens) - 1, 0) * self.decode_step_s)

    def capacity_rps(self, prompt_len: int, new_tokens: int) -> float:
        """Sustained throughput of one replica in requests/s: each
        request occupies a slot for ``steps_per_request`` steps and
        ``concurrency`` slots drain in parallel."""
        total = self.steps_per_request(prompt_len, new_tokens)
        return self.concurrency / (
            total * self.mean_step_s(prompt_len, new_tokens))

    def to_dict(self) -> Dict[str, object]:
        d = dataclasses.asdict(self)
        d["concurrency"] = self.concurrency
        d["blocks_per_request"] = self.blocks_per_request
        return d


def _median(xs: Optional[Sequence[float]]) -> Optional[float]:
    if not xs:
        return None
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def replicas_for(model: ServiceModel, rate_rps: float, *,
                 prompt_len: int, new_tokens: int,
                 headroom: float = 0.85) -> int:
    """Minimum replicas clearing ``rate_rps`` with ``headroom``
    utilisation margin (queues diverge at utilisation 1.0 — planning
    to 100% *is* the SLO violation)."""
    cap = model.capacity_rps(prompt_len, new_tokens) * headroom
    if rate_rps <= 0 or cap <= 0:
        return 1
    return max(int(math.ceil(rate_rps / cap)), 1)


def recommend_fleet(model: ServiceModel, arrivals, *,
                    headroom: float = 0.85,
                    peak_window_s: float = 5.0) -> Dict[str, object]:
    """The analytic fleet recommendation for a concrete workload —
    the shared answer ``pod_report serving`` prints and
    ``fleet_sim`` validates.  Sized to the *peak* windowed rate: a
    flash crowd's mean rate is a lie."""
    from . import workloads as _workloads
    arrivals = list(arrivals)
    if not arrivals:
        return {"requests": 0, "min_replicas": 1,
                "offered_rps_mean": 0.0, "offered_rps_peak": 0.0,
                "capacity_rps_per_replica": None}
    p = max(len(a.prompt) for a in arrivals)
    n = max(a.max_new_tokens for a in arrivals)
    mean = _workloads.mean_rate(arrivals)
    peak = _workloads.peak_rate(arrivals, window_s=peak_window_s)
    cap = model.capacity_rps(p, n)
    return {
        "requests": len(arrivals),
        "prompt_len": p, "new_tokens": n,
        "offered_rps_mean": round(mean, 6),
        "offered_rps_peak": round(peak, 6),
        "peak_window_s": peak_window_s,
        "capacity_rps_per_replica": round(cap, 6),
        "headroom": headroom,
        "concurrency_per_replica": model.concurrency,
        "min_replicas": replicas_for(model, peak, prompt_len=p,
                                     new_tokens=n, headroom=headroom),
    }


class SLOBurnGauge:
    """Multi-window SLO burn rate.  Each request contributes one
    ok/violation sample; over a window, burn = violation fraction /
    error budget.  1.0 = spending the budget exactly; a fast window
    at 2.0 plus a slow window above 1.0 is the classic page-worthy
    fast-burn signal."""

    def __init__(self, windows_s: Sequence[float] = (30.0, 120.0),
                 budget: float = 0.05):
        if not windows_s:
            raise ValueError("need at least one burn window")
        self.windows_s = tuple(sorted(float(w) for w in windows_s))
        self.budget = float(budget)
        self._samples: Deque[Tuple[float, bool]] = deque()

    def observe(self, ok: bool, t: float) -> None:
        self._samples.append((float(t), bool(ok)))
        horizon = t - self.windows_s[-1]
        while self._samples and self._samples[0][0] < horizon:
            self._samples.popleft()

    def burn_rates(self, now: float) -> Dict[float, Optional[float]]:
        """window -> burn rate, None when the window holds no
        samples (no traffic is not a violation)."""
        out: Dict[float, Optional[float]] = {}
        for w in self.windows_s:
            xs = [ok for (t, ok) in self._samples if t >= now - w]
            if not xs:
                out[w] = None
            else:
                frac = sum(1 for ok in xs if not ok) / len(xs)
                out[w] = frac / self.budget if self.budget > 0 else (
                    math.inf if frac else 0.0)
        return out


class ArrivalForecast:
    """EWMA arrival rate + trend.  ``observe(t)`` per admission
    attempt (offered load — shed requests still count);
    ``forecast(now, horizon_s)`` projects the rate forward so the
    policy can buy capacity *before* the spike lands."""

    def __init__(self, tau_s: float = 10.0):
        self.tau_s = float(tau_s)
        self._rate = 0.0
        self._trend = 0.0
        self._t: Optional[float] = None

    def observe(self, t: float) -> None:
        if self._t is None:
            self._t = float(t)
            return
        dt = max(float(t) - self._t, 1e-9)
        inst = 1.0 / dt
        alpha = 1.0 - math.exp(-dt / self.tau_s)
        prev = self._rate
        self._rate += alpha * (inst - self._rate)
        self._trend += alpha * ((self._rate - prev) / dt - self._trend)
        self._t = float(t)

    def rate(self, now: Optional[float] = None) -> float:
        """Current rate estimate; silence since the last arrival
        decays it (an idle stream must not hold a spike's rate)."""
        if self._t is None:
            return 0.0
        if now is None or now <= self._t:
            return self._rate
        dt = now - self._t
        inst = 1.0 / dt
        if inst >= self._rate:
            return self._rate
        alpha = 1.0 - math.exp(-dt / self.tau_s)
        return self._rate + alpha * (inst - self._rate)

    def forecast(self, now: float, horizon_s: float) -> float:
        r = self.rate(now)
        trend = self._trend if r >= self._rate * 0.5 else 0.0
        return max(r + trend * float(horizon_s), 0.0)


@dataclasses.dataclass
class Recommendation:
    """One autoscaler verdict.  ``applied`` flips when the router
    acts on it (scale-down drain) rather than just surfacing it."""

    action: str                  # "hold" | "scale_up" | "scale_down"
    target_replicas: int
    live_replicas: int
    reason: str
    at_s: float
    forecast_rps: float
    burn: Dict[float, Optional[float]]
    applied: bool = False

    def to_dict(self) -> Dict[str, object]:
        d = dataclasses.asdict(self)
        d["burn"] = {f"{w:g}s": (None if b is None else round(b, 4))
                     for w, b in self.burn.items()}
        return d


# process-wide fleet stats (Profiler "Fleet" section) — same pattern
# as serving/stats.py: one plain dict, cheap to keep unconditionally.
def _fleet_zero() -> Dict[str, float]:
    return {"policies": 0, "arrivals": 0, "ttft_samples": 0,
            "ttft_violations": 0, "recommendations": 0,
            "scale_ups": 0, "scale_downs": 0, "applied": 0,
            "last_target": 0, "last_live": 0,
            "last_forecast_rps": 0.0}


_FLEET: Dict[str, float] = _fleet_zero()


def fleet_stats() -> Dict[str, float]:
    return dict(_FLEET)


def reset_fleet_stats() -> None:
    _FLEET.clear()
    _FLEET.update(_fleet_zero())


def fleet_summary_lines() -> List[str]:
    """The "Fleet" block of Profiler.summary_table()."""
    s = _FLEET
    lines = ["Fleet"]
    if not s["policies"]:
        lines.append("  (no AutoscalePolicy instantiated)")
        return lines
    lines.append(
        f"  arrivals: {int(s['arrivals'])}  "
        f"ttft samples: {int(s['ttft_samples'])} "
        f"({int(s['ttft_violations'])} SLO violations)")
    lines.append(
        f"  recommendations: {int(s['recommendations'])}  "
        f"scale-ups: {int(s['scale_ups'])}  "
        f"scale-downs: {int(s['scale_downs'])}  "
        f"applied: {int(s['applied'])}")
    lines.append(
        f"  last: target={int(s['last_target'])} "
        f"live={int(s['last_live'])} "
        f"forecast={s['last_forecast_rps']:.2f} req/s")
    return lines


class AutoscalePolicy:
    """Recommend-only fleet sizing from live signals.

    Feeds: :meth:`observe_arrival` on every admission attempt (offered
    load), :meth:`observe_ttft` on every first token (SLO compliance).
    :meth:`recommend` combines the EWMA forecast with the multi-window
    burn gauge:

      * forecast demand > live capacity  -> scale_up (pre-violation:
        this is the flash-crowd path — the trend term fires while the
        queue is still healthy);
      * fast AND slow windows burning    -> scale_up (the reactive
        backstop when the forecast missed);
      * forecast demand < live capacity, sustained for ``cooldown_s``
        and nothing burning -> scale_down (drain ahead of the trough).

    The clock is injectable and every observe/recommend accepts an
    explicit ``t`` so the simulator can drive it on virtual time.
    """

    def __init__(self, model: ServiceModel, *,
                 slo_ttft_s: Optional[float] = None,
                 prompt_len: int = 64, new_tokens: int = 32,
                 budget: float = 0.05,
                 windows_s: Sequence[float] = (30.0, 120.0),
                 horizon_s: float = 15.0, headroom: float = 0.85,
                 min_replicas: int = 1, max_replicas: int = 64,
                 cooldown_s: float = 30.0, burn_fast: float = 2.0,
                 burn_slow: float = 1.0, forecast_tau_s: float = 10.0,
                 up_cooldown_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.model = model
        self.slo_ttft_s = slo_ttft_s
        self.prompt_len = int(prompt_len)
        self.new_tokens = int(new_tokens)
        self.horizon_s = float(horizon_s)
        self.headroom = float(headroom)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.cooldown_s = float(cooldown_s)
        self.burn_fast = float(burn_fast)
        self.burn_slow = float(burn_slow)
        # the reactive +1 bump must be paced: while both windows burn,
        # an unpaced policy adds a replica on EVERY recommend() call
        # (one per router step) and runs away to max_replicas.  One
        # bump per fast window gives the new capacity a chance to show
        # up in the burn signal before the next bump.
        self.up_cooldown_s = (float(windows_s[0] if windows_s else 30.0)
                              if up_cooldown_s is None
                              else float(up_cooldown_s))
        self._clock = clock
        self.gauge = SLOBurnGauge(windows_s, budget)
        self.forecaster = ArrivalForecast(forecast_tau_s)
        self._below_since: Optional[float] = None
        self._last_bump_s: Optional[float] = None
        self.last: Optional[Recommendation] = None
        _FLEET["policies"] += 1

    # -- signal intake ---------------------------------------------------
    def observe_arrival(self, t: Optional[float] = None) -> None:
        self.forecaster.observe(self._clock() if t is None else t)
        _FLEET["arrivals"] += 1

    def observe_ttft(self, ttft_s: float,
                     t: Optional[float] = None) -> None:
        ok = self.slo_ttft_s is None or ttft_s <= self.slo_ttft_s
        self.gauge.observe(ok, self._clock() if t is None else t)
        _FLEET["ttft_samples"] += 1
        if not ok:
            _FLEET["ttft_violations"] += 1

    # -- the verdict -----------------------------------------------------
    def _burning(self, burn: Dict[float, Optional[float]]) -> bool:
        fast_w = self.gauge.windows_s[0]
        slow_w = self.gauge.windows_s[-1]
        fast = burn.get(fast_w)
        slow = burn.get(slow_w)
        return (fast is not None and fast >= self.burn_fast
                and slow is not None and slow >= self.burn_slow)

    def recommend(self, live_replicas: int,
                  t: Optional[float] = None) -> Recommendation:
        now = self._clock() if t is None else t
        live = int(live_replicas)
        fc = self.forecaster.forecast(now, self.horizon_s)
        demand = replicas_for(self.model, fc,
                              prompt_len=self.prompt_len,
                              new_tokens=self.new_tokens,
                              headroom=self.headroom)
        burn = self.gauge.burn_rates(now)
        burning = self._burning(burn)
        target, reason = demand, (
            f"forecast {fc:.2f} req/s needs {demand} replica(s)")
        if burning and target <= live and (
                self._last_bump_s is None
                or now - self._last_bump_s >= self.up_cooldown_s):
            target = live + 1
            self._last_bump_s = now
            reason = (f"SLO burn fast/slow over "
                      f"({self.burn_fast:g}, {self.burn_slow:g}) "
                      f"thresholds — reactive scale-up")
        target = max(self.min_replicas,
                     min(self.max_replicas, target))
        if target > live:
            action = "scale_up"
            self._below_since = None
        elif target < live and not burning:
            if self._below_since is None:
                self._below_since = now
            if now - self._below_since >= self.cooldown_s:
                action = "scale_down"
                reason += (f" — sustained {self.cooldown_s:g}s below "
                           f"{live} live; drain ahead of the trough")
            else:
                action = "hold"
                target = live
        else:
            action = "hold"
            target = live
            self._below_since = None
        rec = Recommendation(
            action=action, target_replicas=target,
            live_replicas=live, reason=reason, at_s=now,
            forecast_rps=fc, burn=burn)
        self.last = rec
        _FLEET["recommendations"] += 1
        if action == "scale_up":
            _FLEET["scale_ups"] += 1
        elif action == "scale_down":
            _FLEET["scale_downs"] += 1
        _FLEET["last_target"] = target
        _FLEET["last_live"] = live
        _FLEET["last_forecast_rps"] = fc
        return rec

    def mark_applied(self, rec: Recommendation) -> None:
        rec.applied = True
        _FLEET["applied"] += 1
