"""Continuous (in-flight) batching scheduler.

Reference analog: Orca/vLLM continuous batching, shaped by the TPU
compilation model: the running batch is a FIXED array of ``max_running``
slots and every step is one of two compiled signatures (bucket Tc=1 for
pure decode, Tc=chunk when any prefill chunk is in flight), so serving
arbitrary traffic costs at most two XLA compiles per pool signature.

The unit of progress is the *fed* counter: every request knows
``prompt + output`` tokens, of which ``fed`` are written to the KV
cache.  A step feeds ``min(chunk, known - fed)`` tokens — a large gap
is chunked prefill, a gap of exactly 1 is a decode step, and a
preempted request (pages freed, ``fed`` reset to 0) re-prefills its
whole history through the same code path.  A step that closes the gap
samples the next token from the last fed position.

Per step boundary:
  * completions free their pages and open their slot;
  * WAITING requests are admitted into free slots when the page pool
    covers their first chunk (continuous admission — no draining
    between "batches"), behind a free-page watermark of one decode
    page per running request so admission cannot starve decode;
  * if the pool cannot cover a running request's next chunk, the
    youngest running request is preempted and requeued at the front.
"""
from __future__ import annotations

import dataclasses
import enum
import itertools
from collections import deque
from typing import Callable, Deque, Dict, List, Optional

from ..profiler import trace as _trace
from .kv_cache import PagedKVCache, _cdiv

__all__ = ["AdmissionGate", "Request", "RequestState", "Scheduler",
           "StepPlan", "ScheduledSeq"]

_IDS = itertools.count()


class RequestState(enum.Enum):
    WAITING = "waiting"
    RUNNING = "running"
    FINISHED = "finished"
    CANCELLED = "cancelled"   # cooperative cancel at a step boundary
    FAILED = "failed"         # deadline expiry or quarantine; see .error


@dataclasses.dataclass
class Request:
    prompt: List[int]
    max_new_tokens: int
    rid: int = dataclasses.field(default_factory=lambda: next(_IDS))
    eos_token_id: Optional[int] = None
    on_token: Optional[Callable] = None   # (rid, token, finished) -> None
    state: RequestState = RequestState.WAITING
    fed: int = 0                          # tokens written to kv
    output: List[int] = dataclasses.field(default_factory=list)
    arrival_s: float = 0.0
    admitted_s: Optional[float] = None    # first admission (engine clock)
    first_token_s: Optional[float] = None
    finish_s: Optional[float] = None
    deadline_s: Optional[float] = None    # absolute, engine clock
    error: Optional[BaseException] = None  # set when state is FAILED

    @property
    def known(self) -> List[int]:
        return self.prompt + self.output

    @property
    def num_known(self) -> int:
        return len(self.prompt) + len(self.output)

    @property
    def done(self) -> bool:
        if len(self.output) >= self.max_new_tokens:
            return True
        return (self.eos_token_id is not None and bool(self.output)
                and self.output[-1] == self.eos_token_id)


@dataclasses.dataclass
class ScheduledSeq:
    request: Request
    slot: int
    q_len: int      # tokens fed this step
    seq_len: int    # kv length after this step (fed + q_len)
    produces: bool  # True when the step closes the gap and samples
    # speculative verify chunk: q_len = 1 + spec where the trailing
    # ``spec`` tokens are draft proposals the target verifies this step
    # (multi-token verification is a short ragged prefill, so the row
    # rides the Tc=chunk bucket — no new compiled shape)
    spec: int = 0


@dataclasses.dataclass
class StepPlan:
    seqs: List[ScheduledSeq]            # occupied slots only
    bucket: int                         # compiled Tc for this step
    preempted: List[Request] = dataclasses.field(default_factory=list)
    # waiting requests that free slots could seat but the page pool
    # could not cover — they stay queued (never dropped); the engine
    # counts these as admission waits
    admission_blocked: int = 0
    # prompt tokens served from the prefix cache by this step's
    # admissions (the engine folds these into serve_prefix_* metrics)
    prefix_hit_tokens: int = 0


class AdmissionGate:
    """Watermark-hysteresis shed gate for the bounded admission queue:
    start shedding at ``max_queue`` waiting requests, keep shedding
    until the queue drains below half.  Factored out of the engine so
    the fleet simulator's replica model sheds *by the same code* —
    admitted/shed counts match a live run exactly, not approximately.
    """

    def __init__(self, max_queue: int):
        self.max_queue = int(max_queue)
        self.shedding = False

    def check(self, depth: int) -> bool:
        """Advance the hysteresis for one admission attempt at queue
        ``depth``; True means shed it."""
        if self.shedding and depth <= self.max_queue // 2:
            self.shedding = False
        if not self.shedding and depth >= self.max_queue:
            self.shedding = True
        return self.shedding

    @property
    def recover_below(self) -> int:
        return self.max_queue // 2


class Scheduler:
    def __init__(self, kv: PagedKVCache, *, max_running: int = 8,
                 chunk: int = 16, max_model_len: Optional[int] = None):
        self.kv = kv
        self.max_running = int(max_running)
        self.chunk = int(chunk)
        self.max_model_len = int(max_model_len
                                 or kv.max_blocks * kv.page_size)
        self.waiting: Deque[Request] = deque()
        # fixed slot array: index == batch row of the compiled step
        self.slots: List[Optional[Request]] = [None] * self.max_running
        self._slot_of: Dict[int, int] = {}
        # speculative lookahead: when > 0, every pure-decode row is
        # widened to a verify chunk of 1 + spec_k tokens (the engine
        # sets this iff a draft model is attached)
        self.spec_k: int = 0

    # -- queue ----------------------------------------------------------
    def add(self, req: Request) -> None:
        total = len(req.prompt) + req.max_new_tokens
        if total > self.max_model_len:
            raise ValueError(
                f"request needs {total} tokens > max_model_len "
                f"{self.max_model_len}")
        if _cdiv(total, self.kv.page_size) > self.kv.allocator.capacity:
            # genuine misconfiguration, caught at admission — this
            # request could never run even alone on an empty pool
            raise ValueError(
                f"single request exceeds pool capacity: {total} tokens "
                f"need {_cdiv(total, self.kv.page_size)} pages, pool "
                f"has {self.kv.allocator.capacity}")
        if not req.prompt:
            raise ValueError("empty prompt")
        self.waiting.append(req)

    @property
    def num_waiting(self) -> int:
        return len(self.waiting)

    @property
    def num_running(self) -> int:
        return len(self._slot_of)

    def has_work(self) -> bool:
        return bool(self.waiting or self._slot_of)

    # -- internals ------------------------------------------------------
    def _q_len(self, req: Request) -> int:
        gap = req.num_known - req.fed
        q = min(self.chunk, gap)
        if (self.spec_k > 0 and gap == 1
                and 1 + self.spec_k <= self.chunk
                and req.fed + 1 + self.spec_k <= self.max_model_len
                and _cdiv(req.fed + 1 + self.spec_k, self.kv.page_size)
                <= self.kv.max_blocks):
            q = 1 + self.spec_k
        return q

    def _try_grow(self, req: Request, target: int) -> bool:
        """grow(), with one LRU sweep of unreferenced cached pages when
        the free list alone cannot cover the target — eviction under
        watermark pressure, before any preemption."""
        if self.kv.grow(req.rid, target):
            return True
        deficit = (self.kv.pages_needed(req.rid, target)
                   - self.kv.allocator.num_free)
        if deficit > 0 and self.kv.evict_cached(deficit):
            return self.kv.grow(req.rid, target)
        return False

    def _evict_youngest(self, but_not: Request) -> Optional[Request]:
        for slot in range(self.max_running - 1, -1, -1):
            req = self.slots[slot]
            if req is None or req is but_not:
                continue
            self._release_slot(req)
            req.state = RequestState.WAITING
            req.fed = 0          # re-prefills its whole history
            self.waiting.appendleft(req)
            return req
        return None

    def _release_slot(self, req: Request) -> None:
        slot = self._slot_of.pop(req.rid)
        self.slots[slot] = None
        if self.kv.prefix is not None and req.fed >= self.kv.page_size:
            # donate the valid full pages (fed tokens of kv) so a
            # preempted request keeps its prefix hit on replay and a
            # finished request seeds future siblings; the trie holds
            # them at refcount "idle", so eviction can still reclaim
            self.kv.donate(req.rid, req.known, req.fed)
        else:
            self.kv.release(req.rid)

    # -- lifecycle ------------------------------------------------------
    def remove(self, req: Request, now_s: float = 0.0,
               state: RequestState = RequestState.CANCELLED,
               error: Optional[BaseException] = None) -> None:
        """Terminal removal at a step boundary (cancel / deadline /
        quarantine): free pages and slot if running, drop from the
        queue if waiting, stamp the terminal state."""
        if req.rid in self._slot_of:
            self._release_slot(req)
        else:
            try:
                self.waiting.remove(req)
            except ValueError:
                pass
        req.state = state
        req.error = error
        req.finish_s = now_s
        # the terminal trace event is emitted HERE, at the single site
        # every terminal transition funnels through, so "exactly one
        # terminal event per admitted request" holds by construction
        _trace.request_event(state.value, req.rid, t=now_s,
                             tokens=len(req.output),
                             error=(None if error is None
                                    else str(error)[:200]))

    def reset_running(self) -> List[Request]:
        """Pool-rebuild support: demote every running request back to
        WAITING with fed=0 (full history replay), in slot order.  Does
        NOT touch the kv cache — the caller is replacing it wholesale
        (after a failed step the donated pools are suspect)."""
        demoted: List[Request] = []
        for slot in range(self.max_running):
            req = self.slots[slot]
            if req is None:
                continue
            self.slots[slot] = None
            req.state = RequestState.WAITING
            req.fed = 0
            demoted.append(req)
        self._slot_of.clear()
        return demoted

    def requeue_front(self, reqs: List[Request]) -> None:
        """Put requests at the head of the queue, preserving order."""
        for req in reversed(reqs):
            self.waiting.appendleft(req)

    # -- the step boundary ---------------------------------------------
    def finish(self, req: Request, now_s: float = 0.0) -> None:
        """Completion at a step boundary: free pages, open the slot."""
        self._release_slot(req)
        req.state = RequestState.FINISHED
        req.finish_s = now_s
        _trace.request_event("finish", req.rid, t=now_s,
                             tokens=len(req.output))

    def schedule(self) -> StepPlan:
        """Build the next step: grow running requests' tables (with
        preemption), admit from the queue, emit the slot plan."""
        preempted: List[Request] = []

        # 1) running requests first — their next chunk must fit
        for slot in range(self.max_running):
            req = self.slots[slot]
            if req is None:
                continue
            target = req.fed + self._q_len(req)
            while not self._try_grow(req, target):
                victim = self._evict_youngest(but_not=req)
                if victim is None:
                    # alone and still can't grow — another tenant holds
                    # the pages (chaos `exhaust`, a co-located engine):
                    # preempt *itself* rather than crash; add() already
                    # rejected requests that could never fit, so this
                    # replays once pages free up
                    self._release_slot(req)
                    req.state = RequestState.WAITING
                    req.fed = 0
                    self.waiting.appendleft(req)
                    preempted.append(req)
                    break
                preempted.append(victim)

        # 2) continuous admission into free slots, behind a watermark
        # of one decode page per running request.  The prefix cache is
        # consulted first: the matched head of the prompt is borrowed
        # (refcounts bumped, nothing allocated), so the request is
        # charged — in both pages and watermark math — only for its
        # uncached tail.
        admission_blocked = 0
        prefix_hit_tokens = 0
        while self.waiting and self.num_running < self.max_running:
            req = self.waiting[0]
            matched = self.kv.match_prefix(req.rid, req.known)
            if matched:
                req.fed = matched
            first = req.fed + min(self.chunk, req.num_known - req.fed)
            need = self.kv.pages_needed(req.rid, first)
            watermark = sum(
                1 for r in self.slots if r is not None
                and self.kv.pages_needed(r.rid, r.fed + 1))
            deficit = need + watermark - self.kv.allocator.num_free
            if deficit > 0:
                self.kv.evict_cached(deficit)
            if (self.kv.allocator.num_free - need < watermark
                    or not self.kv.grow(req.rid, first)):
                if matched:
                    # undo the borrow: drop the refs (and any pending
                    # COW fork) so the blocked request re-matches when
                    # it is eventually seated
                    self.kv.release(req.rid)
                    req.fed = 0
                admission_blocked = len(self.waiting)
                break
            prefix_hit_tokens += matched
            self.waiting.popleft()
            slot = self.slots.index(None)
            self.slots[slot] = req
            self._slot_of[req.rid] = slot
            req.state = RequestState.RUNNING

        # 3) emit the plan
        seqs: List[ScheduledSeq] = []
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            q_len = self._q_len(req)
            gap = req.num_known - req.fed
            seqs.append(ScheduledSeq(
                request=req, slot=slot, q_len=q_len,
                seq_len=req.fed + q_len,
                produces=req.fed + q_len >= req.num_known,
                spec=q_len - gap if gap == 1 and q_len > 1 else 0))
        bucket = self.chunk if any(s.q_len > 1 for s in seqs) else 1
        return StepPlan(seqs=seqs, bucket=bucket, preempted=preempted,
                        admission_blocked=admission_blocked,
                        prefix_hit_tokens=prefix_hit_tokens)

    def apply(self, plan: StepPlan, next_tokens: Dict[int, object],
              now_s: float = 0.0) -> List[Request]:
        """Commit a computed step: advance fed counters, append sampled
        tokens, fire callbacks, finish completed requests.
        ``next_tokens`` maps slot -> sampled token id for slots whose
        step produced one; a *spec verify* slot maps to the accepted
        token list instead (1..spec+1 tokens, in stream order).
        Returns the requests that finished."""
        finished: List[Request] = []
        for s in plan.seqs:
            req = s.request
            if not s.produces:
                req.fed = s.seq_len
                self.kv.commit(req.rid, req.fed)
                continue
            out = next_tokens[s.slot]
            toks = ([int(t) for t in out] if isinstance(out, (list, tuple))
                    else [int(out)])
            appended = 0
            for tok in toks:
                req.output.append(tok)
                appended += 1
                if req.first_token_s is None:
                    req.first_token_s = now_s
                    _trace.request_event("first_token", req.rid, t=now_s)
                done = req.done
                if req.on_token is not None:
                    req.on_token(req.rid, tok, done)
                if done:
                    break
            # a verify chunk's kv is valid only through the accepted
            # tokens — the rejected tail is stale scratch the next
            # step's feed overwrites before any read.  Non-spec rows
            # keep the exact old bookkeeping: every fed token's kv is
            # real, fed advances by the full chunk.
            req.fed = (s.seq_len - s.q_len + appended if s.spec
                       else s.seq_len)
            self.kv.commit(req.rid, req.fed)
            if req.done:
                finished.append(req)
        for req in finished:
            self.finish(req, now_s)
        return finished
