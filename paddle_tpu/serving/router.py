"""Multi-replica serving front door.

Reference analog: the fleet front ends in the vLLM/SGLang lineage and
the Gemma-on-Cloud-TPU serving story (PAPERS.md): "millions of users"
means N engine replicas behind one router, on capacity that can be
preempted at any time.  ROADMAP item 1(b).

The router owns the request's *identity* (gid, prompt, delivered
tokens, deadline); each engine owns only the replica-local decode
state.  That split is what makes every resilience path below a replay:

  * **Placement** — live, non-draining replicas ranked by queue load
    with a cache-locality bonus when the prompt's prefix was recently
    placed on the replica (shared system prompts land together, the
    prefix-cache groundwork).  A replica that sheds
    (:class:`AdmissionRejected`) is skipped; if every live replica
    sheds, the rejection propagates to the caller — typed, retriable.
  * **Liveness** — every replica heartbeats by making step progress;
    :class:`~paddle_tpu.runtime.health.HeartbeatTracker` (the same
    observer-clock rule the cross-rank HealthMonitor uses) declares a
    replica dead when its beat counter stalls past the timeout, and a
    step that raises (or blows ``step_timeout_s``) kills the replica
    immediately.
  * **Failover** — a dead replica's requests are resubmitted to the
    survivors as ``prompt + delivered_tokens`` with the remaining
    token budget: greedy decode makes the continuation bit-identical
    to the uninterrupted stream, and because the router resumes from
    what was already *delivered*, replay is idempotent — no token is
    streamed twice.
  * **Drain** — SIGTERM (or an explicit ``drain()``) stops placement
    on the replica and migrates its queued + in-flight requests to
    the survivors, the preemption-notice path.

Engine-terminal failures (quarantine, deadline expiry) are *not*
retried — resubmitting a poison request would just poison the next
replica; the typed error is surfaced on the router request instead.
"""
from __future__ import annotations

import dataclasses
import enum
import itertools
import logging
import signal
import time
from collections import OrderedDict, deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from ..profiler import exporter as _exporter
from ..profiler import metrics as _metrics, trace as _trace
from ..runtime.health import HeartbeatTracker
from ..runtime.watchdog import record_incident, run_with_deadline
from ..testing.chaos import chaos_point
from . import stats as _stats
from .errors import (AdmissionRejected, DeadlineExceeded,
                     ReplicaUnavailable)
from .scheduler import RequestState

__all__ = ["Router", "RouterRequest", "ReplicaState", "EngineReplica",
           "replica_summary_lines", "reset_replica_stats"]

_LOG = logging.getLogger("paddle_tpu.serving")
_GIDS = itertools.count()

# replicas remember this many recent prompt prefixes for locality
_PREFIX_LRU = 64

# per-replica placement/failure tallies for the Profiler "Serving"
# section (the process-wide STATS in stats.py stay the aggregate)
_REPLICA_STATS: Dict[str, Dict[str, int]] = {}
_REPLICA_KEYS = ("placed", "shed", "failovers", "drains", "dead")


def _replica_stat(name: str, key: str, n: int = 1) -> None:
    stats = _REPLICA_STATS.setdefault(name, dict.fromkeys(_REPLICA_KEYS, 0))
    stats[key] += n


def replica_summary_lines() -> List[str]:
    """Per-replica rows for the Profiler "Serving" section; empty when
    no router has placed anything this process."""
    lines: List[str] = []
    for name in sorted(_REPLICA_STATS):
        s = _REPLICA_STATS[name]
        lines.append(
            f"  replica {name}: placed={s['placed']} shed={s['shed']} "
            f"failovers={s['failovers']} drains={s['drains']} "
            f"dead={s['dead']}")
    return lines


def reset_replica_stats() -> None:
    _REPLICA_STATS.clear()


class ReplicaState(enum.Enum):
    LIVE = "live"
    DRAINING = "draining"   # finishes nothing new; requests migrated
    DEAD = "dead"


@dataclasses.dataclass
class EngineReplica:
    name: str
    engine: object                      # LLMEngine
    state: ReplicaState = ReplicaState.LIVE
    beats: int = 0                      # liveness counter (step progress)
    prefixes: OrderedDict = dataclasses.field(
        default_factory=OrderedDict)    # prefix key -> None (LRU)


@dataclasses.dataclass
class RouterRequest:
    """One stream as the caller sees it, replica placement aside."""

    gid: int
    prompt: List[int]
    max_new_tokens: int
    eos_token_id: Optional[int]
    on_token: Optional[Callable]        # (gid, token, finished)
    tokens: List[int] = dataclasses.field(default_factory=list)
    replica: Optional[str] = None       # current placement
    rid: Optional[int] = None           # engine-local id
    finished: bool = False
    error: Optional[BaseException] = None
    deadline_abs: Optional[float] = None  # router clock
    migrations: int = 0
    arrival_s: Optional[float] = None     # router clock, at submit()
    first_token_s: Optional[float] = None  # fleet TTFT observation

    @property
    def done(self) -> bool:
        return self.finished or self.error is not None


class Router:
    """Spread an open-loop request stream over N engine replicas.

    ``engines`` may be LLMEngine instances or (name, engine) pairs;
    ``heartbeat_timeout`` is the silence (on ``clock``) after which a
    replica with a stalled beat counter is declared dead;
    ``step_timeout_s`` optionally bounds each replica's step wall-clock
    via ``run_with_deadline`` (a blown budget kills the replica);
    ``locality_prefix`` is the prompt-prefix length used for
    cache-locality placement.

    ``autoscaler`` attaches an
    :class:`~paddle_tpu.serving.autoscale.AutoscalePolicy`: the router
    feeds it every admission attempt and first-token latency, asks it
    for a verdict once per step, and surfaces the result
    (``last_recommendation``, ``serve_fleet_*`` metrics, a
    ``route/autoscale`` trace event on every non-hold).  Recommend-only
    by default; ``autoscale_apply=True`` additionally *applies* the
    one action that needs no new hardware — scale-down drains the
    least-loaded live replica (idempotent: drain() no-ops on anything
    already draining).  Scale-up stays a recommendation: provisioning
    a replica is the operator's move (or ``add_replica()``).
    """

    def __init__(self, engines, *, names: Optional[List[str]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 heartbeat_timeout: float = 10.0,
                 step_timeout_s: Optional[float] = None,
                 locality_prefix: int = 8,
                 autoscaler=None, autoscale_apply: bool = False):
        self._clock = clock
        self.autoscaler = autoscaler
        self.autoscale_apply = bool(autoscale_apply)
        self.last_recommendation = None
        self.step_timeout_s = step_timeout_s
        self.locality_prefix = int(locality_prefix)
        self._replicas: "OrderedDict[str, EngineReplica]" = OrderedDict()
        pairs = []
        for i, e in enumerate(engines):
            if isinstance(e, tuple):
                pairs.append(e)
            else:
                pairs.append((names[i] if names else f"replica{i}", e))
        for name, eng in pairs:
            self._replicas[name] = EngineReplica(name=name, engine=eng)
        if not self._replicas:
            raise ValueError("router needs at least one engine replica")
        self._tracker = HeartbeatTracker(heartbeat_timeout, clock=clock)
        self._requests: Dict[int, RouterRequest] = {}
        # (replica, rid) -> rr: the active placement index
        self._placed: Dict[Tuple[str, int], RouterRequest] = {}
        # submitted but currently unplaceable (mid-failover overload)
        self._orphans: Deque[RouterRequest] = deque()
        self._steps = 0

        _exporter.maybe_serve("router", self)

    # -- introspection ---------------------------------------------------
    def replica_states(self) -> Dict[str, str]:
        return {n: r.state.value for n, r in self._replicas.items()}

    def live_replicas(self) -> List[str]:
        return [n for n, r in self._replicas.items()
                if r.state is ReplicaState.LIVE]

    def output_of(self, gid: int) -> List[int]:
        return list(self._requests[gid].tokens)

    def error_of(self, gid: int) -> Optional[BaseException]:
        return self._requests[gid].error

    def is_finished(self, gid: int) -> bool:
        return self._requests[gid].finished

    def has_work(self) -> bool:
        if self._orphans:
            return True
        return any(not rr.done for rr in self._requests.values())

    # -- placement -------------------------------------------------------
    def _prefix_key(self, prompt: List[int]) -> Tuple[int, ...]:
        return tuple(prompt[:self.locality_prefix])

    def _rank_replicas(self, prompt: List[int]) -> List[EngineReplica]:
        """Live replicas, least-loaded first, with a cache-locality
        bonus.  When a replica's engine runs a prefix cache, the bonus
        is the *real* hit statistic — ``engine.prefix_lookup(prompt)``
        asks the radix trie how many prompt tokens it would serve
        without prefill, scaled to [0, 1] — so shared-system-prompt
        traffic converges on the replica already holding those pages.
        Without a cache the heuristic stays exactly what PR 11 shipped:
        0.5 for a recently-placed prompt prefix (LRU)."""
        key = self._prefix_key(prompt)
        ranked = []
        for rep in self._replicas.values():
            if rep.state is not ReplicaState.LIVE:
                continue
            sch = rep.engine.scheduler
            load = sch.num_waiting + sch.num_running
            lookup = getattr(rep.engine, "prefix_lookup", None)
            hit = lookup(prompt) if lookup is not None else 0
            if hit > 0:
                bonus = min(hit / max(len(prompt), 1), 1.0)
            elif key in rep.prefixes:
                bonus = 0.5
            else:
                bonus = 0.0
            score = float(load) - bonus
            ranked.append((score, len(ranked), rep))
        ranked.sort(key=lambda t: (t[0], t[1]))
        return [rep for _, _, rep in ranked]

    def _place(self, rr: RouterRequest) -> bool:
        """Try to seat rr on the best live replica.  False when no
        replica could take it (all shed, or none live)."""
        prompt = rr.prompt + rr.tokens
        remaining = rr.max_new_tokens - len(rr.tokens)
        deadline_s = None
        if rr.deadline_abs is not None:
            deadline_s = rr.deadline_abs - self._clock()
            if deadline_s <= 0:
                rr.error = DeadlineExceeded(
                    f"request {rr.gid} deadline passed during "
                    f"placement ({len(rr.tokens)} tokens streamed)")
                return True  # terminal — nothing to place
        for rep in self._rank_replicas(prompt):
            try:
                rid = rep.engine.add_request(
                    prompt, remaining, eos_token_id=rr.eos_token_id,
                    on_token=self._stream_cb(rr), deadline_s=deadline_s)
            except AdmissionRejected:
                _replica_stat(rep.name, "shed")
                if _metrics.enabled():
                    _metrics.counter("serve_router_shed_total",
                                     "Placements refused by a shedding "
                                     "replica", replica=rep.name).inc()
                continue
            key = self._prefix_key(prompt)
            rep.prefixes[key] = None
            rep.prefixes.move_to_end(key)
            while len(rep.prefixes) > _PREFIX_LRU:
                rep.prefixes.popitem(last=False)
            rr.replica, rr.rid = rep.name, rid
            self._placed[(rep.name, rid)] = rr
            _replica_stat(rep.name, "placed")
            if _metrics.enabled():
                _metrics.counter("serve_router_placed_total",
                                 "Requests seated on a replica",
                                 replica=rep.name).inc()
            _trace.event("route/place", kind="router", gid=rr.gid,
                         replica=rep.name, rid=rid,
                         migration=rr.migrations)
            return True
        return False

    def _stream_cb(self, rr: RouterRequest) -> Callable:
        def cb(rid, token, finished):
            if rr.first_token_s is None:
                rr.first_token_s = self._clock()
                if (self.autoscaler is not None
                        and rr.arrival_s is not None):
                    self.autoscaler.observe_ttft(
                        rr.first_token_s - rr.arrival_s,
                        t=rr.first_token_s)
            rr.tokens.append(int(token))
            if finished:
                rr.finished = True
            if rr.on_token is not None:
                rr.on_token(rr.gid, int(token), bool(finished))
        return cb

    def submit(self, prompt, max_new_tokens: int,
               eos_token_id: Optional[int] = None,
               on_token: Optional[Callable] = None,
               deadline_s: Optional[float] = None) -> int:
        """Admit one stream; returns its gid.  Raises
        :class:`AdmissionRejected` when every live replica sheds and
        :class:`ReplicaUnavailable` when none is live."""
        now = self._clock()
        if self.autoscaler is not None:
            # offered load: shed and unplaceable submissions still
            # count — the forecast must see the demand we turn away
            self.autoscaler.observe_arrival(t=now)
        if not self.live_replicas():
            raise ReplicaUnavailable("no live replica to place on")
        rr = RouterRequest(
            gid=next(_GIDS), prompt=[int(t) for t in prompt],
            max_new_tokens=int(max_new_tokens),
            eos_token_id=eos_token_id, on_token=on_token,
            arrival_s=now,
            deadline_abs=(None if deadline_s is None
                          else now + float(deadline_s)))
        if not self._place(rr):
            raise AdmissionRejected(
                f"all {len(self.live_replicas())} live replicas are "
                f"shedding — retry with backoff")
        self._requests[rr.gid] = rr
        return rr.gid

    def add_replica(self, name: str, engine) -> None:
        """Attach one new live replica (the scale-up provisioning
        hook): placement sees it from the next submit/step."""
        if name in self._replicas:
            raise ValueError(f"replica {name!r} already attached")
        self._replicas[name] = EngineReplica(name=name, engine=engine)
        _trace.event("route/replica_added", kind="router",
                     replica=name)
        if _metrics.enabled():
            _metrics.counter("serve_replicas_added_total",
                             "Replicas attached after construction",
                             replica=name).inc()

    # -- liveness / failure handling -------------------------------------
    def observe_beat(self, name: str) -> None:
        """External-replica hook: record one unit of step progress for
        a replica the router does not step in-process."""
        self._replicas[name].beats += 1

    def check_health(self) -> List[str]:
        """Declare replicas whose beat counter stalled past the
        heartbeat timeout dead (observer-clock rule — no cross-host
        clock needed) and fail their requests over.  Returns newly
        dead replica names."""
        newly = []
        for name, rep in self._replicas.items():
            if rep.state is not ReplicaState.LIVE:
                continue
            silent = self._tracker.observe(name, rep.beats)
            if silent > self._tracker.timeout_s:
                self._mark_dead(name, reason=(
                    f"heartbeat silent {silent:.1f}s "
                    f"(> {self._tracker.timeout_s:.1f}s)"))
                newly.append(name)
        return newly

    def _active_on(self, name: str) -> List[RouterRequest]:
        return [rr for (rep, _), rr in list(self._placed.items())
                if rep == name and not rr.done]

    def _mark_dead(self, name: str, reason: str) -> None:
        rep = self._replicas[name]
        if rep.state is ReplicaState.DEAD:
            return
        rep.state = ReplicaState.DEAD
        self._tracker.forget(name)
        _stats.STATS["replicas_dead"] += 1
        _replica_stat(name, "dead")
        _trace.event("route/replica_dead", kind="router", replica=name,
                     reason=reason[:200])
        record_incident("serve_replica_dead", replica=name,
                        reason=reason[:200])
        if _metrics.enabled():
            _metrics.counter("serve_replica_dead_total",
                             "Replicas declared dead",
                             replica=name).inc()
        victims = self._active_on(name)
        _LOG.warning("replica %s dead (%s); failing over %d request(s)",
                     name, reason, len(victims))
        for rr in victims:
            self._failover(rr)

    def _failover(self, rr: RouterRequest) -> None:
        """Move one in-flight stream off its (dead/draining) replica.
        Idempotent by construction: the resubmitted prompt is
        ``prompt + delivered``, so the continuation starts exactly
        after the last token the caller already received."""
        src = rr.replica
        self._placed.pop((rr.replica, rr.rid), None)
        rr.replica = rr.rid = None
        rr.migrations += 1
        _stats.STATS["failovers"] += 1
        if src is not None:
            _replica_stat(src, "failovers")
        _trace.event("route/failover", kind="router", gid=rr.gid,
                     src=src, delivered=len(rr.tokens))
        if _metrics.enabled():
            _metrics.counter("serve_failovers_total",
                             "In-flight requests migrated off a dead "
                             "or draining replica",
                             replica=(src or "none")).inc()
        if len(rr.tokens) >= rr.max_new_tokens or rr.finished:
            rr.finished = True
            return
        if not self._place(rr):
            self._orphans.append(rr)  # retried every step

    def drain(self, name: str) -> int:
        """Preemption notice for one replica: stop placing on it and
        migrate its queued + in-flight requests to live replicas.
        Returns the number of requests migrated."""
        rep = self._replicas[name]
        if rep.state is not ReplicaState.LIVE:
            return 0
        rep.state = ReplicaState.DRAINING
        _stats.STATS["drains"] += 1
        _replica_stat(name, "drains")
        _trace.event("route/drain", kind="router", replica=name)
        record_incident("serve_replica_drain", replica=name)
        if _metrics.enabled():
            _metrics.counter("serve_drains_total",
                             "Replica drains (preemption notices)",
                             replica=name).inc()
        victims = self._active_on(name)
        for rr in victims:
            # the replica is still alive — release its pages/slot so
            # the remaining steps (if any) don't waste them
            rep.engine.cancel(rr.rid)
            self._failover(rr)
        return len(victims)

    def install_sigterm_drain(self, name: Optional[str] = None):
        """SIGTERM → drain: ``name`` when the notice is for one
        replica, else every live replica (whole-process preemption).
        Chains any previously-installed handler."""
        prev = signal.getsignal(signal.SIGTERM)

        def _handler(signum, frame):
            targets = [name] if name is not None else self.live_replicas()
            for t in targets:
                self.drain(t)
            if callable(prev):
                prev(signum, frame)

        signal.signal(signal.SIGTERM, _handler)
        return _handler

    # -- the step loop ---------------------------------------------------
    def step(self) -> List[int]:
        """One router iteration: step every live replica (each step is
        a heartbeat), harvest completions and engine-terminal errors,
        fail over replicas that died, retry orphans.  Returns the gids
        that finished this step."""
        finished_gids: List[int] = []
        self._steps += 1
        for name in list(self._replicas):
            rep = self._replicas[name]
            if rep.state is not ReplicaState.LIVE:
                continue
            try:
                chaos_point(f"serve.replica.{name}.step",
                            step=self._steps, replica=name)
                if self.step_timeout_s is not None:
                    rids = run_with_deadline(
                        rep.engine.step, self.step_timeout_s,
                        phase=f"serve.replica.{name}", dump=False)
                else:
                    rids = rep.engine.step()
            except Exception as exc:  # noqa: BLE001 — replica failure
                self._mark_dead(name, reason=f"{type(exc).__name__}: "
                                             f"{exc}")
                continue
            rep.beats += 1
            for rid in rids:
                rr = self._placed.get((name, rid))
                if rr is not None:
                    rr.finished = True
                    finished_gids.append(rr.gid)
            # engine-terminal states (quarantine, deadline, cancel)
            # surface on the router request — never retried
            for rr in self._active_on(name):
                st = rep.engine.state_of(rr.rid)
                if st is RequestState.FAILED:
                    rr.error = rep.engine.error_of(rr.rid)
                    self._placed.pop((name, rr.rid), None)
                elif st is RequestState.CANCELLED:
                    rr.error = rr.error or DeadlineExceeded(
                        f"request {rr.gid} cancelled on {name}")
                    self._placed.pop((name, rr.rid), None)
        self.check_health()
        for _ in range(len(self._orphans)):
            rr = self._orphans.popleft()
            if rr.done:
                continue
            if not self._place(rr):
                self._orphans.append(rr)
                break  # nobody can take them this step
        if self.autoscaler is not None:
            self._autoscale_step()
        return finished_gids

    def _autoscale_step(self) -> None:
        """Ask the policy for a verdict and surface it; with
        ``autoscale_apply``, act on scale-down by draining the
        least-loaded live replica (one per step — drains migrate
        work, so pace them)."""
        live = self.live_replicas()
        rec = self.autoscaler.recommend(len(live), t=self._clock())
        self.last_recommendation = rec
        if _metrics.enabled():
            _metrics.gauge("serve_fleet_live_replicas",
                           "Live replicas behind the router").set(
                len(live))
            _metrics.gauge("serve_fleet_target_replicas",
                           "Autoscaler-recommended fleet size").set(
                rec.target_replicas)
            _metrics.gauge("serve_fleet_forecast_rps",
                           "EWMA-forecast offered load").set(
                rec.forecast_rps)
            for w, b in rec.burn.items():
                if b is not None:
                    _metrics.gauge(
                        "serve_fleet_burn_rate",
                        "SLO error-budget burn rate",
                        window=f"{w:g}s").set(b)
        if rec.action == "hold":
            return
        _trace.event("route/autoscale", kind="router",
                     action=rec.action, target=rec.target_replicas,
                     live=len(live), reason=rec.reason[:200])
        if _metrics.enabled():
            _metrics.counter("serve_fleet_scale_events_total",
                             "Non-hold autoscaler recommendations",
                             action=rec.action).inc()
        if (rec.action == "scale_down" and self.autoscale_apply
                and len(live) > max(rec.target_replicas, 1)):
            def _load(name: str) -> int:
                sch = self._replicas[name].engine.scheduler
                return sch.num_waiting + sch.num_running
            victim = min(live, key=_load)
            self.drain(victim)
            self.autoscaler.mark_applied(rec)

    def run(self, max_steps: Optional[int] = None) -> Dict[int, List[int]]:
        """Step until every submitted stream is terminal (or
        max_steps); returns gid -> delivered tokens."""
        steps = 0
        while self.has_work():
            if max_steps is not None and steps >= max_steps:
                break
            self.step()
            steps += 1
        return {gid: list(rr.tokens)
                for gid, rr in self._requests.items()}
