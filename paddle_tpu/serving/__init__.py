"""TPU serving engine: continuous batching over a paged KV cache.

The three layers, bottom-up:

  * ``kv_cache``  — page pools, block tables, the HBM capacity plan
                    (``plan_capacity``: pages-per-chip before a chip
                    is touched);
  * ``scheduler`` — continuous (in-flight) batching: chunked prefill,
                    per-step admission, completion/eviction and
                    preemption at step boundaries, fixed compiled
                    shapes;
  * ``engine``    — ``LLMEngine``: ``add_request()`` / ``step()`` /
                    streaming ``on_token`` callbacks, one jitted
                    ``models.llama.forward_paged`` call per step, plus
                    the resilience layer: bounded admission with typed
                    retriable shedding, per-request deadlines/SLOs,
                    cooperative cancellation, and step-failure
                    recovery (pool rebuild + replay, poison-request
                    bisection quarantine);
  * ``router``    — ``Router``: the multi-replica front door — load-
                    and cache-locality-aware placement, heartbeat
                    liveness, drain-on-SIGTERM, and dead-replica
                    failover with idempotent bit-identical replay;
  * ``errors``    — the typed failure taxonomy callers branch on
                    (``retriable`` or terminal).

Fleet planning rides on top: ``workloads`` (seeded synthetic arrival
processes shared by bench_serve, pod_report and tools/fleet_sim.py)
and ``autoscale`` (the per-replica ServiceModel, multi-window SLO
burn-rate gauges, and the recommend-only AutoscalePolicy the Router
surfaces).  Both are stdlib-only, like ``stats`` — the jax-free slice
the discrete-event fleet simulator loads standalone.

The attention primitive underneath is
``ops.pallas_ops.ragged_paged_attention`` — one Pallas kernel for the
whole mixed prefill+decode batch, jnp reference off-TPU.  See
docs/serving.md and docs/robustness.md ("Serving resilience").
"""
from . import autoscale, workloads  # noqa: F401
from .autoscale import (AutoscalePolicy, Recommendation,  # noqa: F401
                        ServiceModel, fleet_stats, recommend_fleet,
                        replicas_for, reset_fleet_stats)
from .engine import (LLMEngine, SLOConfig, reset_stats,  # noqa: F401
                     serving_stats, summary_lines)
from .errors import (AdmissionRejected, DeadlineExceeded,  # noqa: F401
                     ReplicaUnavailable, RequestQuarantined,
                     RetriableError, ServingError)
from .kv_cache import (KV_DTYPE_BYTES, BlockAllocator,  # noqa: F401
                       PagedKVCache, kv_bytes_per_token, plan_capacity)
from .prefix_cache import PrefixCache, PrefixStats  # noqa: F401
from .router import (EngineReplica, ReplicaState, Router,  # noqa: F401
                     RouterRequest)
from .scheduler import (AdmissionGate, Request,  # noqa: F401
                        RequestState, ScheduledSeq, Scheduler,
                        StepPlan)
from .spec_decode import (DraftModel, SpecDecodeConfig,  # noqa: F401
                          greedy_accept)

__all__ = ["LLMEngine", "SLOConfig", "serving_stats", "reset_stats",
           "summary_lines",
           "BlockAllocator", "PagedKVCache", "kv_bytes_per_token",
           "plan_capacity", "KV_DTYPE_BYTES",
           "AdmissionGate", "Request", "RequestState", "Scheduler",
           "StepPlan", "ScheduledSeq",
           "workloads", "autoscale", "AutoscalePolicy",
           "Recommendation", "ServiceModel", "fleet_stats",
           "reset_fleet_stats", "recommend_fleet", "replicas_for",
           "PrefixCache", "PrefixStats",
           "SpecDecodeConfig", "DraftModel", "greedy_accept",
           "Router", "RouterRequest", "ReplicaState", "EngineReplica",
           "ServingError", "RetriableError", "AdmissionRejected",
           "DeadlineExceeded", "RequestQuarantined",
           "ReplicaUnavailable"]
