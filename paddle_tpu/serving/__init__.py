"""TPU serving engine: continuous batching over a paged KV cache.

The three layers, bottom-up:

  * ``kv_cache``  — page pools, block tables, the HBM capacity plan
                    (``plan_capacity``: pages-per-chip before a chip
                    is touched);
  * ``scheduler`` — continuous (in-flight) batching: chunked prefill,
                    per-step admission, completion/eviction and
                    preemption at step boundaries, fixed compiled
                    shapes;
  * ``engine``    — ``LLMEngine``: ``add_request()`` / ``step()`` /
                    streaming ``on_token`` callbacks, one jitted
                    ``models.llama.forward_paged`` call per step.

The attention primitive underneath is
``ops.pallas_ops.ragged_paged_attention`` — one Pallas kernel for the
whole mixed prefill+decode batch, jnp reference off-TPU.  See
docs/serving.md.
"""
from .engine import (LLMEngine, reset_stats, serving_stats,  # noqa: F401
                     summary_lines)
from .kv_cache import (BlockAllocator, PagedKVCache,  # noqa: F401
                       kv_bytes_per_token, plan_capacity)
from .scheduler import (Request, RequestState,  # noqa: F401
                        ScheduledSeq, Scheduler, StepPlan)

__all__ = ["LLMEngine", "serving_stats", "reset_stats", "summary_lines",
           "BlockAllocator", "PagedKVCache", "kv_bytes_per_token",
           "plan_capacity", "Request", "RequestState", "Scheduler",
           "StepPlan", "ScheduledSeq"]
