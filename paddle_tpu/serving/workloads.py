"""Synthetic serving workloads — seeded, deterministic, stdlib-only.

One place defines what "diurnal" or "flash-crowd" traffic means, so a
capacity recommendation computed offline (``tools/fleet_sim.py``,
``tools/pod_report.py serving``) and a benchmark replayed live
(``bench_serve.py --workload``) describe byte-for-byte the same
request stream: same arrival offsets, same prompts, same token
budgets, for the same ``(preset, n_requests, seed, ...)`` tuple.

Arrival processes are inhomogeneous-Poisson shaped: exactly
``n_requests`` arrivals over ``horizon_s`` whose empirical density
follows the preset's intensity curve (sorted uniform quantiles mapped
through the inverse cumulative intensity — no thinning, so the count
is exact and the draw order is reproducible).

Presets:
  * ``uniform``       — constant rate, unique prompts.
  * ``shared-prefix`` — constant rate, prompts share one of
    ``n_groups`` system-prompt prefixes (prefix-cache traffic).
  * ``diurnal``       — sinusoidal day/night rate swing.
  * ``bursty``        — square-wave on/off bursts.
  * ``flash-crowd``   — steady base load, then a step-function spike
    (everyone asks about the same hot content: spike arrivals share
    a prefix group).
"""
from __future__ import annotations

import dataclasses
import math
import random
from typing import Callable, Dict, List, Optional, Sequence

__all__ = ["PRESETS", "Arrival", "validate", "generate",
           "step_schedule", "mean_rate", "peak_rate"]

PRESETS = ("uniform", "shared-prefix", "diurnal", "bursty",
           "flash-crowd")

# intensity-curve shape constants (relative units; the generator
# normalises, so only the ratios matter)
_DIURNAL_SWING = 0.8        # peak/trough amplitude around the mean
_BURST_FACTOR = 4.0         # on-phase rate vs off-phase
_BURST_PERIODS = 5          # on/off cycles per horizon
_FLASH_AT = 0.5             # spike start, fraction of horizon
_FLASH_LEN = 0.2            # spike length, fraction of horizon
_FLASH_FACTOR = 6.0         # spike rate vs base rate
_GRID = 2048                # inverse-CDF resolution


@dataclasses.dataclass(frozen=True)
class Arrival:
    """One request of a synthetic workload.  ``t_s`` is the offset
    from workload start; ``group`` tags shared-prefix cohorts
    (0 = unique prompt)."""

    t_s: float
    prompt: tuple
    max_new_tokens: int
    group: int = 0


def validate(preset: str) -> str:
    """Return ``preset`` or raise ValueError enumerating every valid
    preset (the bench_serve/fleet_sim unknown-workload diagnostic)."""
    if preset not in PRESETS:
        raise ValueError(
            f"unknown workload preset {preset!r} "
            f"(valid: {' | '.join(PRESETS)})")
    return preset


def _intensity(preset: str) -> Callable[[float], float]:
    """Relative arrival intensity over x in [0, 1]."""
    if preset in ("uniform", "shared-prefix"):
        return lambda x: 1.0
    if preset == "diurnal":
        return lambda x: 1.0 + _DIURNAL_SWING * math.sin(
            2.0 * math.pi * x)
    if preset == "bursty":
        return lambda x: (_BURST_FACTOR if (
            int(x * 2 * _BURST_PERIODS) % 2 == 0) else 1.0)
    if preset == "flash-crowd":
        return lambda x: (_FLASH_FACTOR
                          if _FLASH_AT <= x < _FLASH_AT + _FLASH_LEN
                          else 1.0)
    raise ValueError(preset)  # pragma: no cover — validate() gates


def _inverse_cdf(preset: str) -> List[float]:
    """Grid of the inverse cumulative intensity: _GRID+1 points
    mapping quantile q in [0, 1] -> time fraction x in [0, 1]."""
    fn = _intensity(preset)
    # cumulative trapezoid over a uniform grid
    xs = [i / _GRID for i in range(_GRID + 1)]
    cum = [0.0]
    for i in range(1, len(xs)):
        a, b = fn(xs[i - 1]), fn(xs[i])
        cum.append(cum[-1] + 0.5 * (a + b) / _GRID)
    total = cum[-1]
    inv: List[float] = []
    j = 0
    for i in range(_GRID + 1):
        q = total * i / _GRID
        while j < _GRID and cum[j + 1] < q:
            j += 1
        lo, hi = cum[j], cum[j + 1]
        frac = 0.0 if hi <= lo else (q - lo) / (hi - lo)
        inv.append((j + frac) / _GRID)
    return inv


def _interp(grid: Sequence[float], q: float) -> float:
    q = min(max(q, 0.0), 1.0)
    pos = q * (len(grid) - 1)
    i = min(int(pos), len(grid) - 2)
    frac = pos - i
    return grid[i] * (1.0 - frac) + grid[i + 1] * frac


def in_flash_window(t_s: float, horizon_s: float) -> bool:
    """True when ``t_s`` falls inside the flash-crowd spike window."""
    x = t_s / horizon_s if horizon_s > 0 else 0.0
    return _FLASH_AT <= x < _FLASH_AT + _FLASH_LEN


def generate(preset: str, n_requests: int, *, seed: int = 0,
             horizon_s: float = 60.0, prompt_len: int = 12,
             max_new_tokens: int = 8, vocab: int = 100,
             n_groups: int = 4,
             prefix_len: Optional[int] = None) -> List[Arrival]:
    """Exactly ``n_requests`` arrivals over ``horizon_s`` seconds,
    sorted by time, fully determined by the arguments.  ``vocab``
    bounds prompt token ids (keep it below the serving model's vocab);
    ``prefix_len`` is the shared-prefix length for grouped cohorts
    (default: half the prompt)."""
    validate(preset)
    if n_requests <= 0:
        return []
    rng = random.Random(seed)
    inv = _inverse_cdf(preset)
    if prefix_len is None:
        prefix_len = max(prompt_len // 2, 1)
    prefix_len = min(prefix_len, prompt_len)
    # one shared prefix per group, drawn up front so the group ->
    # prefix mapping is independent of arrival order
    prefixes = [tuple(rng.randrange(1, vocab) for _ in range(prefix_len))
                for _ in range(max(n_groups, 1))]
    quantiles = sorted(rng.random() for _ in range(n_requests))
    out: List[Arrival] = []
    for q in quantiles:
        t = _interp(inv, q) * horizon_s
        group = 0
        if preset == "shared-prefix":
            group = 1 + rng.randrange(max(n_groups, 1))
        elif preset == "flash-crowd" and in_flash_window(t, horizon_s):
            group = 1  # the hot content everyone is asking about
        if group:
            head = prefixes[(group - 1) % len(prefixes)]
            tail = tuple(rng.randrange(1, vocab)
                         for _ in range(prompt_len - len(head)))
            prompt = head + tail
        else:
            prompt = tuple(rng.randrange(1, vocab)
                           for _ in range(prompt_len))
        out.append(Arrival(t_s=t, prompt=prompt,
                           max_new_tokens=max_new_tokens, group=group))
    return out


def step_schedule(arrivals: Sequence[Arrival],
                  total_steps: int) -> Dict[int, List[Arrival]]:
    """Map arrival offsets onto ``total_steps`` engine-step slots
    (step index -> arrivals submitted before that step).  This is how
    a step-driven harness (bench_serve) replays a time-based workload
    without knowing wall step duration in advance: relative pacing is
    preserved, absolute time is measured, not assumed."""
    if not arrivals:
        return {}
    span = max(a.t_s for a in arrivals) or 1.0
    sched: Dict[int, List[Arrival]] = {}
    for a in arrivals:
        idx = min(int(a.t_s / span * total_steps), total_steps - 1)
        sched.setdefault(idx, []).append(a)
    return sched


def mean_rate(arrivals: Sequence[Arrival],
              horizon_s: Optional[float] = None) -> float:
    """Mean offered rate in requests/s."""
    if not arrivals:
        return 0.0
    span = horizon_s if horizon_s else (max(a.t_s for a in arrivals)
                                        or 1.0)
    return len(arrivals) / span


def peak_rate(arrivals: Sequence[Arrival],
              window_s: float = 5.0) -> float:
    """Peak offered rate: max sliding-window arrival count / window.
    The number capacity planning must clear — a flash crowd's mean
    rate is a lie."""
    if not arrivals:
        return 0.0
    ts = sorted(a.t_s for a in arrivals)
    best, lo = 0, 0
    for hi in range(len(ts)):
        while ts[hi] - ts[lo] > window_s:
            lo += 1
        best = max(best, hi - lo + 1)
    return best / window_s
