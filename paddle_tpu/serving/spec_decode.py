"""Speculative decoding: a draft model proposes, the target verifies.

Reference analog: Leviathan et al. speculative sampling, restricted to
the greedy case so the acceptance rule needs no rejection sampling and
the engine's output stays **bit-identical** to plain decode — the same
guarantee PR 11's crash-recovery replay relies on.

The mechanics fit the serving engine with no new kernel:

* The draft model keeps its **own device pools but the target's page
  ids** — same ``num_pages``/``page_size``, same ``BlockAllocator``,
  same block tables.  Every engine step mirrors the target's exact
  feed through the draft (one extra forward per step, same Tc bucket),
  so the draft's kv tracks the target's fed counter in lockstep: no
  catch-up pass, prefix-cache pages donated by one request carry valid
  draft kv for the next, and a pool rebuild resets both sides at once.
* **Proposal** is k sequential draft decodes over the running batch
  (the Tc=1 bucket, all speculating slots at once), writing draft kv
  at positions ``fed..fed+k-1`` through the already-grown block
  tables.
* **Verification** is the target forward over ``[x0, d1..dk]`` at
  positions ``fed..fed+k`` — exactly a short ragged prefill through
  the existing mixed Tc=chunk bucket (``ScheduledSeq.spec`` marks the
  row; the scheduler widened it before growth, so pages cover it).

Greedy acceptance (``greedy_accept``): with target argmax rows
``g_0..g_k`` (``g_i`` = argmax after feeding token i of the chunk),
accept drafts while ``d_i == g_{i-1}`` and emit ``g_0..g_a`` — by
induction each emitted token is exactly what single-token greedy
decode would have produced, because once ``d_i`` equals the token
plain decode would have fed, position i's kv and logits coincide with
the plain-decode step.  Rejected positions leave stale kv past the
new ``fed``, which the unified fed/known path overwrites before any
read (sequence lengths never cover unwritten positions).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["SpecDecodeConfig", "DraftModel", "greedy_accept"]


@dataclasses.dataclass
class SpecDecodeConfig:
    """Draft-model settings for one engine.

    ``cfg``/``params`` are any llama-family config + params with the
    **same vocabulary** as the target (asserted at engine init); ``k``
    is the lookahead — each pure-decode row is widened to a verify
    chunk of ``1 + k`` tokens, so ``k`` must stay below the engine's
    prefill ``chunk``."""

    cfg: object
    params: object
    k: int = 3


class DraftModel:
    """Device-side half of speculative decoding: draft pools shaped by
    the draft config but indexed by the *target's* page ids, plus the
    compiled draft forwards (one per Tc bucket, like the engine's)."""

    def __init__(self, cfg, params, *, num_pages: int, page_size: int,
                 donate: bool = False):
        from ..models import llama as _llama

        self.cfg = cfg
        self.params = params
        self._fwd = _llama.forward_paged
        self._pool_shape = (cfg.num_hidden_layers,
                            cfg.num_key_value_heads,
                            int(num_pages), int(page_size),
                            cfg.head_dim)
        self._kv_dtype = cfg.dtype
        self._donate = bool(donate)
        self._kp = jnp.zeros(self._pool_shape, self._kv_dtype)
        self._vp = jnp.zeros(self._pool_shape, self._kv_dtype)
        self._fns: Dict[int, object] = {}
        self._copy_fn = None

    def reset(self) -> None:
        """Zero the draft pools (engine pool rebuild: both sides replay
        from scratch so draft kv stays in lockstep with the target)."""
        self._kp = jnp.zeros(self._pool_shape, self._kv_dtype)
        self._vp = jnp.zeros(self._pool_shape, self._kv_dtype)

    def _fn(self, Tc: int):
        fn = self._fns.get(Tc)
        if fn is not None:
            return fn
        cfg, fwd = self.cfg, self._fwd

        def step(params, tokens, kp, vp, tbl, lens, qlens):
            logits, (kp, vp) = fwd(cfg, params, tokens, kp, vp, tbl,
                                   lens, qlens)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), kp, vp

        fn = jax.jit(step,
                     donate_argnums=(2, 3) if self._donate else ())
        self._fns[Tc] = fn
        return fn

    def forward(self, tokens, tbl, lens, qlens) -> np.ndarray:
        """One draft forward over the [R, Tc] batch: writes draft kv
        for every fed position, returns host argmax [R, Tc].  Used both
        to mirror the target's feed (output discarded) and as the
        proposal step (Tc == 1)."""
        out, self._kp, self._vp = self._fn(tokens.shape[1])(
            self.params, jnp.asarray(tokens), self._kp, self._vp,
            jnp.asarray(tbl), jnp.asarray(lens), jnp.asarray(qlens))
        return np.asarray(out)

    def copy_page(self, src, dst) -> None:
        """Copy-on-write fork on the draft pools (same page pair the
        target copied, so donated pages keep valid draft kv).  src/dst
        arrive as traced int32 scalars — one compile total."""
        if self._copy_fn is None:
            def cp(kp, vp, s, d):
                return (kp.at[:, :, d].set(kp[:, :, s]),
                        vp.at[:, :, d].set(vp[:, :, s]))

            self._copy_fn = jax.jit(
                cp, donate_argnums=(0, 1) if self._donate else ())
        self._kp, self._vp = self._copy_fn(
            self._kp, self._vp, jnp.int32(src), jnp.int32(dst))

    def propose(self, rows: List[Tuple[int, int, int, List[int]]],
                k: int, R: int, Bmax: int) -> Dict[int, List[int]]:
        """k sequential greedy draft decodes for the speculating slots.

        ``rows`` is ``(slot, last_token, fed, block_row)`` per row —
        the draft feeds ``last_token`` at position ``fed`` (its kv is
        valid through ``fed - 1`` by the mirror invariant) and chains
        its own argmax k times, writing draft kv as it goes.  Returns
        slot -> the k proposed token ids."""
        tokens = np.zeros((R, 1), np.int32)
        tbl = np.zeros((R, Bmax), np.int32)
        lens = np.zeros((R,), np.int32)
        qlens = np.zeros((R,), np.int32)
        cur: Dict[int, int] = {}
        pos: Dict[int, int] = {}
        for slot, last_tok, fed, block_row in rows:
            tbl[slot] = block_row
            cur[slot] = int(last_tok)
            pos[slot] = int(fed)
            qlens[slot] = 1
        drafts: Dict[int, List[int]] = {slot: [] for slot in cur}
        for _ in range(k):
            for slot in cur:
                tokens[slot, 0] = cur[slot]
                lens[slot] = pos[slot] + 1
            out = self.forward(tokens, tbl, lens, qlens)
            for slot in cur:
                d = int(out[slot, 0])
                drafts[slot].append(d)
                cur[slot] = d
                pos[slot] += 1
        return drafts

    def shutdown(self) -> None:
        self._kp = self._vp = None
        self._fns.clear()
        self._copy_fn = None


def greedy_accept(drafts: List[int], target_row: List[int]) -> List[int]:
    """The rejection-sampling-free acceptance rule.

    ``target_row`` holds the target's argmax at each verify position:
    ``g_0`` after the real last token, ``g_i`` after draft ``d_i``.
    Emit ``g_0``; then accept drafts left to right while
    ``d_i == g_{i-1}`` (the draft guessed exactly the token plain
    greedy decode would have fed next), emitting ``g_i`` for each.
    The first mismatch stops — everything after it was conditioned on
    a token plain decode would never have produced.  Output is
    therefore always a prefix of (and at least one token of) what
    plain greedy decode emits: bit-identical streams."""
    emitted = [int(target_row[0])]
    for i, d in enumerate(drafts):
        if int(d) != int(target_row[i]):
            break
        emitted.append(int(target_row[i + 1]))
    return emitted
