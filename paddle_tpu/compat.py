"""Reference-API compatibility shims for the last few top-level names.

These close the gap between the reference's ``paddle/__init__.py``
``__all__`` (283 names) and this package, so scripts written against
the reference import-cleanly. CUDA-specific names map to this stack's
device reality with a one-time warning — code that *selects* a CUDA
place keeps running on the accelerator that actually exists
(reference: paddle/fluid/core.py CUDAPlace, paddle/__init__.py
get_cuda_rng_state).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .core.place import _mapped_vendor_place
from .framework.random import get_rng_state, set_rng_state

__all__ = ["dtype", "batch", "tolist", "check_shape", "CUDAPlace",
           "CUDAPinnedPlace", "NPUPlace", "get_cuda_rng_state",
           "set_cuda_rng_state"]

# isinstance(x, paddle.dtype) parity: dtypes on this stack are numpy
# dtype objects (jnp.float32 etc. are scalar-type aliases coercible
# via np.dtype)
dtype = np.dtype


# single vendor-place shim; core.place owns the mapping behavior
_mapped_place = _mapped_vendor_place


class CUDAPlace:
    """reference: fluid/core CUDAPlace — compat constructor returning
    the place this build actually computes on."""

    def __new__(cls, device_id=0):
        return _mapped_place("CUDAPlace", device_id)


class CUDAPinnedPlace:
    def __new__(cls):
        return _mapped_place("CUDAPinnedPlace")


class NPUPlace:
    def __new__(cls, device_id=0):
        return _mapped_place("NPUPlace", device_id)


def get_cuda_rng_state():
    """reference: paddle.get_cuda_rng_state — one RNG state per device.
    Here the framework keeps a single splittable key; returned as a
    one-element list to match the per-device-list contract."""
    return [get_rng_state()]


def set_cuda_rng_state(state_list):
    states = state_list if isinstance(state_list, (list, tuple)) \
        else [state_list]
    if states:
        set_rng_state(states[0])


def batch(reader, batch_size, drop_last=False):
    """reference: paddle/batch.py — the legacy reader combinator:
    sample-yielding callable -> batch-yielding callable."""
    if batch_size <= 0:
        raise ValueError(f"batch_size must be positive, got {batch_size}")

    def batch_reader():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batch_reader


def tolist(x):
    """reference: tensor/to_string tolist — nested python lists."""
    arr = getattr(x, "_array", x)
    return np.asarray(arr).tolist()


def check_shape(shape, op_name="check_shape",
                expected_shape_type=(list, tuple),
                expected_element_type=(int,),
                expected_tensor_dtype=("int32", "int64")):
    """reference: fluid/data_feeder.py check_shape — validate a shape
    argument: a list/tuple of ints, or an integer Tensor."""
    from .core.tensor import Tensor

    if isinstance(shape, Tensor):
        if str(shape.dtype) not in ("int32", "int64") and \
                shape._array.dtype not in (jnp.int32, jnp.int64):
            raise TypeError(
                f"{op_name}: a Tensor shape must be int32/int64, got "
                f"{shape._array.dtype}")
        return
    if not isinstance(shape, expected_shape_type):
        raise TypeError(
            f"{op_name}: shape must be {expected_shape_type} or an "
            f"integer Tensor, got {type(shape)}")
    for item in shape:
        if isinstance(item, Tensor):
            continue
        if not isinstance(item, expected_element_type) or \
                isinstance(item, bool):
            raise TypeError(
                f"{op_name}: shape elements must be ints, got "
                f"{type(item)}")
