"""Static-analysis suite (`tpu_lint`): jaxpr + AST + kernel/SPMD checks.

Level 1 (``jaxpr_checks``) lints any traceable function *without
executing it* — hidden host callbacks in loop bodies, silent f64
promotion, int32-overflow reductions, oversized baked-in constants,
unusable donations, and collective divergence across cond branches.
Run it at trace time via ``to_static(..., lint=True)`` or globally via
``FLAGS_tpu_lint``; findings surface in the Profiler "Lint" section and
as ``lint_findings_total`` metrics.

Level 2 (``ast_checks``) lints Python source — the ``tools/tpu_lint.py``
CLI runs it over the framework itself (self-hosting, with a checked-in
baseline at ``tools/tpu_lint_baseline.json``).

Level 3 (``kernel_checks`` + ``spmd_checks``) goes below the jaxpr:
the kernel verifier intercepts every ``pl.pallas_call`` during tracing
(or replays registered kernels via ``verify_kernel`` /
``verify_registered``) and proves grid/BlockSpec divisibility, in-bounds
index maps, output coverage, Mosaic tiling legality, and VMEM budgets —
all on CPU, nothing executes. The SPMD checker abstractly executes a
jaxpr per rank-group to prove all ranks issue the same collective
sequence (deadlock-by-divergence at trace time), plus axis-name misuse
and donation-vs-sharding conflicts. Both feed ``check_jaxpr`` /
``lint_callable``; the CLI's ``--kernels`` mode runs the registry.

See docs/static_analysis.md for the rule catalogue and pragma syntax.
"""
from . import core
from . import ast_checks
from . import jaxpr_checks
from . import spmd_checks
from . import kernel_checks
from .core import (ERROR, WARNING, Finding, enabled, findings, record,
                   reset, summary_lines)
from .ast_checks import AST_RULES, check_file, check_paths, check_source
from .jaxpr_checks import (DEFAULT_CONFIG, JAXPR_RULES, check_jaxpr,
                           lint_callable, lint_traced)
from .spmd_checks import SPMD_RULES, check_spmd, collective_events
from .kernel_checks import (DEFAULT_KERNEL_CONFIG, KERNEL_RULES,
                            capture_sites, check_sites,
                            register_kernel_case, register_kernel_provider,
                            verify_kernel, verify_module, verify_registered)

__all__ = ["core", "ast_checks", "jaxpr_checks", "spmd_checks",
           "kernel_checks", "Finding", "ERROR", "WARNING", "enabled",
           "findings", "record", "reset", "summary_lines", "AST_RULES",
           "JAXPR_RULES", "SPMD_RULES", "KERNEL_RULES", "DEFAULT_CONFIG",
           "DEFAULT_KERNEL_CONFIG", "check_file", "check_paths",
           "check_source", "check_jaxpr", "check_spmd", "check_sites",
           "collective_events", "capture_sites", "lint_callable",
           "lint_traced", "register_kernel_case",
           "register_kernel_provider", "verify_kernel", "verify_module",
           "verify_registered"]
