"""Static-analysis suite (`tpu_lint`): jaxpr + AST hazard checks.

Level 1 (``jaxpr_checks``) lints any traceable function *without
executing it* — hidden host callbacks in loop bodies, silent f64
promotion, int32-overflow reductions, oversized baked-in constants,
unusable donations, and collective divergence across cond branches.
Run it at trace time via ``to_static(..., lint=True)`` or globally via
``FLAGS_tpu_lint``; findings surface in the Profiler "Lint" section and
as ``lint_findings_total`` metrics.

Level 2 (``ast_checks``) lints Python source — the ``tools/tpu_lint.py``
CLI runs it over the framework itself (self-hosting, with a checked-in
baseline at ``tools/tpu_lint_baseline.json``).

See docs/static_analysis.md for the rule catalogue and pragma syntax.
"""
from . import core
from . import ast_checks
from . import jaxpr_checks
from .core import (ERROR, WARNING, Finding, enabled, findings, record,
                   reset, summary_lines)
from .ast_checks import AST_RULES, check_file, check_paths, check_source
from .jaxpr_checks import (DEFAULT_CONFIG, JAXPR_RULES, check_jaxpr,
                           lint_callable, lint_traced)

__all__ = ["core", "ast_checks", "jaxpr_checks", "Finding", "ERROR",
           "WARNING", "enabled", "findings", "record", "reset",
           "summary_lines", "AST_RULES", "JAXPR_RULES", "DEFAULT_CONFIG",
           "check_file", "check_paths", "check_source", "check_jaxpr",
           "lint_callable", "lint_traced"]
