"""Level-1 lint: jaxpr analyzers.

Each rule walks a traced function's jaxpr (``core.walk_eqns`` — the same
eqn-by-eqn recursion as ``profiler/numerics.localize``, minus the
evaluation) and reports hazards the compiler or runtime would only
surface as slowness, wrong numbers, or a deadlock:

============================  =========  ====================================
rule                          severity   hazard
============================  =========  ====================================
host-callback-in-loop         error      pure/io/debug callback inside a
                                         scan/while body — a hidden host
                                         round-trip every iteration
f64-promotion                 warning    an op silently promotes to
                                         float64/complex128 (x64 mode) —
                                         2x memory + off the TPU fast path
int32-overflow-reduction      warning    sum/cumsum/dot over a large int32
                                         (or narrower) operand accumulates
                                         in int32 — overflow-prone
oversized-constant            warning    big array captured as a baked-in
                                         constant instead of an argument —
                                         bloats every executable + recompiles
                                         on change
unusable-donation             warning    donated buffer matches no output
                                         shape/dtype — donation silently lost
collective-divergence         error      cond branches issue different
                                         collective sequences — a deadlock
                                         precursor across the mesh
============================  =========  ====================================

All jax imports are lazy so ``tools/tpu_lint.py`` can load this package
without paying (or having) the jax import.
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional

from .core import (ERROR, WARNING, Finding, eqn_site, filter_file_pragmas,
                   sub_closed_jaxprs, walk_eqns)

__all__ = ["JAXPR_RULES", "DEFAULT_CONFIG", "check_jaxpr", "lint_callable",
           "lint_traced"]

DEFAULT_CONFIG: Dict[str, Any] = {
    # consts >= this many bytes should be arguments, not literals
    "max_const_bytes": 1 << 20,
    # reductions over >= this many int32 elements are overflow-prone
    "int_reduce_elems": 1 << 20,
}

_CALLBACK_PRIMS = {"pure_callback", "io_callback", "debug_callback",
                   "host_callback_call", "outside_call", "callback",
                   "python_callback"}

_COLLECTIVE_PRIMS = {"psum", "pmax", "pmin", "ppermute", "pbroadcast",
                     "all_gather", "all_to_all", "reduce_scatter",
                     "psum_scatter", "pgather"}

_WIDE_DTYPES = ("float64", "complex128")

# rule id -> (severity, check fn, one-line doc).  Checks take
# (closed_jaxpr, config, name) and return a list of Findings.
JAXPR_RULES: Dict[str, tuple] = {}


def _jaxpr_rule(rule_id: str, severity: str, doc: str):
    def deco(fn):
        JAXPR_RULES[rule_id] = (severity, fn, doc)
        return fn
    return deco


def _aval(v):
    return getattr(v, "aval", None)


def _dtype_name(v) -> str:
    a = _aval(v)
    return str(getattr(a, "dtype", ""))


def _finding(rule: str, severity: str, msg: str, eqn=None, name=None,
             **extra) -> Finding:
    file, line, where = eqn_site(eqn) if eqn is not None else (None, None,
                                                              "<jaxpr>")
    extra.setdefault("where", where)
    return Finding(rule=rule, severity=severity, message=msg, file=file,
                   line=line, function=name, source="jaxpr", extra=extra)


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------

@_jaxpr_rule("host-callback-in-loop", ERROR,
             "host/debug callback inside a scan or while body")
def _check_host_callbacks(closed, cfg, name) -> List[Finding]:
    out = []
    for eqn, path, in_loop in walk_eqns(closed.jaxpr):
        if in_loop and eqn.primitive.name in _CALLBACK_PRIMS:
            out.append(_finding(
                "host-callback-in-loop", ERROR,
                f"{eqn.primitive.name} inside a device loop body "
                f"({path}): the host is called back every iteration — "
                "a hidden sync point; hoist it out of the loop or batch "
                "results and transfer once",
                eqn=eqn, name=name, path=path))
    return out


@_jaxpr_rule("f64-promotion", WARNING,
             "silent promotion to float64/complex128")
def _check_f64_promotion(closed, cfg, name) -> List[Finding]:
    out = []
    for eqn, path, _ in walk_eqns(closed.jaxpr):
        if sub_closed_jaxprs(eqn):
            continue  # blame the leaf primitive inside, not the wrapper
        wide_out = [v for v in eqn.outvars
                    if _dtype_name(v) in _WIDE_DTYPES]
        if not wide_out:
            continue
        if any(_dtype_name(v) in _WIDE_DTYPES for v in eqn.invars):
            continue  # propagation, not introduction
        weak = any(getattr(_aval(v), "weak_type", False)
                   for v in eqn.invars)
        hint = ("a weakly-typed python scalar widened the result; "
                "wrap the scalar in jnp.asarray(..., dtype=...)" if weak
                else "add an explicit dtype or cast the operand")
        out.append(_finding(
            "f64-promotion", WARNING,
            f"{eqn.primitive.name} produces {_dtype_name(wide_out[0])} "
            f"from narrower inputs — {hint}",
            eqn=eqn, name=name))
    return out


_REDUCE_PRIMS = {"reduce_sum", "cumsum"}
_NARROW_INTS = ("int32", "int16", "int8", "uint32", "uint16", "uint8")


def _reduced_elems(eqn) -> int:
    a = _aval(eqn.invars[0])
    shape = getattr(a, "shape", ())
    if eqn.primitive.name in _REDUCE_PRIMS:
        axes = eqn.params.get("axes")
        if axes is None:
            axis = eqn.params.get("axis")
            axes = (axis,) if axis is not None else tuple(
                range(len(shape)))
        try:
            return math.prod(int(shape[ax]) for ax in axes)
        except (IndexError, TypeError):
            return 0
    if eqn.primitive.name == "dot_general":
        dnums = eqn.params.get("dimension_numbers")
        try:
            (lhs_contract, _), _ = dnums
            return math.prod(int(shape[ax]) for ax in lhs_contract)
        except (TypeError, ValueError, IndexError):
            return 0
    return 0


@_jaxpr_rule("int32-overflow-reduction", WARNING,
             "large reduction accumulating in a narrow integer dtype")
def _check_int_reductions(closed, cfg, name) -> List[Finding]:
    threshold = int(cfg["int_reduce_elems"])
    out = []
    for eqn, path, _ in walk_eqns(closed.jaxpr):
        if eqn.primitive.name not in _REDUCE_PRIMS | {"dot_general"}:
            continue
        dt = _dtype_name(eqn.invars[0])
        if dt not in _NARROW_INTS:
            continue
        n = _reduced_elems(eqn)
        if n >= threshold:
            out.append(_finding(
                "int32-overflow-reduction", WARNING,
                f"{eqn.primitive.name} reduces {n} {dt} elements with a "
                f"{dt} accumulator — overflow-prone; cast to int64/float32 "
                "before reducing",
                eqn=eqn, name=name, elements=n, dtype=dt))
    return out


def _const_nbytes(c) -> int:
    shape = getattr(c, "shape", None)
    dtype = getattr(c, "dtype", None)
    if shape is None or dtype is None:
        return 0
    try:
        return math.prod(int(d) for d in shape) * dtype.itemsize
    except (TypeError, AttributeError):
        return 0


def _iter_consts(closed):
    """Yield (constvar, const, owning_jaxpr) across nested sub-jaxprs."""
    jaxpr = closed.jaxpr
    consts = getattr(closed, "consts", getattr(closed, "literals", ()))
    for var, c in zip(jaxpr.constvars, consts):
        yield var, c, jaxpr
    for eqn in jaxpr.eqns:
        for sub in sub_closed_jaxprs(eqn):
            if hasattr(sub, "jaxpr"):  # only ClosedJaxprs carry consts
                yield from _iter_consts(sub)


@_jaxpr_rule("oversized-constant", WARNING,
             "large array baked into the executable as a constant")
def _check_oversized_consts(closed, cfg, name) -> List[Finding]:
    threshold = int(cfg["max_const_bytes"])
    out = []
    for var, c, jaxpr in _iter_consts(closed):
        nbytes = _const_nbytes(c)
        if nbytes < threshold:
            continue
        # attribute to the first eqn consuming the constant
        use = next((e for e in jaxpr.eqns if var in e.invars), None)
        shape = tuple(getattr(c, "shape", ()))
        out.append(_finding(
            "oversized-constant", WARNING,
            f"constant {getattr(c, 'dtype', '?')}{list(shape)} "
            f"({nbytes / (1 << 20):.1f} MiB) is baked into the "
            "executable — pass it as an argument (closed-over weights "
            "recompile on every change and bloat the serialized program)",
            eqn=use, name=name, nbytes=nbytes))
    return out


def _donation_findings(invars, donated_mask, outvars, eqn, name):
    out_avals = []
    for v in outvars:
        a = _aval(v)
        if a is not None:
            out_avals.append((tuple(getattr(a, "shape", ())),
                              str(getattr(a, "dtype", ""))))
    findings = []
    for i, (v, donated) in enumerate(zip(invars, donated_mask)):
        if not donated:
            continue
        a = _aval(v)
        sig = (tuple(getattr(a, "shape", ())),
               str(getattr(a, "dtype", "")))
        if sig in out_avals:
            out_avals.remove(sig)  # each output reuses one donation
            continue
        findings.append(_finding(
            "unusable-donation", WARNING,
            f"donated argument {i} ({sig[1]}{list(sig[0])}) matches no "
            "output shape/dtype — the buffer cannot be reused and the "
            "donation is silently dropped (and the caller's array is "
            "still invalidated)",
            eqn=eqn, name=name, arg_index=i))
    return findings


@_jaxpr_rule("unusable-donation", WARNING,
             "donated buffer that no output can reuse")
def _check_donation(closed, cfg, name, donate_argnums=()) -> List[Finding]:
    out = []
    if donate_argnums:
        invars = closed.jaxpr.invars
        mask = [i in set(donate_argnums) for i in range(len(invars))]
        out.extend(_donation_findings(invars, mask, closed.jaxpr.outvars,
                                      None, name))
    for eqn, path, _ in walk_eqns(closed.jaxpr):
        donated = eqn.params.get("donated_invars")
        if donated and any(donated):
            out.extend(_donation_findings(eqn.invars, donated, eqn.outvars,
                                          eqn, name))
    return out


def _collective_sig(closed) -> tuple:
    sig = []
    for eqn, path, _ in walk_eqns(closed):
        if eqn.primitive.name in _COLLECTIVE_PRIMS:
            axes = eqn.params.get("axes", eqn.params.get("axis_name"))
            if not isinstance(axes, tuple):
                axes = (axes,)
            sig.append((eqn.primitive.name, tuple(str(a) for a in axes)))
    return tuple(sig)


@_jaxpr_rule("collective-divergence", ERROR,
             "cond branches issue different collective sequences")
def _check_collective_divergence(closed, cfg, name) -> List[Finding]:
    out = []
    for eqn, path, _ in walk_eqns(closed.jaxpr):
        if eqn.primitive.name != "cond":
            continue
        branches = eqn.params.get("branches") or ()
        sigs = [_collective_sig(br) for br in branches]
        if len(set(sigs)) > 1:
            desc = "; ".join(
                f"branch {i}: " + (", ".join(
                    f"{p}({','.join(ax)})" for p, ax in s) or "none")
                for i, s in enumerate(sigs))
            out.append(_finding(
                "collective-divergence", ERROR,
                "cond branches issue different collective sequences — "
                "if the predicate differs across devices this deadlocks "
                f"the mesh ({desc}); issue identical collectives on every "
                "branch or hoist them out of the cond",
                eqn=eqn, name=name, branches=desc))
    return out


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def check_jaxpr(closed, name: Optional[str] = None,
                donate_argnums=(), config: Optional[dict] = None,
                rules=None, axis_names=None) -> List[Finding]:
    """Run every (or the selected) jaxpr rule over a ClosedJaxpr —
    the Level-1 rules above plus the Level-3 SPMD consistency rules
    (``spmd_checks``). Findings carry file:line from each eqn's
    source_info; pragmas in the attributed source files are honored.
    ``axis_names``, when given, is the set of mesh axes the deployment
    defines (enables the spmd-axis-misuse undefined-axis check)."""
    cfg = dict(DEFAULT_CONFIG)
    if config:
        cfg.update(config)
    out: List[Finding] = []
    for rule_id, (severity, fn, doc) in JAXPR_RULES.items():
        if rules is not None and rule_id not in rules:
            continue
        if rule_id == "unusable-donation":
            out.extend(fn(closed, cfg, name, donate_argnums=donate_argnums))
        else:
            out.extend(fn(closed, cfg, name))
    from . import spmd_checks
    out.extend(spmd_checks.check_spmd(closed, name=name,
                                      axis_names=axis_names,
                                      config=config, rules=rules))
    return filter_file_pragmas(out)


def lint_callable(fn: Callable, *args, name: Optional[str] = None,
                  donate_argnums=(), config: Optional[dict] = None,
                  rules=None, axis_names=None, **kwargs) -> List[Finding]:
    """Trace ``fn(*args, **kwargs)`` to a jaxpr (never executing it) and
    lint it. Accepts jax.ShapeDtypeStructs in place of real arrays."""
    import jax
    traced = fn if not kwargs else (lambda *a: fn(*a, **kwargs))
    closed = jax.make_jaxpr(traced)(*args)
    return check_jaxpr(closed, name=name or getattr(
        fn, "__qualname__", getattr(fn, "__name__", repr(fn))),
        donate_argnums=donate_argnums, config=config, rules=rules,
        axis_names=axis_names)


def lint_traced(jitted: Callable, dyn_arrays, name: Optional[str] = None,
                donate_argnums=()) -> List[Finding]:
    """Trace-time hook used by ``to_static``: lint a fresh jit signature
    and record the findings. Tracing runs under the Level-3 kernel
    verifier's ``capture_sites`` shim, so every ``pl.pallas_call`` the
    program reaches is verified too. Must never break the traced call —
    any analysis failure is swallowed."""
    from . import core as _core
    try:
        import jax
        from . import kernel_checks
        sites: list = []
        with kernel_checks.capture_sites(sites):
            closed = jax.make_jaxpr(jitted)(*dyn_arrays)
        found = check_jaxpr(closed, name=name,
                            donate_argnums=donate_argnums)
        if sites:
            found = found + kernel_checks.check_sites(sites, name=name)
    except Exception:
        return []
    _core.record(found)
    return found
