"""Level-3 lint, part (b): the SPMD collective-consistency checker.

A multi-host TPU program deadlocks the moment two ranks disagree about
which collective comes next.  PR 7's runtime health layer detects that
hang *after* it happens; this module proves the absence of the whole
divergence class at trace time, by abstractly executing a jaxpr per
rank-group:

* every collective is reduced to an event ``(primitive, axis names,
  dtype)`` — the wire signature that must match across ranks;
* control flow is walked structurally: ``pjit`` / ``remat`` /
  ``custom_*`` bodies are inlined (the checker is interprocedural),
  ``cond`` branches are compared event-for-event, and ``while`` /
  ``scan`` bodies contribute a repeated sub-sequence;
* a taint analysis seeded at ``axis_index`` tracks which values are
  rank-dependent, flowing through arithmetic, nested jaxprs, and loop
  carries — so the checker can distinguish "these branches differ and
  the predicate *provably* differs per rank" (a certain deadlock) from
  "these branches differ and the predicate might" (a hazard).

============================  =========  ====================================
rule                          severity   hazard
============================  =========  ====================================
spmd-divergent-collectives    error      cond branches issue different
                                         collective sequences (names, order,
                                         axes, or dtypes) — deadlock if the
                                         predicate differs across ranks;
                                         certain deadlock when the predicate
                                         is axis_index-tainted
spmd-rank-dependent-loop      error      a while loop that issues collectives
                                         with a rank-dependent trip count —
                                         some ranks issue more collectives
                                         than others
spmd-axis-misuse              error      a collective over a duplicated axis
                                         name, no axes at all, or an axis the
                                         caller's mesh does not define
spmd-donation-sharding        warning    a donated pjit input whose sharding
                                         matches no output — shape/dtype line
                                         up but the resharding copy defeats
                                         the donation
============================  =========  ====================================

Level 1's ``collective-divergence`` stays as the cheap structural check;
this module supersedes it with dtype-sensitivity, loop handling, and
rank-dependence proofs.  Like the rest of the package it imports without
jax — it only traverses jaxpr objects handed to it.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from .core import (ERROR, WARNING, Finding, eqn_site, filter_file_pragmas,
                   sub_closed_jaxprs)

__all__ = ["SPMD_RULES", "check_spmd", "collective_events",
           "rank_tainted_vars"]

SPMD_RULES: Dict[str, tuple] = {
    "spmd-divergent-collectives": (
        ERROR, "cond branches issue different collective sequences "
               "(order, axes, or dtypes)"),
    "spmd-rank-dependent-loop": (
        ERROR, "while loop with collectives has a rank-dependent "
               "trip count"),
    "spmd-axis-misuse": (
        ERROR, "collective over duplicate/empty/undefined axis names"),
    "spmd-donation-sharding": (
        WARNING, "donated pjit input whose sharding matches no output"),
}

_COLLECTIVE_PRIMS = {"psum", "pmax", "pmin", "ppermute", "pbroadcast",
                     "all_gather", "all_to_all", "reduce_scatter",
                     "psum_scatter", "pgather"}

# primitives that observe which rank they run on: taint sources
_RANK_PRIMS = {"axis_index"}

_LOOP_PRIMS = {"while", "scan"}


def _axes_of(eqn) -> Tuple[str, ...]:
    axes = eqn.params.get("axes", eqn.params.get("axis_name"))
    if axes is None:
        axes = ()
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    return tuple(str(a) for a in axes)


def _dtype_of(eqn) -> str:
    for v in eqn.invars:
        a = getattr(v, "aval", None)
        dt = getattr(a, "dtype", None)
        if dt is not None:
            return str(dt)
    return "?"


def _jaxpr_of(j):
    return getattr(j, "jaxpr", j)


# ---------------------------------------------------------------------------
# collective event sequences (the per-rank wire signature)
# ---------------------------------------------------------------------------

def collective_events(jaxpr) -> Tuple:
    """The ordered collective signature of a (Closed)Jaxpr: a tuple of
    ``(prim, axes, dtype)`` events, with cond branches folded in as a
    ``("cond", (branch_sig, ...))`` structural event and loop bodies as
    ``("loop:<prim>", body_sig)`` — two jaxprs with equal signatures
    issue, rank-for-rank, the same collectives in the same order."""
    jaxpr = _jaxpr_of(jaxpr)
    events: List[Tuple] = []
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim in _COLLECTIVE_PRIMS:
            events.append((prim, _axes_of(eqn), _dtype_of(eqn)))
        elif prim == "cond":
            branches = eqn.params.get("branches") or ()
            events.append(("cond", tuple(collective_events(b)
                                         for b in branches)))
        elif prim in _LOOP_PRIMS:
            body = (eqn.params.get("body_jaxpr")
                    or eqn.params.get("jaxpr"))
            cond_j = eqn.params.get("cond_jaxpr")
            sub = ()
            if cond_j is not None:
                sub += collective_events(cond_j)
            if body is not None:
                sub += collective_events(body)
            if sub:
                events.append((f"loop:{prim}", sub))
        else:
            for sub in sub_closed_jaxprs(eqn):  # pjit/remat/custom_*: inline
                events.extend(collective_events(sub))
    return tuple(events)


def _fmt_events(events: Sequence, limit: int = 4) -> str:
    parts = []
    for ev in events[:limit]:
        if ev[0] == "cond":
            parts.append("cond(...)")
        elif ev[0].startswith("loop:"):
            parts.append(f"{ev[0]}[{_fmt_events(ev[1])}]")
        else:
            prim, axes, dtype = ev
            parts.append(f"{prim}({','.join(axes)}):{dtype}")
    if len(events) > limit:
        parts.append(f"... +{len(events) - limit}")
    return ", ".join(parts) or "none"


# ---------------------------------------------------------------------------
# rank-dependence taint (seeded at axis_index, flows through everything)
# ---------------------------------------------------------------------------

def rank_tainted_vars(jaxpr, tainted_in: Optional[Set] = None,
                      _depth: int = 0) -> Set:
    """The set of variables in ``jaxpr`` whose value can differ across
    ranks.  ``tainted_in`` marks which of the jaxpr's invars arrive
    tainted; taint propagates through every eqn (any tainted input
    taints all outputs), into and out of nested jaxprs, and around loop
    carries (bodies are re-run to a fixpoint)."""
    jaxpr = _jaxpr_of(jaxpr)
    tainted: Set = set(tainted_in or ())
    if _depth > 16:
        return tainted

    def is_tainted(v) -> bool:
        return not hasattr(v, "val") and v in tainted  # Literals never

    changed = True
    passes = 0
    while changed and passes < 8:  # fixpoint for loop-carried taint
        changed = False
        passes += 1
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            if prim in _RANK_PRIMS:
                taint_out = True
            elif prim in _COLLECTIVE_PRIMS:
                # a full reduction over the mesh RE-synchronizes the
                # value (psum of x is rank-uniform if x's divergence is
                # what's being reduced) — but proving that needs value
                # semantics, so stay conservative: propagate input taint.
                taint_out = any(is_tainted(v) for v in eqn.invars)
            else:
                taint_out = any(is_tainted(v) for v in eqn.invars)
                subs = sub_closed_jaxprs(eqn)
                if subs and (taint_out or _has_rank_prim(subs)):
                    taint_out = _sub_taint(eqn, subs, is_tainted, _depth)
            if taint_out:
                for v in eqn.outvars:
                    if v not in tainted:
                        tainted.add(v)
                        changed = True
    return tainted


def _has_rank_prim(subs) -> bool:
    for sub in subs:
        j = _jaxpr_of(sub)
        for eqn in j.eqns:
            if eqn.primitive.name in _RANK_PRIMS:
                return True
            if _has_rank_prim(sub_closed_jaxprs(eqn)):
                return True
    return False


def _sub_taint(eqn, subs, is_tainted, depth) -> bool:
    """Whether any sub-jaxpr output of a higher-order eqn is tainted,
    mapping outer invar taint onto inner invars positionally (cond's
    leading predicate operand is dropped for branch jaxprs)."""
    for sub in subs:
        inner = _jaxpr_of(sub)
        invars = eqn.invars
        if eqn.primitive.name == "cond":
            invars = invars[1:]  # branches see the operands, not the pred
        offset = max(0, len(invars) - len(inner.invars))
        seed = set()
        for iv, ov in zip(inner.invars, invars[offset:]):
            if is_tainted(ov):
                seed.add(iv)
        inner_tainted = rank_tainted_vars(inner, seed, _depth=depth + 1)
        if any(v in inner_tainted for v in inner.outvars
               if not hasattr(v, "val")):
            return True
    return False


def _pred_is_rank_dependent(eqn, tainted: Set) -> bool:
    """cond: is the branch-index operand tainted?"""
    if not eqn.invars:
        return False
    v = eqn.invars[0]
    return not hasattr(v, "val") and v in tainted


def _while_trip_rank_dependent(eqn, tainted: Set) -> bool:
    """while: is the cond_jaxpr's predicate tainted, given carry taint
    and any axis_index inside the cond itself?"""
    cond_j = eqn.params.get("cond_jaxpr")
    if cond_j is None:
        return False
    inner = _jaxpr_of(cond_j)
    offset = max(0, len(eqn.invars) - len(inner.invars))
    seed = set()
    for iv, ov in zip(inner.invars, eqn.invars[offset:]):
        if not hasattr(ov, "val") and ov in tainted:
            seed.add(iv)
    inner_tainted = rank_tainted_vars(inner, seed)
    return any(v in inner_tainted for v in inner.outvars
               if not hasattr(v, "val"))


# ---------------------------------------------------------------------------
# the checks
# ---------------------------------------------------------------------------

def _finding(rule: str, msg: str, eqn=None, name=None, **extra) -> Finding:
    severity, _ = SPMD_RULES[rule]
    file, line, where = eqn_site(eqn) if eqn is not None else (None, None,
                                                              "<jaxpr>")
    extra.setdefault("where", where)
    return Finding(rule=rule, severity=severity, message=msg, file=file,
                   line=line, function=name, source="spmd", extra=extra)


def _walk(jaxpr, visit, _depth=0):
    """Call ``visit(eqn, jaxpr)`` for every eqn, recursing into every
    nested jaxpr (branches, bodies, pjit — the interprocedural walk)."""
    jaxpr = _jaxpr_of(jaxpr)
    if _depth > 32:
        return
    for eqn in jaxpr.eqns:
        visit(eqn, jaxpr)
        for sub in sub_closed_jaxprs(eqn):
            _walk(sub, visit, _depth + 1)


def _check_divergence(closed, name, want_cond: bool, want_loop: bool,
                      out: List[Finding]):
    """The taint-aware walk: recompute the tainted-var set for every
    nested jaxpr (seeding inner invars from outer taint), so a cond
    buried inside jit's pjit wrapper still sees its predicate's
    rank-dependence."""

    def recurse(jaxpr, tainted_in: Set, depth: int):
        jaxpr = _jaxpr_of(jaxpr)
        if depth > 16:
            return
        tainted = rank_tainted_vars(jaxpr, tainted_in)
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            if prim == "cond" and want_cond:
                _report_divergent_cond(eqn, tainted, name, out)
            if prim == "while" and want_loop:
                _report_rank_dependent_loop(eqn, tainted, name, out)
            invars = eqn.invars[1:] if prim == "cond" else eqn.invars
            for sub in sub_closed_jaxprs(eqn):
                inner = _jaxpr_of(sub)
                offset = max(0, len(invars) - len(inner.invars))
                seed = {iv for iv, ov in zip(inner.invars, invars[offset:])
                        if not hasattr(ov, "val") and ov in tainted}
                recurse(inner, seed, depth + 1)

    recurse(closed, set(), 0)


def _report_divergent_cond(eqn, tainted, name, out: List[Finding]):
    branches = eqn.params.get("branches") or ()
    sigs = [collective_events(b) for b in branches]
    if len(set(sigs)) <= 1:
        return
    rank_dep = _pred_is_rank_dependent(eqn, tainted)
    desc = "; ".join(f"branch {i}: {_fmt_events(s)}"
                     for i, s in enumerate(sigs))
    certainty = ("the predicate is derived from axis_index, so ranks "
                 "WILL take different branches — this deadlocks"
                 if rank_dep else
                 "if the predicate differs across ranks this deadlocks")
    out.append(_finding(
        "spmd-divergent-collectives",
        f"cond branches issue different collective sequences "
        f"({desc}); {certainty} the mesh at the first mismatched "
        "collective — make every branch issue the identical "
        "sequence (same order, axes, and dtypes) or hoist the "
        "collectives out of the cond",
        eqn=eqn, name=name, rank_dependent=rank_dep, branches=desc))


def _report_rank_dependent_loop(eqn, tainted, name, out: List[Finding]):
    body = eqn.params.get("body_jaxpr")
    body_events = collective_events(body) if body is not None else ()
    if not body_events:
        return
    if _while_trip_rank_dependent(eqn, tainted):
        out.append(_finding(
            "spmd-rank-dependent-loop",
            f"while loop issues collectives ({_fmt_events(body_events)}) "
            "but its trip count depends on axis_index — ranks exit "
            "after different iteration counts and the extra "
            "iterations' collectives block forever; make the trip "
            "count rank-uniform (e.g. psum/pmax the continue flag) "
            "or move the collectives out of the loop",
            eqn=eqn, name=name))


def _check_axis_misuse(closed, axis_names, name, out: List[Finding]):
    known = set(axis_names) if axis_names is not None else None

    def visit(eqn, owner):
        if eqn.primitive.name not in _COLLECTIVE_PRIMS:
            return
        axes = _axes_of(eqn)
        if len(axes) != len(set(axes)):
            out.append(_finding(
                "spmd-axis-misuse",
                f"{eqn.primitive.name} lists axis "
                f"{[a for a in axes if axes.count(a) > 1][0]!r} more than "
                f"once ({list(axes)}) — a duplicated mesh axis reduces "
                "twice over the same ranks",
                eqn=eqn, name=name, axes=list(axes)))
        elif not axes:
            # psum with an EMPTY axis tuple is jax's own identity
            # marker: shard_map's transpose inserts psum(x, ()) for
            # unmentioned-axis bookkeeping, so grad-of-shard_map jaxprs
            # legitimately contain it. Only hand-written collectives
            # with no axes are the no-op footgun.
            if eqn.primitive.name == "psum":
                return
            out.append(_finding(
                "spmd-axis-misuse",
                f"{eqn.primitive.name} names no axes — the collective "
                "is a no-op on every mesh; name the mesh axis to reduce "
                "over",
                eqn=eqn, name=name, axes=[]))
        elif known is not None:
            unknown = [a for a in axes if a not in known]
            if unknown:
                out.append(_finding(
                    "spmd-axis-misuse",
                    f"{eqn.primitive.name} reduces over axis "
                    f"{unknown[0]!r} but the mesh only defines "
                    f"{sorted(known)} — this fails (or worse, silently "
                    "rebinds) the moment the program runs on the real "
                    "mesh",
                    eqn=eqn, name=name, axes=list(axes),
                    known=sorted(known)))
    _walk(closed, visit)


def _sharding_repr(s) -> Optional[str]:
    if s is None or type(s).__name__ in ("UnspecifiedValue",):
        return None
    try:
        return repr(s)
    except Exception:  # exotic sharding object — treat as unconstrained
        return None


def _check_donation_sharding(closed, name, out: List[Finding]):
    def visit(eqn, owner):
        donated = eqn.params.get("donated_invars")
        in_sh = eqn.params.get("in_shardings")
        out_sh = eqn.params.get("out_shardings")
        if not donated or not any(donated) or in_sh is None \
                or out_sh is None:
            return
        out_slots = []
        for v, sh in zip(eqn.outvars, out_sh):
            a = getattr(v, "aval", None)
            out_slots.append((tuple(getattr(a, "shape", ())),
                              str(getattr(a, "dtype", "")),
                              _sharding_repr(sh)))
        for i, (v, don, sh) in enumerate(zip(eqn.invars, donated, in_sh)):
            if not don:
                continue
            a = getattr(v, "aval", None)
            sig = (tuple(getattr(a, "shape", ())),
                   str(getattr(a, "dtype", "")))
            srep = _sharding_repr(sh)
            if srep is None:
                continue  # unconstrained input sharding can alias anything
            matches = [o for o in out_slots if o[:2] == sig]
            if not matches:
                continue  # no shape/dtype match at all: Level 1's rule
            usable = [o for o in matches if o[2] is None or o[2] == srep]
            if usable:
                out_slots.remove(usable[0])
                continue
            out.append(_finding(
                "spmd-donation-sharding",
                f"donated argument {i} ({sig[1]}{list(sig[0])}) matches "
                "an output by shape/dtype but not by sharding — XLA "
                "inserts a resharding copy and the donated buffer "
                "cannot be reused; align in_shardings/out_shardings or "
                "drop the donation",
                eqn=eqn, name=name, arg_index=i))
    _walk(closed, visit)


# ---------------------------------------------------------------------------
# entry point (merged into jaxpr_checks.check_jaxpr)
# ---------------------------------------------------------------------------

def check_spmd(closed, name: Optional[str] = None,
               axis_names: Optional[Sequence[str]] = None,
               config: Optional[dict] = None, rules=None) -> List[Finding]:
    """Run the SPMD consistency rules over a ClosedJaxpr.
    ``axis_names``, when given, is the set of mesh axes the deployment
    actually defines (enables the undefined-axis check)."""
    out: List[Finding] = []
    want = lambda r: rules is None or r in rules
    want_cond = want("spmd-divergent-collectives")
    want_loop = want("spmd-rank-dependent-loop")
    if want_cond or want_loop:
        _check_divergence(closed, name, want_cond, want_loop, out)
    if want("spmd-axis-misuse"):
        _check_axis_misuse(closed, axis_names, name, out)
    if want("spmd-donation-sharding"):
        _check_donation_sharding(closed, name, out)
    return filter_file_pragmas(out)
