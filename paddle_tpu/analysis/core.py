"""Static-analysis core: findings, pragmas, the runtime registry, and
the lint baseline.

Reference analog: paddle/fluid/framework's ProgramDesc validation and IR
passes — the reference catches malformed static graphs *before* they
run; this package is the jax_graft equivalent for the hazards that have
actually bitten this repo (hidden host syncs, retraces, silent dtype
promotion, baked-in weights, collective divergence).

Two rule families share this core:

* ``jaxpr_checks`` walks a traced function's jaxpr (no execution) —
  see :func:`walk_eqns` for the shared recursive eqn iterator.
* ``ast_checks`` walks Python source — framework or user code — with
  the same :class:`Finding` shape, so the CLI, the baseline, and the
  Profiler "Lint" section present one stream.

Gating contract (same as ``FLAGS_tpu_metrics``): :func:`enabled` is one
dict lookup plus a bool check; with ``FLAGS_tpu_lint`` off and no
``to_static(..., lint=True)``, no per-call work happens at all — the
trace-time hook sits inside the new-signature branch, which steady-state
calls never enter.

This module is import-safe WITHOUT the paddle_tpu package (stdlib only):
``tools/tpu_lint.py`` loads ``analysis`` standalone so the CLI never
pays the jax import.
"""
from __future__ import annotations

import json
import os
import re
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Tuple

try:  # inside the paddle_tpu package: share the real flag registry
    from ..core import flags as _flags
    _FLAG_DICT = _flags._REGISTRY
except ImportError:  # standalone load (tools/tpu_lint.py) — no flags, no jax
    _FLAG_DICT = {}

_FLAG_NAME = "FLAGS_tpu_lint"

ERROR = "error"
WARNING = "warning"

__all__ = ["Finding", "ERROR", "WARNING", "enabled", "record", "findings",
           "reset", "summary_lines", "walk_eqns", "eqn_site",
           "pragma_suppressed", "filter_pragmas", "filter_file_pragmas",
           "baseline_entries", "write_baseline", "load_baseline",
           "diff_baseline"]


def enabled() -> bool:
    """Whether trace-time lint is on (the only check hot paths pay)."""
    return bool(_FLAG_DICT.get(_FLAG_NAME, False))


@dataclass
class Finding:
    """One lint finding, from either rule family."""

    rule: str
    severity: str
    message: str
    file: Optional[str] = None
    line: Optional[int] = None
    function: Optional[str] = None      # traced function (jaxpr findings)
    source: str = "ast"                 # "ast" | "jaxpr"
    extra: Dict[str, Any] = field(default_factory=dict)

    @property
    def where(self) -> str:
        return f"{self.file or '<unknown>'}:{self.line or 0}"

    def to_dict(self) -> dict:
        d = {"rule": self.rule, "severity": self.severity,
             "message": self.message, "file": self.file, "line": self.line,
             "source": self.source}
        if self.function:
            d["function"] = self.function
        if self.extra:
            d["extra"] = self.extra
        return d


# ---------------------------------------------------------------------------
# pragma suppression:  # tpu-lint: disable=<rule>[,<rule>...] | disable=all
# on the flagged line or the line directly above it
# ---------------------------------------------------------------------------

_PRAGMA_RE = re.compile(r"#\s*tpu-lint:\s*disable=([A-Za-z0-9_,\- ]+)")


def _pragma_rules(line_text: str) -> Optional[set]:
    m = _PRAGMA_RE.search(line_text)
    if not m:
        return None
    return {r.strip() for r in m.group(1).split(",") if r.strip()}


def pragma_suppressed(finding: Finding, lines: List[str]) -> bool:
    """Whether a ``# tpu-lint: disable=`` pragma on the finding's line
    (or the line above) covers this rule."""
    if finding.line is None:
        return False
    for ln in (finding.line, finding.line - 1):
        if 1 <= ln <= len(lines):
            rules = _pragma_rules(lines[ln - 1])
            if rules and ("all" in rules or finding.rule in rules):
                return True
    return False


def filter_pragmas(findings: Iterable[Finding],
                   lines: List[str]) -> List[Finding]:
    return [f for f in findings if not pragma_suppressed(f, lines)]


_FILE_LINES_LOCK = threading.Lock()
_FILE_LINES: Dict[str, List[str]] = {}
_FILE_LINES_CAP = 256


def _lines_of(path: str) -> List[str]:
    with _FILE_LINES_LOCK:
        cached = _FILE_LINES.get(path)
    if cached is not None:
        return cached
    try:
        with open(path, "r", errors="replace") as f:
            lines = f.read().splitlines()
    except OSError:
        lines = []
    with _FILE_LINES_LOCK:
        if len(_FILE_LINES) >= _FILE_LINES_CAP:
            _FILE_LINES.clear()
        _FILE_LINES[path] = lines
    return lines


def filter_file_pragmas(findings: Iterable[Finding]) -> List[Finding]:
    """Pragma-filter findings that carry a real file path (jaxpr findings
    attribute into user source; a pragma there must be honored too)."""
    out = []
    for f in findings:
        if f.file and f.line and os.path.isfile(f.file) \
                and pragma_suppressed(f, _lines_of(f.file)):
            continue
        out.append(f)
    return out


# ---------------------------------------------------------------------------
# runtime findings registry (trace-time jaxpr findings land here; the
# Profiler "Lint" section and lint_findings_total counters read it)
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_FINDINGS: List[Finding] = []
_SEEN: set = set()
_FINDINGS_CAP = 10000


def record(new_findings: Iterable[Finding]) -> List[Finding]:
    """Deduplicate (rule, file, line, function) and append to the session
    registry; mirrors each *new* finding into the metrics registry as a
    ``lint_findings_total{rule=...}`` counter (no-op with metrics off).
    Returns the findings that were actually new."""
    added = []
    with _LOCK:
        for f in new_findings:
            key = (f.rule, f.file, f.line, f.function)
            if key in _SEEN or len(_FINDINGS) >= _FINDINGS_CAP:
                continue
            _SEEN.add(key)
            _FINDINGS.append(f)
            added.append(f)
    for f in added:
        _mirror_metric(f)
    return added


def _mirror_metric(f: Finding) -> None:
    try:
        from ..profiler import metrics as _metrics
    except ImportError:  # standalone load — no metrics registry
        return
    _metrics.counter(
        "lint_findings_total",
        "Static-analysis findings recorded at trace time, by rule.",
        rule=f.rule).inc()


def findings() -> List[Finding]:
    with _LOCK:
        return list(_FINDINGS)


def reset() -> None:
    """Drop all recorded findings (tests)."""
    with _LOCK:
        _FINDINGS.clear()
        _SEEN.clear()


def summary_lines() -> List[str]:
    """The Profiler "Lint" section."""
    lines = [f"Lint  (FLAGS_tpu_lint={'on' if enabled() else 'off'})"]
    with _LOCK:
        fs = list(_FINDINGS)
    if not fs:
        lines.append("  no findings recorded")
        return lines
    n_err = sum(1 for f in fs if f.severity == ERROR)
    lines.append(f"  findings: {len(fs)}  ({n_err} errors, "
                 f"{len(fs) - n_err} warnings)")
    by_rule: Dict[str, int] = {}
    for f in fs:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    for rule in sorted(by_rule):
        lines.append(f"    {rule:<32} {by_rule[rule]:>5}")
    for f in fs[:10]:
        fn = f" [{f.function}]" if f.function else ""
        lines.append(f"  {f.severity[:4].upper()} {f.rule} "
                     f"{f.where}{fn}: {f.message[:80]}")
    if len(fs) > 10:
        lines.append(f"  ... and {len(fs) - 10} more "
                     f"(paddle_tpu.analysis.findings())")
    return lines


# ---------------------------------------------------------------------------
# shared jaxpr walker (pattern from profiler/numerics._interpret, but
# abstract: no evaluation, just structure + loop context)
# ---------------------------------------------------------------------------

_SUB_KEYS = ("jaxpr", "call_jaxpr", "fun_jaxpr", "cond_jaxpr", "body_jaxpr")
_LOOP_PRIMS = {"scan", "while"}


def sub_closed_jaxprs(eqn) -> list:
    """ClosedJaxpr-like sub-jaxprs a higher-order eqn carries (pjit /
    scan / while / cond / remat / custom_* bodies)."""
    out = []
    for k in _SUB_KEYS:
        j = eqn.params.get(k)
        if j is not None:
            out.append(j)
    branches = eqn.params.get("branches")
    if branches:
        out.extend(branches)
    return out


def walk_eqns(jaxpr, in_loop: bool = False, path: str = ""):
    """Yield ``(eqn, path, in_loop)`` for every eqn, recursing into
    nested pjit/cond/scan/while/remat sub-jaxprs. ``in_loop`` is True
    inside a scan or while body — the "this runs every iteration"
    context the host-callback rule cares about."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)  # accept ClosedJaxpr
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        yield eqn, path + name, in_loop
        child_in_loop = in_loop or name in _LOOP_PRIMS
        for sub in sub_closed_jaxprs(eqn):
            yield from walk_eqns(sub, in_loop=child_in_loop,
                                 path=f"{path}{name}/")


def eqn_site(eqn) -> Tuple[Optional[str], Optional[int], str]:
    """(file, line, "file:line (fn)") attribution of an eqn, best effort
    (same source_info path as profiler/numerics)."""
    where = "<unknown>"
    try:
        from jax._src import source_info_util
        where = source_info_util.summarize(eqn.source_info)
        fr = source_info_util.user_frame(eqn.source_info)
        if fr is not None:
            return fr.file_name, int(fr.start_line), where
    except Exception:  # tpu-lint: disable=except-pass — best-effort attribution
        pass
    return None, None, where


# ---------------------------------------------------------------------------
# baseline: the checked-in backlog.  Entries are path-relative and
# sorted so --baseline-update is deterministic; comparison ratchets on
# per-(rule, path) counts, so edits that only move lines don't fail.
# ---------------------------------------------------------------------------

def _rel(path: Optional[str], root: str) -> str:
    if not path:
        return "<unknown>"
    try:
        rel = os.path.relpath(os.path.abspath(path), os.path.abspath(root))
    except ValueError:  # different drive (windows)
        rel = path
    return rel.replace(os.sep, "/")


def baseline_entries(findings: Iterable[Finding], root: str) -> List[dict]:
    entries = [{"rule": f.rule, "severity": f.severity,
                "path": _rel(f.file, root), "line": f.line or 0,
                "message": f.message}
               for f in findings]
    entries.sort(key=lambda e: (e["path"], e["line"], e["rule"]))
    return entries


def write_baseline(path: str, findings: Iterable[Finding],
                   root: str) -> dict:
    doc = {"version": 1, "tool": "tpu_lint",
           "entries": baseline_entries(findings, root)}
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return doc


def load_baseline(path: str) -> dict:
    with open(path, "r") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "entries" not in doc:
        raise ValueError(f"{path}: not a tpu_lint baseline file")
    return doc


def diff_baseline(findings: List[Finding], baseline: dict,
                  root: str) -> Tuple[List[Finding], List[dict]]:
    """(new, fixed): ``new`` are findings beyond the baseline's
    per-(rule, path) count — matched by line first so unchanged code
    keeps its entries; ``fixed`` reports buckets that shrank (the
    baseline should be regenerated to claim the win)."""
    base_buckets: Dict[Tuple[str, str], List[int]] = {}
    for e in baseline.get("entries", []):
        base_buckets.setdefault((e["rule"], e["path"]), []).append(
            int(e.get("line", 0)))

    cur_buckets: Dict[Tuple[str, str], List[Finding]] = {}
    for f in findings:
        cur_buckets.setdefault((f.rule, _rel(f.file, root)), []).append(f)

    new: List[Finding] = []
    for key, flist in sorted(cur_buckets.items()):
        base_lines = list(base_buckets.get(key, []))
        extra_n = len(flist) - len(base_lines)
        if extra_n <= 0:
            continue
        remaining: Dict[int, int] = {}
        for ln in base_lines:
            remaining[ln] = remaining.get(ln, 0) + 1
        unmatched = []
        for f in sorted(flist, key=lambda f: f.line or 0):
            if remaining.get(f.line or 0, 0) > 0:
                remaining[f.line or 0] -= 1
            else:
                unmatched.append(f)
        new.extend(unmatched[:extra_n])

    fixed = []
    for key, base_lines in sorted(base_buckets.items()):
        n_cur = len(cur_buckets.get(key, []))
        if n_cur < len(base_lines):
            fixed.append({"rule": key[0], "path": key[1],
                          "removed": len(base_lines) - n_cur})
    return new, fixed
