"""Level-2 lint: Python AST rules for framework and user code.

These catch the hazards that live *outside* any jaxpr — the host-side
patterns that made PR 1-3 slow or silent:

============================  =========  ====================================
rule                          severity   hazard
============================  =========  ====================================
host-sync-in-loop             error      float()/bool()/int() over a
                                         jnp/jax expression, or
                                         .item()/.numpy()/.tolist(), inside
                                         a loop or a to_static body — a
                                         blocking device→host transfer per
                                         iteration (the _unscale_grads bug)
except-pass                   warning    `except Exception: pass` (or bare
                                         except) silently swallowing —
                                         narrow it, log it, or pragma it
mutable-default-arg           warning    list/dict/set literal as a default
                                         — shared across calls
flag-lookup-in-loop           warning    get_flags()/flags.flag()/
                                         os.environ lookups inside a loop —
                                         hoist the read out of the hot path
mosaic-block-shape            warning    pl.BlockSpec literal whose block
                                         shape violates Mosaic's tiling rule
                                         (last dim % 128, second-to-last
                                         % 8) for every dtype — the
                                         BENCH_r02 `(1, 256)` launch-failure
                                         class; legal only if the array dim
                                         happens to equal the block dim
============================  =========  ====================================

The sanctioned host-transfer idiom is an *explicit* ``jax.device_get``
of a batched stats array (one transfer per step): expressions containing
``device_get`` / ``block_until_ready`` are deliberately not flagged.

Suppression: ``# tpu-lint: disable=<rule>[,<rule>]`` (or ``=all``) on
the flagged line or the line above.

Stdlib-only on purpose: ``tools/tpu_lint.py`` runs these rules without
importing jax (or paddle_tpu).
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence

from .core import ERROR, WARNING, Finding, filter_pragmas

__all__ = ["AST_RULES", "check_source", "check_file", "check_paths",
           "iter_py_files"]

# rule id -> (severity, one-line doc) — the catalogue the CLI and docs use
AST_RULES: Dict[str, tuple] = {
    "host-sync-in-loop": (
        ERROR, "implicit blocking device->host transfer inside a loop or "
               "to_static body"),
    "except-pass": (
        WARNING, "except Exception/bare except whose body only passes"),
    "mutable-default-arg": (
        WARNING, "mutable default argument shared across calls"),
    "flag-lookup-in-loop": (
        WARNING, "flag/env lookup inside a loop body"),
    "mosaic-block-shape": (
        WARNING, "pl.BlockSpec block-shape literal that no dtype makes "
                 "Mosaic-legal (last dim % 128, second-to-last % 8)"),
}

_SYNC_ATTRS = {"item", "numpy", "tolist"}
_SYNC_WRAPPERS = {"float", "bool", "int"}
_TRACED_ROOTS = {"jnp", "jax", "lax"}
_EXPLICIT_TRANSFER = {"device_get", "block_until_ready"}
_TO_STATIC_NAMES = {"to_static"}


def _root_name(node: ast.AST) -> Optional[str]:
    """Leftmost Name of a dotted expression (jnp.linalg.norm -> jnp)."""
    while isinstance(node, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def _func_attr(node: ast.AST) -> Optional[str]:
    """Final attribute/name of a call target (jax.device_get -> device_get)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _contains_traced_call(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and \
                _root_name(sub.func) in _TRACED_ROOTS:
            return True
    return False


def _contains_explicit_transfer(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and \
                _func_attr(sub.func) in _EXPLICIT_TRANSFER:
            return True
    return False


def _is_to_static_decorated(node) -> bool:
    for dec in getattr(node, "decorator_list", ()):
        target = dec.func if isinstance(dec, ast.Call) else dec
        if _func_attr(target) in _TO_STATIC_NAMES:
            return True
    return False


def _is_mutable_default(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set)):
        return True
    return (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
            and node.func.id in {"list", "dict", "set"}
            and not node.args and not node.keywords)


def _swallows(handler: ast.ExceptHandler) -> bool:
    typ = handler.type
    broad = (typ is None
             or (isinstance(typ, ast.Name)
                 and typ.id in {"Exception", "BaseException"})
             or (isinstance(typ, ast.Attribute)
                 and typ.attr in {"Exception", "BaseException"}))
    if not broad:
        return False
    body = handler.body
    if all(isinstance(s, ast.Pass) for s in body):
        return True
    return (len(body) == 1 and isinstance(body[0], ast.Expr)
            and isinstance(body[0].value, ast.Constant)
            and body[0].value.value is Ellipsis)


def _blockspec_literal_shape(call: ast.Call) -> Optional[tuple]:
    """The all-int-literal block shape of a pl.BlockSpec(...) call, or
    None when it isn't one / the shape isn't fully literal (variables —
    e.g. autotuned block sizes — can't be judged statically)."""
    if _func_attr(call.func) != "BlockSpec":
        return None
    shape_node = None
    if call.args:
        shape_node = call.args[0]
    for kw in call.keywords:
        if kw.arg == "block_shape":
            shape_node = kw.value
    if not isinstance(shape_node, (ast.Tuple, ast.List)):
        return None
    dims = []
    for elt in shape_node.elts:
        if not (isinstance(elt, ast.Constant) and isinstance(elt.value, int)
                and not isinstance(elt.value, bool)):
            return None
        dims.append(elt.value)
    return tuple(dims)


def _mosaic_illegal_dims(shape: tuple) -> List[str]:
    """Which dims of a literal block shape violate Mosaic's divisibility
    rule for every dtype (mirror of pallas_ops.mosaic_block_legal, minus
    the block-dim == array-dim escape, which is unknowable statically).
    rank >= 2: last % 128 and second-to-last % 8; rank 1: % 128 (the
    f32 tiling — wider-tiled narrow dtypes only raise the bar)."""
    problems = []
    if len(shape) >= 2:
        if shape[-1] % 128:
            problems.append(f"last dim {shape[-1]} % 128 != 0")
        if shape[-2] % 8:
            problems.append(f"second-to-last dim {shape[-2]} % 8 != 0")
    elif len(shape) == 1 and shape[0] % 128:
        problems.append(f"dim {shape[0]} % 128 != 0")
    return problems


def _is_flag_lookup(call: ast.Call) -> bool:
    fn = call.func
    attr = _func_attr(fn)
    if attr in {"get_flags", "getenv"}:
        return True
    if attr == "flag" and isinstance(fn, ast.Attribute):
        return True  # flags.flag(...) / _flags.flag(...)
    # os.environ.get(...)
    if attr == "get" and isinstance(fn, ast.Attribute) and \
            isinstance(fn.value, ast.Attribute) and \
            fn.value.attr == "environ":
        return True
    return False


class _Checker(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.findings: List[Finding] = []
        self._loop_depth = 0
        self._traced_depth = 0  # inside a to_static-decorated function

    def _add(self, rule: str, line: int, message: str, **extra):
        severity = AST_RULES[rule][0]
        self.findings.append(Finding(
            rule=rule, severity=severity, message=message, file=self.path,
            line=line, source="ast", extra=extra))

    # -- context tracking ---------------------------------------------------
    def visit_For(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_While = visit_For
    visit_AsyncFor = visit_For

    def _visit_function(self, node):
        self._check_defaults(node)
        traced = _is_to_static_decorated(node)
        # a nested def is a new host frame: loops outside it don't make
        # its body per-iteration code
        outer_loop, self._loop_depth = self._loop_depth, 0
        self._traced_depth += 1 if traced else 0
        self.generic_visit(node)
        self._traced_depth -= 1 if traced else 0
        self._loop_depth = outer_loop

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    def _check_defaults(self, node):
        args = node.args
        for default in list(args.defaults) + \
                [d for d in args.kw_defaults if d is not None]:
            if _is_mutable_default(default):
                self._add("mutable-default-arg", default.lineno,
                          f"mutable default argument in "
                          f"{node.name}() — shared across calls; "
                          "default to None and create inside")

    # -- rules --------------------------------------------------------------
    def visit_ExceptHandler(self, node):
        if _swallows(node):
            self._add("except-pass", node.lineno,
                      "broad except silently swallows all errors — narrow "
                      "to the expected exception, log at debug level, or "
                      "annotate with `# tpu-lint: disable=except-pass` "
                      "if genuinely best-effort")
        self.generic_visit(node)

    def visit_Call(self, node):
        hot = self._loop_depth > 0 or self._traced_depth > 0
        where = ("loop body" if self._loop_depth > 0
                 else "to_static-traced body")
        attr = _func_attr(node.func)
        if hot and attr in _SYNC_ATTRS and not node.args \
                and isinstance(node.func, ast.Attribute):
            self._add("host-sync-in-loop", node.lineno,
                      f".{attr}() inside a {where} blocks on a "
                      "device->host transfer every iteration — batch the "
                      "scalars device-side and jax.device_get() once")
        elif hot and isinstance(node.func, ast.Name) \
                and node.func.id in _SYNC_WRAPPERS and len(node.args) == 1 \
                and _contains_traced_call(node.args[0]) \
                and not _contains_explicit_transfer(node.args[0]):
            self._add("host-sync-in-loop", node.lineno,
                      f"{node.func.id}(<jax expression>) inside a {where} "
                      "forces a blocking device->host sync every "
                      "iteration — fuse the scalars into one device "
                      "computation and jax.device_get() once")
        if self._loop_depth > 0 and _is_flag_lookup(node):
            self._add("flag-lookup-in-loop", node.lineno,
                      "flag/env lookup inside a loop — read it once "
                      "before the loop (per-step dict/env lookups add up "
                      "in hot paths)")
        shape = _blockspec_literal_shape(node)
        if shape is not None:
            problems = _mosaic_illegal_dims(shape)
            if problems:
                self._add("mosaic-block-shape", node.lineno,
                          f"BlockSpec block shape {shape} is Mosaic-"
                          f"illegal for every dtype ({'; '.join(problems)})"
                          " unless the array dim happens to equal the "
                          "block dim — kernels launch-fail at run time "
                          "(the BENCH_r02 class); derive block sizes "
                          "from a mosaic_block_legal-filtered candidate "
                          "set instead")
        self.generic_visit(node)


def check_source(source: str, path: str = "<string>",
                 rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint one source text. Returns pragma-filtered findings sorted by
    line. Syntax errors surface as a single ``syntax-error`` finding."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(rule="syntax-error", severity=WARNING,
                        message=f"could not parse: {e.msg}", file=path,
                        line=e.lineno or 0, source="ast")]
    checker = _Checker(path)
    checker.visit(tree)
    found = checker.findings
    if rules is not None:
        allowed = set(rules)
        found = [f for f in found if f.rule in allowed]
    found = filter_pragmas(found, source.splitlines())
    found.sort(key=lambda f: (f.line or 0, f.rule))
    return found


def check_file(path: str,
               rules: Optional[Sequence[str]] = None) -> List[Finding]:
    try:
        with open(path, "r", errors="replace") as f:
            source = f.read()
    except OSError as e:
        return [Finding(rule="io-error", severity=WARNING,
                        message=str(e), file=path, line=0, source="ast")]
    return check_source(source, path=path, rules=rules)


_SKIP_DIRS = {"__pycache__", ".git", ".tox", ".venv", "node_modules",
              "build", "dist"}


def iter_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in _SKIP_DIRS
                                     and not d.startswith("."))
                out.extend(os.path.join(dirpath, f)
                           for f in sorted(filenames) if f.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
    return out


def check_paths(paths: Sequence[str],
                rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint every .py file under ``paths`` (deterministic order)."""
    findings: List[Finding] = []
    for path in iter_py_files(paths):
        findings.extend(check_file(path, rules=rules))
    findings.sort(key=lambda f: (f.file or "", f.line or 0, f.rule))
    return findings
