"""Level-3 lint, part (a): the Pallas kernel verifier.

Levels 1–2 look at jaxprs and Python source; neither sees *inside* a
``pl.pallas_call``.  This module does, without a TPU and without running
anything: a tracing shim temporarily replaces
``jax.experimental.pallas.pallas_call`` and the target function is
abstractly evaluated with ``jax.eval_shape``.  Every pallas_call site
executed during the trace is captured — kernel function, grid,
BlockSpecs, out_shape, scratch shapes, operand avals, and the exact
call-site file:line — and then checked against the rules below.
BENCH_r02 lost a bench round to an illegal block spec that Mosaic only
rejected at compile time on-device; every rule here fires on CPU at
trace time instead.

============================  =========  ====================================
rule                          severity   hazard
============================  =========  ====================================
kernel-grid-divisibility      error      grid x block_shape does not tile an
                                         operand evenly — the edge block is
                                         padded (read) / partially written
kernel-index-oob              error      an index_map emits a block index
                                         outside the operand (the classic
                                         off-by-one ``i + 1``) — Mosaic
                                         reads/writes out of bounds
kernel-output-coverage        error      some output block is never emitted
                                         by any grid point — silent garbage
                                         in the uncovered region
kernel-mosaic-block           error      a derived block violates Mosaic
                                         tiling for the *actual* dtype
                                         (``autotune.mosaic_block_legal``)
kernel-vmem-budget            warning    estimated VMEM footprint (resident
                                         blocks + scratch) exceeds the
                                         per-generation budget
kernel-unused-ref             warning    an output or scratch ref the kernel
                                         body never touches — dead VMEM
kernel-narrow-accumulator     warning    a bf16/f16 scratch accumulator over
                                         bf16/f16 inputs — accumulate in f32
kernel-verifier-error         warning    a registered kernel case failed to
                                         trace at all (itself a red flag)
============================  =========  ====================================

Proven vs. heuristic: when ``prod(grid)`` is at or under
``index_eval_points`` the index maps are evaluated over the *entire*
grid, so in-bounds access and output coverage are proved, not sampled.
Above the cap only the grid corners are evaluated (bounds stay sound for
monotone affine maps — everything shipped here — but coverage is
skipped) and the finding notes the downgrade.

Like the rest of the package this module imports without jax; jax is
only touched inside :func:`verify_kernel` / :func:`capture_sites`.
"""
from __future__ import annotations

import ast
import contextlib
import functools
import inspect
import itertools
import math
import sys
import textwrap
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from . import core as _core
from .core import ERROR, WARNING, Finding

__all__ = ["KERNEL_RULES", "DEFAULT_KERNEL_CONFIG", "KernelSite",
           "capture_sites", "check_sites", "verify_kernel",
           "verify_registered", "verify_module", "register_kernel_case",
           "register_kernel_provider", "registered_cases"]

# rule id -> (severity, one-line doc).  Checks are methods of the site
# checker below rather than free functions: they share one normalized
# view of the call.
KERNEL_RULES: Dict[str, tuple] = {
    "kernel-grid-divisibility": (
        ERROR, "grid x block_shape does not tile an operand evenly"),
    "kernel-index-oob": (
        ERROR, "index_map emits a block index outside the operand"),
    "kernel-output-coverage": (
        ERROR, "some output block is never written by any grid point"),
    "kernel-mosaic-block": (
        ERROR, "block shape violates Mosaic tiling for the actual dtype"),
    "kernel-vmem-budget": (
        WARNING, "estimated VMEM footprint exceeds the generation budget"),
    "kernel-unused-ref": (
        WARNING, "output/scratch ref the kernel body never references"),
    "kernel-narrow-accumulator": (
        WARNING, "bf16/f16 scratch accumulator over bf16/f16 inputs"),
    "kernel-verifier-error": (
        WARNING, "registered kernel case failed to trace"),
}

DEFAULT_KERNEL_CONFIG: Dict[str, Any] = {
    # explicit budget override (bytes).  None -> pick by device generation.
    "vmem_budget_bytes": None,
    # per-generation VMEM budgets: ~16 MiB/core on v4/v5, double on v6e,
    # minus headroom for Mosaic's own double-buffering and spills (the
    # same margin ops/pallas_ops uses to prefilter autotune candidates).
    "vmem_budgets": {"v4": 12 << 20, "v5e": 12 << 20, "v5p": 12 << 20,
                     "v6e": 24 << 20, "default": 12 << 20},
    # full index-map enumeration cap: grids up to this many points are
    # proved exhaustively; larger grids fall back to corner sampling.
    "index_eval_points": 1 << 16,
}

_NARROW_FLOATS = ("bfloat16", "float16")


# ---------------------------------------------------------------------------
# capture: a context manager that swaps jax.experimental.pallas.pallas_call
# for a recording shim.  ops/pallas_ops.py resolves ``pl.pallas_call`` at
# call time, so the swap intercepts every site traced inside the block.
# ---------------------------------------------------------------------------

class KernelSite:
    """One captured ``pl.pallas_call`` invocation (normalized)."""

    def __init__(self, kernel, grid, in_specs, out_specs, out_shapes,
                 scratch_shapes, file, line, num_scalar_prefetch=0):
        self.kernel = kernel
        self.grid: Tuple[int, ...] = grid
        self.in_specs = in_specs          # list[BlockSpec | None]
        self.out_specs = out_specs        # list[BlockSpec | None]
        self.out_shapes = out_shapes      # list[ShapeDtypeStruct-like]
        self.scratch_shapes = scratch_shapes
        self.file = file
        self.line = line
        # PrefetchScalarGridSpec: the first N operands are SMEM scalar
        # refs handed to every index_map after the grid indices
        self.num_scalar_prefetch = int(num_scalar_prefetch)
        self.operands: list = []          # avals, filled at the inner call
        self.scalar_operands: list = []   # leading scalar-prefetch args

    @property
    def kernel_name(self) -> str:
        fn = self.kernel
        while isinstance(fn, functools.partial):
            fn = fn.func
        return getattr(fn, "__name__", repr(fn))


def _as_tuple(x) -> tuple:
    if x is None:
        return ()
    if isinstance(x, (tuple, list)):
        return tuple(x)
    return (x,)


def _tree_leaves(x, is_leaf):
    """Tiny pytree flattener (dict/list/tuple) — avoids importing jax
    tree utils for what is always a shallow structure here."""
    if x is None:
        return []
    if is_leaf(x):
        return [x]
    if isinstance(x, dict):
        out = []
        for k in sorted(x):
            out.extend(_tree_leaves(x[k], is_leaf))
        return out
    if isinstance(x, (tuple, list)):
        out = []
        for item in x:
            out.extend(_tree_leaves(item, is_leaf))
        return out
    return [x]


def _normalize_call(kernel, args, kwargs, blockspec_cls, file, line
                    ) -> Optional[KernelSite]:
    """Build a KernelSite from raw pallas_call arguments; None if the
    call uses a shape this verifier does not model (grid_spec objects
    with no recoverable grid, etc.)."""
    out_shape = kwargs.get("out_shape")
    if out_shape is None and len(args) > 0:
        out_shape = args[0]
    grid = kwargs.get("grid", ())
    in_specs = kwargs.get("in_specs")
    out_specs = kwargs.get("out_specs")
    scratch = kwargs.get("scratch_shapes", ())
    grid_spec = kwargs.get("grid_spec")
    nsp = 0
    if grid_spec is not None:  # pl.GridSpec / PrefetchScalarGridSpec
        grid = getattr(grid_spec, "grid", grid)
        in_specs = getattr(grid_spec, "in_specs", in_specs)
        out_specs = getattr(grid_spec, "out_specs", out_specs)
        scratch = getattr(grid_spec, "scratch_shapes", scratch)
        try:
            nsp = int(getattr(grid_spec, "num_scalar_prefetch", 0) or 0)
        except (TypeError, ValueError):
            nsp = 0
    if isinstance(grid, int):
        grid = (grid,)
    try:
        grid = tuple(int(g) for g in _as_tuple(grid))
    except (TypeError, ValueError):
        return None  # dynamic grid — out of scope
    is_spec = lambda s: isinstance(s, blockspec_cls)
    is_shape = lambda s: hasattr(s, "shape") and hasattr(s, "dtype")
    return KernelSite(
        kernel=kernel,
        grid=grid,
        in_specs=[s if is_spec(s) else None
                  for s in _tree_leaves(in_specs, is_spec)],
        out_specs=[s if is_spec(s) else None
                   for s in _tree_leaves(out_specs, is_spec)],
        out_shapes=_tree_leaves(out_shape, is_leaf=is_shape),
        scratch_shapes=_tree_leaves(_as_tuple(scratch), is_leaf=is_shape),
        file=file, line=line, num_scalar_prefetch=nsp)


@contextlib.contextmanager
def capture_sites(sites: List[KernelSite]):
    """Swap ``pl.pallas_call`` for a shim that records every call site
    (and its operand avals) into ``sites`` while delegating to the real
    pallas_call, so tracing behaves identically. A no-op (still a valid
    context) when jax/pallas is unavailable."""
    try:
        import jax  # noqa: F401  (ensures jax present before patching)
        from jax.experimental import pallas as pl
    except ImportError:
        yield sites
        return

    real = pl.pallas_call
    blockspec_cls = pl.BlockSpec

    def shim(kernel, *args, **kwargs):
        fr = sys._getframe(1)
        site = _normalize_call(kernel, args, kwargs, blockspec_cls,
                               fr.f_code.co_filename, fr.f_lineno)
        wrapped = real(kernel, *args, **kwargs)
        if site is None:
            return wrapped

        @functools.wraps(wrapped)
        def with_operands(*operands, **okw):
            ops = [o for o in operands
                   if hasattr(o, "shape") and hasattr(o, "dtype")]
            # scalar-prefetch operands lead; they live in SMEM and pair
            # with no BlockSpec, so keep them out of the grid operands
            site.scalar_operands = ops[:site.num_scalar_prefetch]
            site.operands = ops[site.num_scalar_prefetch:]
            sites.append(site)
            return wrapped(*operands, **okw)
        return with_operands

    pl.pallas_call = shim
    try:
        yield sites
    finally:
        pl.pallas_call = real


# ---------------------------------------------------------------------------
# the per-site checker
# ---------------------------------------------------------------------------

def _mosaic_legal() -> Callable:
    """The shared Mosaic tiling predicate.  Prefer the autotune export
    (one source of truth with candidate filtering); fall back to a local
    copy when analysis is loaded standalone without the package."""
    try:
        from paddle_tpu.ops.autotune import mosaic_block_legal
        return mosaic_block_legal
    except ImportError:
        return _mosaic_block_legal_fallback


def _mosaic_block_legal_fallback(block_shape, array_shape,
                                 dtype_bits: int = 32) -> bool:
    # mirror of ops/pallas_ops.mosaic_block_legal — keep in sync.
    if len(block_shape) != len(array_shape):
        return False
    if len(block_shape) >= 2:
        *_, sub, lane = block_shape
        *_, asub, alane = array_shape
        if lane % 128 != 0 and lane != alane:
            return False
        if sub % 8 != 0 and sub != asub:
            return False
        return True
    if len(block_shape) == 1:
        packing = max(1, 32 // max(1, dtype_bits))
        return (block_shape[0] % (128 * packing) == 0
                or block_shape[0] == array_shape[0])
    return True


def _dtype_name(dtype) -> str:
    """Canonical dtype name: accepts numpy dtypes, jax scalar classes
    (``jnp.bfloat16`` — what pltpu.VMEM stores), and strings."""
    try:
        import numpy as np
        return str(np.dtype(dtype))
    except (ImportError, TypeError):
        return str(dtype)


def _dtype_itemsize(dtype) -> int:
    size = getattr(dtype, "itemsize", None)
    if size:
        return int(size)
    name = _dtype_name(dtype)
    if name in _NARROW_FLOATS or name in ("int16", "uint16"):
        return 2
    if name in ("int8", "uint8", "bool",
                "float8_e4m3fn", "float8_e5m2"):
        return 1
    if name in ("float64", "int64", "uint64", "complex64"):
        return 8
    return 4


def _block_dims(spec, array_shape) -> Optional[Tuple[int, ...]]:
    """Concrete per-dim block sizes for a spec over an array, or None
    when the spec covers the whole array (no blocking)."""
    bshape = getattr(spec, "block_shape", None) if spec is not None else None
    if bshape is None:
        return None
    dims = []
    for d, b in enumerate(bshape):
        if b is None:  # squeezed dim: block extent 1
            dims.append(1)
        else:
            try:
                dims.append(int(b))
            except (TypeError, ValueError):
                return None
    if len(dims) != len(array_shape):
        return None  # rank mismatch — pallas itself rejects this later
    return tuple(dims)


def _is_blocked(spec) -> bool:
    mode = getattr(spec, "indexing_mode", None)
    if mode is None:
        return True
    return type(mode).__name__ in ("Blocked", "blocked")


class _Operand:
    """One (array, spec) pair the grid iterates over."""

    def __init__(self, role, index, shape, dtype, spec):
        self.role = role        # "in" | "out"
        self.index = index
        self.shape = tuple(int(d) for d in shape)
        self.dtype = dtype
        self.spec = spec
        self.blocks = _block_dims(spec, self.shape)

    @property
    def label(self) -> str:
        return f"{self.role}[{self.index}]"

    def grid_blocks(self) -> Tuple[int, ...]:
        """Blocks needed per dim to cover the array (ceil division)."""
        return tuple(-(-a // b) for a, b in zip(self.shape, self.blocks))


def _grid_points(grid: Tuple[int, ...], cap: int):
    """(points, exhaustive): the full grid when small enough to prove
    properties, otherwise the corner set (bounds-only heuristic)."""
    total = math.prod(grid) if grid else 0
    if total == 0:
        return [], True
    if total <= cap:
        return list(itertools.product(*(range(g) for g in grid))), True
    corners = itertools.product(*({0, g - 1} for g in grid))
    return list(corners), False


class _SiteChecker:
    def __init__(self, site: KernelSite, cfg: dict,
                 name: Optional[str], rules):
        self.site = site
        self.cfg = cfg
        self.name = name
        self.rules = rules
        self.findings: List[Finding] = []

    def _want(self, rule: str) -> bool:
        return self.rules is None or rule in self.rules

    def _emit(self, rule: str, msg: str, file=None, line=None, **extra):
        severity, _ = KERNEL_RULES[rule]
        extra.setdefault("kernel", self.site.kernel_name)
        self.findings.append(Finding(
            rule=rule, severity=severity, message=msg,
            file=file or self.site.file, line=line or self.site.line,
            function=self.name, source="kernel", extra=extra))

    def _operands(self) -> List[_Operand]:
        s = self.site
        ops = []
        for i, o in enumerate(s.operands):
            spec = s.in_specs[i] if i < len(s.in_specs) else None
            ops.append(_Operand("in", i, o.shape, o.dtype, spec))
        for i, o in enumerate(s.out_shapes):
            spec = s.out_specs[i] if i < len(s.out_specs) else None
            ops.append(_Operand("out", i, o.shape, o.dtype, spec))
        return ops

    def run(self) -> List[Finding]:
        ops = self._operands()
        blocked = [o for o in ops if o.blocks is not None]
        self._check_divisibility(blocked)
        self._check_mosaic(blocked)
        self._check_index_maps(blocked)
        self._check_vmem(ops)
        self._check_kernel_body()
        return self.findings

    # --- rule: kernel-grid-divisibility -----------------------------------
    def _check_divisibility(self, blocked: List[_Operand]):
        if not self._want("kernel-grid-divisibility"):
            return
        for op in blocked:
            bad = [(d, a, b) for d, (a, b) in
                   enumerate(zip(op.shape, op.blocks)) if a % b != 0]
            if bad:
                desc = ", ".join(f"dim {d}: {a} % {b} != 0"
                                 for d, a, b in bad)
                self._emit(
                    "kernel-grid-divisibility",
                    f"{self.site.kernel_name}: {op.label} shape "
                    f"{list(op.shape)} is not tiled evenly by block "
                    f"{list(op.blocks)} ({desc}) — the edge block is "
                    "silently padded on read and partially written on "
                    "write; pick a divisor block or pad the operand",
                    operand=op.label, shape=list(op.shape),
                    block=list(op.blocks))

    # --- rule: kernel-mosaic-block ----------------------------------------
    def _check_mosaic(self, blocked: List[_Operand]):
        if not self._want("kernel-mosaic-block"):
            return
        legal = _mosaic_legal()
        for op in blocked:
            bits = _dtype_itemsize(op.dtype) * 8
            try:
                ok = legal(op.blocks, op.shape, dtype_bits=bits)
            except TypeError:  # older signature without dtype_bits
                ok = legal(op.blocks, op.shape)
            if not ok:
                self._emit(
                    "kernel-mosaic-block",
                    f"{self.site.kernel_name}: {op.label} block "
                    f"{list(op.blocks)} over {str(op.dtype)}"
                    f"{list(op.shape)} violates Mosaic tiling for "
                    f"{bits}-bit elements (lane dim % 128, sublane % 8, "
                    "rank-1 % (128 * 32/bits), or exactly the array dim) "
                    "— Mosaic would reject or silently retile this at "
                    "compile time",
                    operand=op.label, block=list(op.blocks),
                    dtype=str(op.dtype))

    # --- rules: kernel-index-oob + kernel-output-coverage -----------------
    def _concrete_scalars(self) -> Optional[tuple]:
        """Concrete numpy values of the scalar-prefetch operands, or None
        when any is traced. Registered verify cases close over an example
        block table (a real ndarray), which makes scalar-driven index
        maps provable: ``table[r, j]`` works on an ndarray exactly as it
        does on the SMEM ref. Traced scalars leave the maps unverifiable
        — skipped and noted, same as any map that raises."""
        import numpy as np
        vals = []
        for o in self.site.scalar_operands:
            try:
                vals.append(np.asarray(o))
            except Exception:  # tracer — no concrete table to prove with
                return None
        return tuple(vals)

    def _eval_map(self, spec, point) -> Optional[Tuple[int, ...]]:
        index_map = getattr(spec, "index_map", None)
        if index_map is None:
            return (0,) * len(spec.block_shape)
        try:
            if self.site.num_scalar_prefetch:
                scalars = self._scalar_args
                if scalars is None:
                    self._index_map_skips.add(
                        "scalar-prefetch operands are traced — index maps "
                        "not provable without a concrete example table")
                    return None
                idx = index_map(*point, *scalars)
            else:
                idx = index_map(*point)
        except Exception as e:  # map needs tracers/refs — skip, note once
            self._index_map_skips.add(f"{type(e).__name__}: {e}")
            return None
        if not isinstance(idx, tuple):
            idx = (idx,)
        try:
            return tuple(int(i) for i in idx)
        except (TypeError, ValueError):
            return None

    def _check_index_maps(self, blocked: List[_Operand]):
        want_oob = self._want("kernel-index-oob")
        want_cov = self._want("kernel-output-coverage")
        if not (want_oob or want_cov) or not self.site.grid:
            return
        self._index_map_skips: set = set()
        self._scalar_args = self._concrete_scalars()
        points, exhaustive = _grid_points(
            self.site.grid, int(self.cfg["index_eval_points"]))
        for op in blocked:
            if not _is_blocked(op.spec):
                continue  # Unblocked specs index in elements — out of scope
            grid_blocks = op.grid_blocks()
            emitted: set = set()
            oob_hit = None
            for point in points:
                idx = self._eval_map(op.spec, point)
                if idx is None or len(idx) != len(grid_blocks):
                    emitted = None
                    break
                emitted.add(idx)
                if oob_hit is None and any(
                        i < 0 or i >= n for i, n in zip(idx, grid_blocks)):
                    oob_hit = (point, idx)
            if oob_hit and want_oob:
                point, idx = oob_hit
                self._emit(
                    "kernel-index-oob",
                    f"{self.site.kernel_name}: {op.label} index_map"
                    f"{tuple(point)} -> block {tuple(idx)} but the valid "
                    f"block range is {tuple(grid_blocks)} for shape "
                    f"{list(op.shape)} / block {list(op.blocks)} — "
                    "out-of-bounds access (off-by-one index_map?)",
                    operand=op.label, grid_point=list(point),
                    block_index=list(idx))
            if (op.role == "out" and want_cov and exhaustive
                    and emitted is not None and oob_hit is None):
                required = set(itertools.product(
                    *(range(n) for n in grid_blocks)))
                missing = sorted(required - emitted)
                if missing:
                    preview = ", ".join(str(m) for m in missing[:4])
                    self._emit(
                        "kernel-output-coverage",
                        f"{self.site.kernel_name}: {op.label} — "
                        f"{len(missing)} of {len(required)} output blocks "
                        f"are never written by any grid point (first "
                        f"missing: {preview}) — the uncovered region is "
                        "returned uninitialized",
                        operand=op.label, missing=len(missing),
                        required=len(required))

    # --- rule: kernel-vmem-budget -----------------------------------------
    def _vmem_budget(self) -> Tuple[int, str]:
        override = self.cfg.get("vmem_budget_bytes")
        if override:
            return int(override), "override"
        budgets = dict(self.cfg["vmem_budgets"])
        kind = ""
        try:
            import jax
            kind = jax.devices()[0].device_kind.lower()
        except Exception:  # no backend at all — fall through to default
            kind = ""
        for gen in sorted(budgets, key=len, reverse=True):
            if gen != "default" and gen in kind:
                return int(budgets[gen]), gen
        return int(budgets.get("default", 12 << 20)), "default"

    def _check_vmem(self, ops: List[_Operand]):
        block_bytes = 0
        for op in ops:
            dims = op.blocks if op.blocks is not None else op.shape
            block_bytes += math.prod(dims) * _dtype_itemsize(op.dtype)
        scratch_bytes = 0
        for s in self.site.scratch_shapes:
            scratch_bytes += (math.prod(int(d) for d in s.shape)
                              * _dtype_itemsize(s.dtype))
        # scalar-prefetch operands (block tables, per-page scale pools)
        # have no BlockSpec but are resident whole for the kernel's
        # lifetime — a quantized-KV scale pool left out of the estimate
        # would understate the footprint exactly where it grew
        scalar_bytes = 0
        for o in self.site.scalar_operands:
            shape = getattr(o, "shape", None)
            dtype = getattr(o, "dtype", None)
            if shape is None or dtype is None:
                continue
            scalar_bytes += (math.prod(int(d) for d in shape)
                             * _dtype_itemsize(dtype))
        total = block_bytes + scratch_bytes + scalar_bytes
        budget, gen = self._vmem_budget()
        self._record_estimate(block_bytes, scratch_bytes, scalar_bytes,
                              budget, gen)
        if total > budget and self._want("kernel-vmem-budget"):
            self._emit(
                "kernel-vmem-budget",
                f"{self.site.kernel_name}: estimated VMEM footprint "
                f"{total / (1 << 20):.1f} MiB (blocks "
                f"{block_bytes / (1 << 20):.1f} + scratch "
                f"{scratch_bytes / (1 << 20):.1f} + scalar operands "
                f"{scalar_bytes / (1 << 20):.1f}) exceeds the {gen} "
                f"budget of {budget / (1 << 20):.0f} MiB — shrink the "
                "block sizes or stream the large operand "
                "(config key 'vmem_budget_bytes' overrides the budget)",
                vmem_bytes=total, budget_bytes=budget, generation=gen)

    def _record_estimate(self, block_bytes, scratch_bytes, scalar_bytes,
                         budget, gen):
        try:
            from ..profiler import xmem as _xmem
        except ImportError:  # standalone analysis load — no profiler
            return
        _xmem.record_kernel_estimate(
            self.site.kernel_name,
            vmem_bytes=block_bytes + scratch_bytes + scalar_bytes,
            block_bytes=block_bytes, scratch_bytes=scratch_bytes,
            scalar_bytes=scalar_bytes,
            budget_bytes=budget, generation=gen,
            grid=list(self.site.grid),
            where=f"{self.site.file}:{self.site.line}")

    # --- rules: kernel-unused-ref + kernel-narrow-accumulator -------------
    def _kernel_ref_params(self):
        """(fn, positional ref param names) after unwrapping partials."""
        fn = self.site.kernel
        skip_lead = 0
        bound_kw: set = set()
        while isinstance(fn, functools.partial):
            skip_lead += len(fn.args)
            bound_kw |= set(fn.keywords or {})
            fn = fn.func
        try:
            src = textwrap.dedent(inspect.getsource(fn))
            tree = ast.parse(src)
        except (OSError, TypeError, SyntaxError, IndentationError):
            return fn, None, None
        fndef = next((n for n in ast.walk(tree)
                      if isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))), None)
        if fndef is None:
            return fn, None, None
        params = [a.arg for a in fndef.args.args][skip_lead:]
        params = [p for p in params if p not in bound_kw]
        return fn, fndef, params

    def _check_kernel_body(self):
        want_unused = self._want("kernel-unused-ref")
        want_narrow = self._want("kernel-narrow-accumulator")
        if not (want_unused or want_narrow):
            return
        s = self.site
        narrow_in = [_dtype_name(o.dtype) for o in s.operands
                     if _dtype_name(o.dtype) in _NARROW_FLOATS]
        narrow_scratch = [
            (i, _dtype_name(sc.dtype))
            for i, sc in enumerate(s.scratch_shapes)
            if _dtype_name(sc.dtype) in _NARROW_FLOATS]
        if want_narrow and narrow_in and narrow_scratch:
            idx, dt = narrow_scratch[0]
            self._emit(
                "kernel-narrow-accumulator",
                f"{s.kernel_name}: scratch[{idx}] accumulates in {dt} "
                f"over {narrow_in[0]} inputs — rounding error compounds "
                "across the grid; allocate the accumulator as float32 "
                "and cast once on the final write",
                scratch_index=idx, scratch_dtype=dt)
        if not want_unused:
            return
        fn, fndef, params = self._kernel_ref_params()
        if fndef is None or params is None:
            return
        n_in, n_out = len(s.operands), len(s.out_shapes)
        n_scratch = len(s.scratch_shapes)
        nsp = s.num_scalar_prefetch
        if len(params) < nsp + n_in + n_out:
            return  # signature does not line up (varargs etc.) — skip
        roles = ([("scalar", i) for i in range(nsp)]
                 + [("in", i) for i in range(n_in)]
                 + [("out", i) for i in range(n_out)]
                 + [("scratch", i) for i in range(n_scratch)])
        used = {n.id for stmt in fndef.body for n in ast.walk(stmt)
                if isinstance(n, ast.Name)}
        file = None
        try:
            file = inspect.getsourcefile(fn)
        except TypeError:
            file = None
        line = getattr(getattr(fn, "__code__", None), "co_firstlineno",
                       None)
        for pname, (role, i) in zip(params, roles):
            if role in ("in", "scalar") or pname in used \
                    or pname.startswith("_"):
                continue
            self._emit(
                "kernel-unused-ref",
                f"{s.kernel_name}: {role} ref '{pname}' "
                f"({role}[{i}]) is never referenced in the kernel body "
                "— it still occupies VMEM every invocation; drop it or "
                "prefix it with '_' if intentionally reserved",
                file=file, line=line, ref=pname, role=role)


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def check_sites(sites: Iterable[KernelSite], name: Optional[str] = None,
                config: Optional[dict] = None, rules=None) -> List[Finding]:
    """Run every kernel rule over captured sites (pragmas in the
    attributed files are honored, same as the other levels)."""
    cfg = dict(DEFAULT_KERNEL_CONFIG)
    if config:
        cfg.update(config)
    out: List[Finding] = []
    for site in sites:
        out.extend(_SiteChecker(site, cfg, name, rules).run())
    return _core.filter_file_pragmas(out)


def verify_kernel(fn: Callable, *avals, name: Optional[str] = None,
                  config: Optional[dict] = None, rules=None
                  ) -> List[Finding]:
    """Abstractly evaluate ``fn(*avals)`` (ShapeDtypeStructs or arrays —
    nothing executes, no TPU needed) and verify every ``pl.pallas_call``
    it traces.  Returns the findings; empty means the kernel(s) proved
    clean under the exhaustive-grid rules and heuristically clean under
    the rest."""
    import jax
    sites: List[KernelSite] = []
    with capture_sites(sites):
        # a fresh wrapper per call defeats the jit trace cache —
        # eval_shape on a previously-traced (fn, avals) pair would
        # replay the cached jaxpr and never reach the pallas_call shim
        jax.eval_shape(lambda *a: fn(*a), *avals)
    return check_sites(
        sites, config=config, rules=rules,
        name=name or getattr(fn, "__qualname__",
                             getattr(fn, "__name__", repr(fn))))


# ---------------------------------------------------------------------------
# the kernel registry: ops modules register providers at import time so
# the CLI / tier-1 ratchet can sweep every shipped kernel.
# ---------------------------------------------------------------------------

_CASES: List[tuple] = []            # (case_name, fn, avals)
_PROVIDERS: Dict[str, Callable] = {}  # provider name -> () -> [cases]


def register_kernel_case(name: str, fn: Callable, avals: tuple) -> None:
    """Register one (name, traceable fn, example avals) case directly."""
    _CASES.append((name, fn, tuple(avals)))


def register_kernel_provider(name: str, provider: Callable) -> None:
    """Register a lazy case provider (called only when a sweep runs) —
    the import-time hook ops/pallas_ops.py uses."""
    _PROVIDERS[name] = provider


def registered_cases() -> List[tuple]:
    """All registered cases, importing the built-in kernel library first
    so its import-time registration has happened."""
    try:
        import importlib
        importlib.import_module("paddle_tpu.ops.pallas_ops")
    except ImportError:  # standalone / jax-free environment
        importlib = None
    cases = list(_CASES)
    providers = dict(_PROVIDERS)
    # When this module was loaded standalone (the CLI's
    # "tpu_lint_analysis" alias), import-time registration from
    # pallas_ops landed in the canonical package module — merge it.
    canon = sys.modules.get("paddle_tpu.analysis.kernel_checks")
    if canon is not None and canon.__dict__ is not globals():
        cases.extend(getattr(canon, "_CASES", []))
        providers.update(getattr(canon, "_PROVIDERS", {}))
    for pname in sorted(providers):
        cases.extend(providers[pname]())
    return cases


def verify_registered(names=None, config: Optional[dict] = None,
                      rules=None) -> List[Finding]:
    """Sweep every registered kernel case through :func:`verify_kernel`.
    A case that fails to even trace becomes a ``kernel-verifier-error``
    finding rather than an exception — the sweep always completes."""
    out: List[Finding] = []
    for case_name, fn, avals in registered_cases():
        if names is not None and case_name not in names:
            continue
        try:
            out.extend(verify_kernel(fn, *avals, name=case_name,
                                     config=config, rules=rules))
        except Exception as e:
            out.append(Finding(
                rule="kernel-verifier-error", severity=WARNING,
                message=f"kernel case '{case_name}' failed to trace: "
                        f"{type(e).__name__}: {e}",
                function=case_name, source="kernel",
                extra={"case": case_name}))
    return out


def verify_module(path: str, config: Optional[dict] = None,
                  rules=None) -> Tuple[List[Finding], int]:
    """Load a python file and verify the cases its
    ``kernel_verify_cases()`` hook returns.  Used by the CLI
    ``--kernels`` mode for out-of-tree kernel modules.  Returns
    (findings, number of cases run)."""
    import importlib
    import importlib.util
    import os
    # A file inside a package (``__init__.py`` parents) must be imported
    # under its dotted name or its relative imports break; walk up to
    # find the package root, then import normally.
    apath = os.path.abspath(path)
    parts = [os.path.basename(apath)[:-3] if apath.endswith(".py")
             else os.path.basename(apath)]
    pkg_dir = os.path.dirname(apath)
    while os.path.isfile(os.path.join(pkg_dir, "__init__.py")):
        parts.insert(0, os.path.basename(pkg_dir))
        pkg_dir = os.path.dirname(pkg_dir)
    if len(parts) > 1:
        if pkg_dir not in sys.path:
            sys.path.insert(0, pkg_dir)
        mod = importlib.import_module(".".join(parts))
    else:
        modname = "_tpu_lint_kernels_" + parts[0]
        spec = importlib.util.spec_from_file_location(modname, apath)
        if spec is None or spec.loader is None:
            raise ImportError(f"cannot load {path}")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    hook = getattr(mod, "kernel_verify_cases", None)
    if hook is None:
        return [], 0
    out: List[Finding] = []
    cases = list(hook())
    for case_name, fn, avals in cases:
        try:
            out.extend(verify_kernel(fn, *avals, name=case_name,
                                     config=config, rules=rules))
        except Exception as e:
            out.append(Finding(
                rule="kernel-verifier-error", severity=WARNING,
                message=f"kernel case '{case_name}' failed to trace: "
                        f"{type(e).__name__}: {e}",
                file=path, function=case_name, source="kernel",
                extra={"case": case_name}))
    return out, len(cases)
