"""paddle.amp.debugging parity: targeted tensor numerics checks.

Reference analog: python/paddle/amp/debugging.py (TensorCheckerConfig,
enable_tensor_checker/disable_tensor_checker, check_numerics backed by
FLAGS_check_nan_inf + nan_inf_utils). The TPU-native twist: checks must
survive jit — `check_numerics` on a traced Tensor plants a
`jax.debug.callback` (the pattern jit/dy2static.py uses for traced
asserts) so the scan runs on the *host* at execution time, inside the
compiled program, with the configured action.

Gating: everything rides ``FLAGS_tpu_check_nan_inf`` through
`profiler.numerics.enabled()` — one dict lookup + bool check when off.
A check planted while the flag was on at trace time re-consults the
flag at run time, so toggling the flag off silences already-compiled
checks too.

Actions:
  "warn"    — RuntimeWarning naming the site and NaN/Inf counts
  "raise"   — NonFiniteError eagerly; inside jit the error surfaces
              through XLA as an XlaRuntimeError carrying the message
  "collect" — append a finding to ``numerics.collected()`` (bounded)
"""
from __future__ import annotations

import threading
from typing import Optional

from ..profiler import numerics as _numerics

__all__ = ["DebugMode", "TensorCheckerConfig", "enable_tensor_checker",
           "disable_tensor_checker", "check_numerics", "checker_config",
           "advance_step", "collect_results", "clear_results"]


class DebugMode:
    """reference: paddle.amp.debugging.DebugMode enum."""

    CHECK_NAN_INF_AND_ABORT = "raise"
    CHECK_NAN_INF = "warn"
    CHECK_ALL = "collect"


_VALID_ACTIONS = ("warn", "raise", "collect")


class TensorCheckerConfig:
    """Configuration of the global tensor checker.

    Args:
        enable: master switch (enable_tensor_checker also sets
            ``FLAGS_tpu_check_nan_inf`` so instrumented hot paths wake).
        debug_mode / action: "warn" | "raise" | "collect" (DebugMode
            constants map onto these).
        start_step / end_step: optional [start, end) step window; steps
            advance via `advance_step()` (hapi train_batch calls it once
            per batch; manual loops may call it themselves). Outside the
            window checks are skipped entirely.
    """

    def __init__(self, enable: bool = True,
                 debug_mode: str = DebugMode.CHECK_NAN_INF_AND_ABORT,
                 start_step: Optional[int] = None,
                 end_step: Optional[int] = None,
                 output_dir: Optional[str] = None):
        if debug_mode not in _VALID_ACTIONS:
            raise ValueError(
                f"debug_mode must be one of {_VALID_ACTIONS} (or a "
                f"DebugMode constant), got {debug_mode!r}")
        self.enable = bool(enable)
        self.action = debug_mode
        self.start_step = start_step
        self.end_step = end_step
        self.output_dir = output_dir
        self._step = 0

    def in_window(self) -> bool:
        if self.start_step is not None and self._step < self.start_step:
            return False
        if self.end_step is not None and self._step >= self.end_step:
            return False
        return True

    def update_and_check_step(self) -> bool:
        self._step += 1
        return self.in_window()


_LOCK = threading.Lock()
_CONFIG: list = [None]


def checker_config() -> Optional[TensorCheckerConfig]:
    return _CONFIG[0]


def enable_tensor_checker(config: Optional[TensorCheckerConfig] = None):
    """Install ``config`` (default: raise-on-NaN/Inf) as the global
    tensor checker and switch ``FLAGS_tpu_check_nan_inf`` on."""
    from ..core import flags as _flags

    cfg = config or TensorCheckerConfig()
    with _LOCK:
        _CONFIG[0] = cfg
        _flags._REGISTRY["FLAGS_tpu_check_nan_inf"] = bool(cfg.enable)
    return cfg


def disable_tensor_checker():
    """Uninstall the checker and switch the watchdog flag off."""
    from ..core import flags as _flags

    with _LOCK:
        _CONFIG[0] = None
        _flags._REGISTRY["FLAGS_tpu_check_nan_inf"] = False


def advance_step():
    """Advance the checker's step counter (no-op without a config).
    Called once per training step by hapi train_batch so
    start_step/end_step windows track real steps."""
    cfg = _CONFIG[0]
    if cfg is not None:
        cfg.update_and_check_step()


def _default_action() -> str:
    cfg = _CONFIG[0]
    return cfg.action if cfg is not None else "warn"


def _host_check(name: str, action: str, arr):
    """Runs on the host (directly, or via jax.debug.callback from inside
    a compiled program). Re-checks the flag so compiled-in checks go
    quiet when the watchdog is switched off after compilation."""
    if not _numerics.enabled():
        return
    cfg = _CONFIG[0]
    if cfg is not None and not cfg.in_window():
        return
    summary = _numerics._summarize_array(arr)
    _numerics.record_site(name, summary is not None, summary)
    if summary is not None:
        _numerics._dispatch(name, summary, action)


def check_numerics(x, name: str = "tensor", action: Optional[str] = None):
    """Scan ``x`` for NaN/Inf at the watchdog site ``name``.

    Works both eagerly and inside traced code: a concrete Tensor/array
    is checked immediately; a traced one gets a `jax.debug.callback`
    planted in the program, so the check runs at execution time on the
    device-computed value. Returns ``x`` unchanged either way, so it can
    be dropped inline: ``h = check_numerics(h, "attn_out")``.

    With ``FLAGS_tpu_check_nan_inf`` off this is a dict lookup + bool
    check and returns immediately (no trace-time work is planted).
    """
    if not _numerics.enabled():
        return x
    if action is None:
        action = _default_action()
    elif action not in _VALID_ACTIONS:
        raise ValueError(
            f"action must be one of {_VALID_ACTIONS}, got {action!r}")
    import jax

    from ..core.tensor import Tensor

    arr = x._array if isinstance(x, Tensor) else x
    if not hasattr(arr, "dtype"):
        return x
    if isinstance(arr, jax.core.Tracer):
        # traced: plant a host callback carrying the full array — the
        # host side counts NaN/Inf and fires the action per config
        jax.debug.callback(_host_check, name, action, arr)
        return x
    _host_check(name, action, arr)
    return x


def collect_results():
    """Findings recorded by action='collect' checks (oldest first)."""
    return _numerics.collected()


def clear_results():
    _numerics.clear_collected()
