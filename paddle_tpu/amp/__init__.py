"""AMP — mixed precision.

Reference analog: python/paddle/amp/ (auto_cast O1/O2 with per-op white/
black lists at auto_cast.py:135-149, GradScaler at grad_scaler.py:38; cast
insertion generated into ad_funcs by eager_gen.py).

TPU-native stance: bf16 is the native mixed-precision dtype and needs NO
loss scaling; auto_cast with dtype='bfloat16' casts white-list op inputs in
apply_op (the ad_func hook point). GradScaler is kept for fp16 parity and
becomes a no-op passthrough when scaling is unnecessary (use_dynamic_loss_
scaling honored for fp16).
"""
from __future__ import annotations

import threading
from typing import Optional

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import dtype as dtype_mod

__all__ = ["auto_cast", "amp_guard", "GradScaler", "decorate",
           "white_list", "black_list", "debugging"]

# O1 lists (reference: python/paddle/amp/auto_cast.py:135-149)
WHITE_LIST = {"matmul", "bmm", "mv", "linear", "conv1d", "conv2d", "conv3d",
              "conv1d_transpose", "conv2d_transpose", "conv3d_transpose",
              "einsum", "scaled_dot_product_attention"}
BLACK_LIST = {"exp", "square", "log", "log2", "log10", "log1p", "mean",
              "sum", "cos_sim", "softmax", "log_softmax",
              "softmax_with_cross_entropy", "cross_entropy",
              "sigmoid_focal_loss", "binary_cross_entropy", "cumsum",
              "layer_norm", "batch_norm", "rms_norm", "norm", "logsumexp",
              "erf", "erfinv"}


def white_list():
    return {"float16": {"O1": WHITE_LIST, "O2": WHITE_LIST},
            "bfloat16": {"O1": WHITE_LIST, "O2": WHITE_LIST}}


def black_list():
    return {"float16": {"O1": BLACK_LIST, "O2": set()},
            "bfloat16": {"O1": BLACK_LIST, "O2": set()}}


from ..core import tensor as _tensor_mod


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"
        self.custom_white = set()
        self.custom_black = set()


_STATE = _AmpState()


def amp_state():
    return _STATE


class auto_cast:
    """Context manager: `with paddle.amp.auto_cast(level='O1'): ...`"""

    def __init__(self, enable=True, custom_white_list=None,
                 custom_black_list=None, level="O1", dtype="bfloat16",
                 use_promote=True):
        self.enable = enable
        self.level = level
        self.dtype = dtype_mod.convert_dtype(dtype)
        self.custom_white = set(custom_white_list or [])
        self.custom_black = set(custom_black_list or [])

    def __enter__(self):
        self._saved = (_STATE.enabled, _STATE.dtype, _STATE.level,
                       _STATE.custom_white, _STATE.custom_black)
        _STATE.enabled = self.enable
        _STATE.dtype = self.dtype
        _STATE.level = self.level
        _STATE.custom_white = self.custom_white
        _STATE.custom_black = self.custom_black
        return self

    def __exit__(self, *exc):
        (_STATE.enabled, _STATE.dtype, _STATE.level, _STATE.custom_white,
         _STATE.custom_black) = self._saved
        return False


amp_guard = auto_cast


def amp_cast_inputs(op_name, arrays):
    """Called from apply_op when AMP is on: white-list ops run in low
    precision, black-list ops in fp32, others follow inputs (promote)."""
    if not _STATE.enabled:
        return arrays
    name = op_name.split(".")[-1]
    low = _STATE.dtype
    white = (WHITE_LIST | _STATE.custom_white) - _STATE.custom_black
    black = (BLACK_LIST | _STATE.custom_black) - _STATE.custom_white
    if _STATE.level == "O2":
        if name in black:
            return [a.astype(jnp.float32)
                    if jnp.issubdtype(a.dtype, jnp.floating) else a
                    for a in arrays]
        return [a.astype(low)
                if jnp.issubdtype(a.dtype, jnp.floating) else a
                for a in arrays]
    if name in white:
        return [a.astype(low)
                if jnp.issubdtype(a.dtype, jnp.floating) else a
                for a in arrays]
    if name in black:
        return [a.astype(jnp.float32)
                if jnp.issubdtype(a.dtype, jnp.floating) else a
                for a in arrays]
    return arrays


_tensor_mod._AMP_CAST_HOOK[0] = amp_cast_inputs


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2 decoration: cast model params to the low dtype (keeping fp32
    master weights inside the optimizer accumulators, which are fp32 by
    construction here)."""
    if level == "O2":
        dt = dtype_mod.convert_dtype(dtype)
        items = models if isinstance(models, (list, tuple)) else [models]
        for m in items:
            m.to(dtype=dt)
    if optimizers is None:
        return models
    return models, optimizers


# per-optimizer unscale bookkeeping (reference: grad_scaler.py
# OptimizerState READY/UNSCALED/STEPPED) — what prevents the canonical
# `scaler.unscale_(opt); clip; scaler.step(opt)` pattern from dividing
# gradients by the scale twice
_READY, _UNSCALED, _STEPPED = "ready", "unscaled", "stepped"


class GradScaler:
    """Dynamic loss scaling (reference: grad_scaler.py:AmpScaler). On TPU
    with bf16 this is a passthrough; with fp16 it scales and checks
    found_inf exactly like the reference.

    Telemetry (FLAGS_tpu_metrics): `amp_loss_scale` gauge plus
    `amp_found_inf_total` / `amp_skipped_steps_total` counters, mirrored
    into the Profiler "Numerics" section.
    """

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = bool(enable)
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        # id(optimizer) -> _READY/_UNSCALED/_STEPPED, cleared by update()
        self._opt_states: dict = {}

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def _unscale_grads(self, optimizer):
        """Divide all grads by the scale and check finiteness with ONE
        fused reduction / host sync (the old path ran a blocking
        `bool(jnp.any(...))` per parameter)."""
        inv = 1.0 / self._scale
        params = [p for p in optimizer._parameter_list
                  if p.grad is not None]
        if not params:
            self._found_inf = False
            return
        unscaled = [p.grad._array.astype(jnp.float32) * inv
                    for p in params]
        finite_flags = [jnp.all(jnp.isfinite(g)) for g in unscaled]
        all_finite = finite_flags[0]
        for f in finite_flags[1:]:
            all_finite = jnp.logical_and(all_finite, f)
        found = not bool(all_finite)  # the single host sync
        for p, g in zip(params, unscaled):
            p.grad._set_array(g)
        self._found_inf = found
        if found:
            from ..profiler import metrics as _metrics, \
                numerics as _numerics
            if _metrics.enabled():
                _metrics.counter(
                    "amp_found_inf_total",
                    "Unscale passes that found non-finite grads").inc()
            if _numerics.enabled():
                _numerics.record_site(
                    "grad_scaler.unscale", True,
                    {"nan": -1, "inf": -1, "size": len(params),
                     "shape": [], "dtype": "float32"})

    def unscale_(self, optimizer):
        """Explicit unscale (for clipping between unscale and step).
        Calling it twice before step()/update() raises, like the
        reference's OptimizerState.UNSCALED guard."""
        if not self._enable:
            return
        state = self._opt_states.get(id(optimizer), _READY)
        if state == _UNSCALED:
            raise RuntimeError(
                "unscale_() has already been called on this optimizer "
                "since the last update()")
        if state == _STEPPED:
            raise RuntimeError(
                "unscale_() is being called after step(); call update() "
                "first")
        self._unscale_grads(optimizer)
        self._opt_states[id(optimizer)] = _UNSCALED

    def step(self, optimizer):
        # like the reference AmpScaler.step: no scale update here — the
        # canonical pattern is scaler.step(opt); scaler.update()
        if not self._enable:
            optimizer.step()
            return
        state = self._opt_states.get(id(optimizer), _READY)
        if state == _STEPPED:
            raise RuntimeError(
                "step() has already been called on this optimizer since "
                "the last update()")
        if state != _UNSCALED:
            # not explicitly unscaled by the caller — unscale exactly
            # once here (the double-unscale fix)
            self._unscale_grads(optimizer)
        if not self._found_inf:
            optimizer.step()
        else:
            from ..profiler import metrics as _metrics
            if _metrics.enabled():
                _metrics.counter(
                    "amp_skipped_steps_total",
                    "Optimizer steps skipped on non-finite grads").inc()
        self._opt_states[id(optimizer)] = _STEPPED

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)
        self.update()

    def update(self):
        self._opt_states.clear()
        if not (self._enable and self._dynamic):
            self._found_inf = False
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
            self._found_inf = False
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        from ..profiler import metrics as _metrics, numerics as _numerics
        if _metrics.enabled():
            _metrics.gauge("amp_loss_scale",
                           "Current dynamic loss scale").set(self._scale)
        _numerics.note("loss_scale", self._scale)

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        if not self._enable:
            # reference contract: a disabled scaler round-trips as {}
            return {"enable": False}
        return {"enable": self._enable,
                "use_dynamic_loss_scaling": self._dynamic,
                "scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_every_n_steps": self._incr_every,
                "decr_every_n_nan_or_inf": self._decr_every,
                "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, sd):
        self._enable = bool(sd.get("enable", self._enable))
        if not self._enable:
            return
        self._dynamic = bool(sd.get("use_dynamic_loss_scaling",
                                    self._dynamic))
        self._scale = float(sd.get("scale", self._scale))
        self._incr_ratio = sd.get("incr_ratio", self._incr_ratio)
        self._decr_ratio = sd.get("decr_ratio", self._decr_ratio)
        self._incr_every = sd.get("incr_every_n_steps", self._incr_every)
        self._decr_every = sd.get("decr_every_n_nan_or_inf",
                                  self._decr_every)
        self._good_steps = int(sd.get("good_steps", 0))
        self._bad_steps = int(sd.get("bad_steps", 0))


def _norm_param_ids(model):
    """ids of parameters owned by normalization layers — O2 keeps these
    fp32 (reference: amp_decorate keep_batch_norm_fp32; norm scale/bias
    in low precision destabilizes the running statistics and the tiny
    per-channel affine terms)."""
    from ..nn.layer import norm as _norm

    norm_types = (_norm._BatchNormBase, _norm.LayerNorm, _norm.RMSNorm,
                  _norm.GroupNorm, _norm._InstanceNormBase)
    ids = set()
    for layer in model.sublayers(include_self=True):
        if isinstance(layer, norm_types):
            for p in layer.parameters(include_sublayers=False):
                ids.add(id(p))
    return ids


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """paddle.amp.decorate parity (python/paddle/amp/auto_cast.py
    decorate/amp_decorate): O2 casts the model's float parameters to the
    low precision dtype and switches the optimizer(s) to fp32
    master-weight updates (the multi_precision contract of the fused
    optimizer kernels). O1 returns everything unchanged — per-op list
    casting happens inside auto_cast.

    Returns (models, optimizers) with the same single/list structure the
    caller passed.
    """
    import jax.numpy as jnp

    if level not in ("O1", "O2"):
        raise ValueError(f"level must be 'O1' or 'O2', got {level!r}")
    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    single_opt = optimizers is not None and \
        not isinstance(optimizers, (list, tuple))
    opt_list = [] if optimizers is None else (
        [optimizers] if single_opt else list(optimizers))

    if level == "O2":
        low = jnp.bfloat16 if dtype == "bfloat16" else jnp.float16
        for m in model_list:
            keep_fp32 = _norm_param_ids(m)
            for p in m.parameters():
                if id(p) in keep_fp32:
                    continue
                if p._array.dtype in (jnp.float32, jnp.float64):
                    p._set_array(p._array.astype(low))
        for opt in opt_list:
            if master_weight is not False:
                opt._use_master_weights = True

    models_out = model_list[0] if single_model else model_list
    if optimizers is None:
        return models_out
    return models_out, (opt_list[0] if single_opt else opt_list)


# numerics debugging (paddle.amp.debugging analog): TensorCheckerConfig,
# enable_tensor_checker, check_numerics — see docs/observability.md
from . import debugging  # noqa: E402
