"""Text datasets (reference: python/paddle/text/datasets/ — Conll05st,
Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16).

Network download is unavailable (zero-egress), and real-corpus parsing is
not implemented: passing `data_file` raises NotImplementedError. Each
dataset instead produces a deterministic synthetic corpus with the same
record structure as the real one — the hermetic-CI pattern shared with
vision.datasets."""
from __future__ import annotations

import numpy as np

from ..io.dataset import Dataset

__all__ = ["Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
           "WMT14", "WMT16"]


class _SyntheticTextDataset(Dataset):
    """Deterministic token-id sequences; subclasses define record shape.
    Positional order (data_file, mode) matches the reference datasets."""

    def __init__(self, data_file=None, mode="train", seed=0):
        if data_file is not None:
            raise NotImplementedError(
                f"{type(self).__name__}: loading a real corpus from "
                f"data_file is not supported in this zero-egress build; "
                f"omit data_file to use the deterministic synthetic "
                f"corpus (same record structure).")
        self.mode = mode
        self.data_file = data_file
        self._rng = np.random.RandomState(
            seed if mode == "train" else seed + 1)
        self._build()

    def _build(self):
        raise NotImplementedError

    def __len__(self):
        return len(self._records)

    def __getitem__(self, idx):
        return self._records[idx]


class Imdb(_SyntheticTextDataset):
    """Sentiment classification: (token_ids, label). reference:
    text/datasets/imdb.py."""

    def __init__(self, data_file=None, mode="train", cutoff=150):
        self.cutoff = cutoff
        self.word_idx = {f"w{i}": i for i in range(5000)}
        super().__init__(data_file, mode, seed=10)

    def _build(self):
        n = 512 if self.mode == "train" else 128
        self._records = []
        for _ in range(n):
            length = self._rng.randint(8, 64)
            doc = self._rng.randint(0, 5000, (length,)).astype(np.int64)
            label = np.int64(self._rng.randint(0, 2))
            self._records.append((doc, label))


class Imikolov(_SyntheticTextDataset):
    """N-gram LM windows (reference: text/datasets/imikolov.py)."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50):
        self.window_size = window_size
        self.data_type = data_type
        self.word_idx = {f"w{i}": i for i in range(2000)}
        super().__init__(data_file, mode, seed=11)

    def _build(self):
        n = 1024 if self.mode == "train" else 256
        if self.data_type == "NGRAM":
            self._records = [
                tuple(self._rng.randint(0, 2000, (self.window_size,))
                      .astype(np.int64))
                for _ in range(n)]
        else:  # SEQ
            self._records = [
                self._rng.randint(0, 2000,
                                  (self._rng.randint(4, 20),))
                .astype(np.int64)
                for _ in range(n)]


class Movielens(_SyntheticTextDataset):
    """Rating records (user, movie, rating feature tuple). reference:
    text/datasets/movielens.py."""

    def _build(self):
        n = 1024 if self.mode == "train" else 256
        self._records = []
        for _ in range(n):
            user_id = np.int64(self._rng.randint(1, 6041))
            gender = np.int64(self._rng.randint(0, 2))
            age = np.int64(self._rng.randint(0, 7))
            job = np.int64(self._rng.randint(0, 21))
            movie_id = np.int64(self._rng.randint(1, 3953))
            categories = self._rng.randint(0, 18, (3,)).astype(np.int64)
            title = self._rng.randint(0, 5000, (4,)).astype(np.int64)
            rating = np.float32(self._rng.randint(1, 6))
            self._records.append((user_id, gender, age, job, movie_id,
                                  categories, title, rating))


class UCIHousing(_SyntheticTextDataset):
    """13 features → price (reference: text/datasets/uci_housing.py)."""

    def _build(self):
        n = 404 if self.mode == "train" else 102
        feats = self._rng.randn(n, 13).astype(np.float32)
        w = self._rng.randn(13).astype(np.float32)
        prices = (feats @ w + self._rng.randn(n) * 0.1).astype(np.float32)
        self._records = [(feats[i], prices[i:i + 1]) for i in range(n)]


class Conll05st(_SyntheticTextDataset):
    """SRL records: word/predicate/ctx windows + mark + labels.
    reference: text/datasets/conll05.py."""

    def _build(self):
        n = 256 if self.mode == "train" else 64
        self._records = []
        for _ in range(n):
            length = self._rng.randint(5, 30)
            word = self._rng.randint(0, 44068, (length,)).astype(np.int64)
            pred = np.full((length,), self._rng.randint(0, 3162),
                           np.int64)
            ctx = [self._rng.randint(0, 44068, (length,)).astype(np.int64)
                   for _ in range(5)]
            mark = self._rng.randint(0, 2, (length,)).astype(np.int64)
            label = self._rng.randint(0, 59, (length,)).astype(np.int64)
            self._records.append((word, *ctx, pred, mark, label))


class _WMTBase(_SyntheticTextDataset):
    src_vocab = 30000
    trg_vocab = 30000

    def __init__(self, data_file=None, mode="train", dict_size=-1):
        self.dict_size = dict_size if dict_size > 0 else self.src_vocab
        super().__init__(data_file, mode, seed=13)

    def _build(self):
        n = 256 if self.mode == "train" else 64
        self._records = []
        for _ in range(n):
            sl = self._rng.randint(4, 25)
            tl = self._rng.randint(4, 25)
            src = self._rng.randint(0, self.dict_size, (sl,)) \
                .astype(np.int64)
            trg = self._rng.randint(0, self.dict_size, (tl,)) \
                .astype(np.int64)
            trg_next = np.concatenate([trg[1:], [1]]).astype(np.int64)
            self._records.append((src, trg, trg_next))


class WMT14(_WMTBase):
    """reference: text/datasets/wmt14.py."""


class WMT16(_WMTBase):
    """reference: text/datasets/wmt16.py."""
