"""Viterbi decoding for CRF-style sequence labelling.

Reference: python/paddle/text/viterbi_decode.py (ViterbiDecoder layer →
_C_ops.viterbi_decode, CUDA kernel at
paddle/phi/kernels/gpu/viterbi_decode_kernel.cu).

TPU-native: the time recursion is a lax.scan over the sequence axis; each
step is a batched [B, T, T] max-sum — dense, static-shape work the VPU/MXU
handle well. Backtracking is a second (reversed) scan over the argmax
history."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .. import nn
from ..core.tensor import Tensor, apply_op

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def _viterbi_arrays(potentials, transition, lengths, include_bos_eos_tag):
    """potentials [B, L, N] fp, transition [N, N], lengths [B] int.

    BOS/EOS semantics mirror the reference kernel
    (paddle/phi/kernels/cpu/viterbi_decode_kernel.cc:229-279: the
    transition matrix's LAST row is the start tag, the SECOND-TO-LAST row
    the stop tag; the start row is added at t=0, the stop row at each
    sequence's last valid step, and no tag is barred from emission)."""
    B, L, N = potentials.shape
    lengths = lengths.astype(jnp.int32)
    pots = jnp.swapaxes(potentials, 0, 1)  # [L, B, N]
    steps = jnp.arange(1, L)

    if include_bos_eos_tag:
        start_row = transition[N - 1][None, :]
        stop_row = transition[N - 2][None, :]
        alpha0 = pots[0] + start_row
        alpha0 = alpha0 + jnp.where((lengths == 1)[:, None], stop_row, 0.0)
    else:
        alpha0 = pots[0]

    def step(alpha, t):
        # alpha [B, N]; candidate scores [B, prev N, next N]
        scores = alpha[:, :, None] + transition[None, :, :] \
            + pots[t][:, None, :]
        best_prev = jnp.argmax(scores, axis=1)          # [B, N]
        new_alpha = jnp.max(scores, axis=1)             # [B, N]
        # sequences already past their length keep their alpha
        active = (t < lengths)[:, None]
        new_alpha = jnp.where(active, new_alpha, alpha)
        if include_bos_eos_tag:
            new_alpha = new_alpha + jnp.where(
                (t == lengths - 1)[:, None], stop_row, 0.0)
        return new_alpha, (best_prev, active)

    alpha, (history, actives) = lax.scan(step, alpha0, steps)

    scores = jnp.max(alpha, axis=-1)
    last_tag = jnp.argmax(alpha, axis=-1)               # [B]

    def back(tag, hist_active):
        hist, active = hist_active                      # [B, N], [B, 1]
        prev = jnp.take_along_axis(hist, tag[:, None], axis=1)[:, 0]
        tag_new = jnp.where(active[:, 0], prev, tag)
        return tag_new, tag

    _, path_rev = lax.scan(back, last_tag, (history, actives),
                           reverse=True)
    first_tag = _
    path = jnp.concatenate([first_tag[None], path_rev], axis=0)  # [L, B]
    path = jnp.swapaxes(path, 0, 1)                     # [B, L]
    # zero-pad beyond each sequence's length (reference returns only the
    # valid prefix per row; with static shapes we mask instead)
    mask = jnp.arange(L)[None, :] < lengths[:, None]
    path = jnp.where(mask, path, 0)
    return scores, path.astype(jnp.int64)


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """Returns (scores [B], paths [B, L]) — reference
    python/paddle/text/viterbi_decode.py:viterbi_decode."""
    return apply_op(
        lambda p, t, l: _viterbi_arrays(p, t, l, include_bos_eos_tag),
        potentials, transition_params, lengths, op_name="viterbi_decode",
        n_outs=2)


class ViterbiDecoder(nn.Layer):
    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions if isinstance(transitions, Tensor) \
            else Tensor(jnp.asarray(transitions))
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
