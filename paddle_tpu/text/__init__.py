"""paddle.text parity (reference: python/paddle/text/__init__.py).

ViterbiDecoder/viterbi_decode are implemented with lax.scan (static trip
count, MXU-friendly batched max-sum recursions) instead of the reference's
CUDA viterbi_decode op (paddle/phi/kernels/gpu/viterbi_decode_kernel.cu).
Datasets mirror the reference list with hermetic synthetic backends
(zero-egress environments; same pattern as vision.datasets)."""
from .viterbi_decode import ViterbiDecoder, viterbi_decode  # noqa: F401
from .datasets import (  # noqa: F401
    Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16)

__all__ = [
    "Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing", "WMT14",
    "WMT16", "ViterbiDecoder", "viterbi_decode",
]
