"""Metrics.

Reference analog: python/paddle/metric/metrics.py (Metric/Accuracy/
Precision/Recall/Auc) + paddle.metric.accuracy op.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op
from ..ops.registry import _ensure_tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def accuracy(input, label, k=1, correct=None, total=None, name=None):  # noqa: A002
    input, label = _ensure_tensor(input), _ensure_tensor(label)

    def _f(pred, lab):
        topk_idx = jnp.argsort(-pred, axis=-1)[..., :k]
        lab_ = lab.reshape(-1, 1)
        hit = jnp.any(topk_idx == lab_, axis=-1)
        return jnp.mean(hit.astype(jnp.float32))
    return apply_op(_f, input, label, op_name="accuracy")


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred_arr = pred._array if isinstance(pred, Tensor) else jnp.asarray(pred)
        lab_arr = label._array if isinstance(label, Tensor) \
            else jnp.asarray(label)
        topk_idx = jnp.argsort(-pred_arr, axis=-1)[..., :self.maxk]
        if lab_arr.ndim == pred_arr.ndim and lab_arr.shape[-1] == 1:
            lab = lab_arr
        elif lab_arr.ndim == pred_arr.ndim - 1:
            lab = lab_arr[..., None]
        else:  # one-hot
            lab = jnp.argmax(lab_arr, axis=-1)[..., None]
        correct = (topk_idx == lab)
        return Tensor(correct)

    def update(self, correct, *args):
        arr = np.asarray(correct._array if isinstance(correct, Tensor)
                         else correct)
        num_samples = arr.shape[0] if arr.ndim else 1
        accs = []
        for k in self.topk:
            c = arr[..., :k].any(axis=-1).sum()
            self.total[self.topk.index(k)] += float(c)
            self.count[self.topk.index(k)] += num_samples
            accs.append(float(c) / num_samples)
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / c if c else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = np.asarray(preds._array if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._array if isinstance(labels, Tensor)
                       else labels)
        pred_cls = (p > 0.5).astype(np.int32).reshape(-1)
        l = l.reshape(-1).astype(np.int32)
        self.tp += int(((pred_cls == 1) & (l == 1)).sum())
        self.fp += int(((pred_cls == 1) & (l == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = np.asarray(preds._array if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._array if isinstance(labels, Tensor)
                       else labels)
        pred_cls = (p > 0.5).astype(np.int32).reshape(-1)
        l = l.reshape(-1).astype(np.int32)
        self.tp += int(((pred_cls == 1) & (l == 1)).sum())
        self.fn += int(((pred_cls == 0) & (l == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        p = np.asarray(preds._array if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._array if isinstance(labels, Tensor)
                       else labels).reshape(-1)
        if p.ndim == 2:
            p = p[:, 1]
        idx = np.minimum((p * self.num_thresholds).astype(np.int64),
                         self.num_thresholds)
        for i, lab in zip(idx, l):
            if lab:
                self._stat_pos[i] += 1
            else:
                self._stat_neg[i] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        # trapezoid over thresholds, descending
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapz(tpr, fpr))

    def name(self):
        return self._name
