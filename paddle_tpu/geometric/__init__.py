"""Graph-NN message passing.

Reference analog: python/paddle/geometric/ (send_u_recv/send_ue_recv/
segment_* over phi graph_send_recv kernels). TPU-native: jax.ops.segment_sum
family — XLA lowers to sorted-scatter which tiles well.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op
from ..ops.registry import register, _ensure_tensor

__all__ = ["send_u_recv", "send_ue_recv", "send_uv", "segment_sum",
           "segment_mean", "segment_max", "segment_min",
           "sample_neighbors", "reindex_graph", "reindex_heter_graph"]


def _segment(name, combiner):
    def op(data, segment_ids, name=None):
        data, segment_ids = _ensure_tensor(data), _ensure_tensor(segment_ids)
        num = int(jnp.max(segment_ids._array)) + 1 \
            if segment_ids._array.size else 0

        def _f(d, s):
            return combiner(d, s.astype(jnp.int32), num)
        return apply_op(_f, data, segment_ids, op_name=op.__name__)
    op.__name__ = name
    register(name, op)
    return op


segment_sum = _segment(
    "segment_sum",
    lambda d, s, n: jax.ops.segment_sum(d, s, num_segments=n))
segment_mean = _segment(
    "segment_mean",
    lambda d, s, n: jax.ops.segment_sum(d, s, num_segments=n)
    / jnp.maximum(jax.ops.segment_sum(jnp.ones_like(d), s, num_segments=n),
                  1))
segment_max = _segment(
    "segment_max",
    lambda d, s, n: jax.ops.segment_max(d, s, num_segments=n))
segment_min = _segment(
    "segment_min",
    lambda d, s, n: jax.ops.segment_min(d, s, num_segments=n))


_REDUCERS = {
    "sum": jax.ops.segment_sum,
    "mean": None,  # handled specially
    "max": jax.ops.segment_max,
    "min": jax.ops.segment_min,
}


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    x = _ensure_tensor(x)
    src_index = _ensure_tensor(src_index)
    dst_index = _ensure_tensor(dst_index)
    n_out = out_size or x.shape[0]

    def _f(xa, si, di):
        msgs = jnp.take(xa, si.astype(jnp.int32), axis=0)
        di = di.astype(jnp.int32)
        if reduce_op == "mean":
            s = jax.ops.segment_sum(msgs, di, num_segments=n_out)
            c = jax.ops.segment_sum(jnp.ones_like(msgs), di,
                                    num_segments=n_out)
            return s / jnp.maximum(c, 1)
        return _REDUCERS[reduce_op](msgs, di, num_segments=n_out)
    return apply_op(_f, x, src_index, dst_index, op_name="send_u_recv")


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    x, y = _ensure_tensor(x), _ensure_tensor(y)
    src_index = _ensure_tensor(src_index)
    dst_index = _ensure_tensor(dst_index)
    n_out = out_size or x.shape[0]

    def _f(xa, ya, si, di):
        msgs = jnp.take(xa, si.astype(jnp.int32), axis=0)
        if message_op == "add":
            msgs = msgs + ya
        elif message_op == "mul":
            msgs = msgs * ya
        elif message_op == "sub":
            msgs = msgs - ya
        elif message_op == "div":
            msgs = msgs / ya
        di = di.astype(jnp.int32)
        if reduce_op == "mean":
            s = jax.ops.segment_sum(msgs, di, num_segments=n_out)
            c = jax.ops.segment_sum(jnp.ones_like(msgs), di,
                                    num_segments=n_out)
            return s / jnp.maximum(c, 1)
        return _REDUCERS[reduce_op](msgs, di, num_segments=n_out)
    return apply_op(_f, x, y, src_index, dst_index, op_name="send_ue_recv")


def send_uv(x, y, src_index, dst_index, compute_type="add", name=None):
    """Per-edge message op(x[src], y[dst]) — [E, ...] output
    (reference: python/paddle/geometric/message_passing/send_recv.py
    send_uv over the graph_send_uv phi kernel)."""
    x, y = _ensure_tensor(x), _ensure_tensor(y)
    src_index = _ensure_tensor(src_index)
    dst_index = _ensure_tensor(dst_index)
    ops = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
           "div": jnp.divide}
    assert compute_type in ops, f"unknown compute_type {compute_type!r}"
    fn = ops[compute_type]

    def _f(xa, ya, si, di):
        return fn(xa[si.astype(jnp.int32)], ya[di.astype(jnp.int32)])
    return apply_op(_f, x, y, src_index, dst_index, op_name="send_uv")


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """Uniform CSC neighbor sampling — host-side numpy data prep (the
    reference's kernel is also dynamic-shaped CPU/GPU prep work, not a
    training-loop op; reference:
    python/paddle/geometric/sampling/neighbors.py sample_neighbors)."""
    from ..ops.registry import host_only_guard
    host_only_guard("geometric.sample_neighbors", row, colptr, input_nodes)
    import numpy as np
    rown = np.asarray(row._array if isinstance(row, Tensor) else row)
    colp = np.asarray(colptr._array if isinstance(colptr, Tensor)
                      else colptr)
    nodes = np.asarray(input_nodes._array
                       if isinstance(input_nodes, Tensor) else input_nodes)
    eid_arr = None
    if eids is not None:
        eid_arr = np.asarray(eids._array if isinstance(eids, Tensor)
                             else eids)
    out_n, out_c, out_e = [], [], []
    for nd in nodes.reshape(-1):
        beg, end = int(colp[nd]), int(colp[nd + 1])
        neigh = rown[beg:end]
        idx = np.arange(beg, end)
        if sample_size >= 0 and len(neigh) > sample_size:
            # global numpy RNG: each epoch resamples a fresh subgraph
            pick = np.random.choice(len(neigh), size=sample_size,
                                    replace=False)
            neigh = neigh[pick]
            idx = idx[pick]
        out_n.append(neigh)
        out_c.append(len(neigh))
        if eid_arr is not None:
            out_e.append(eid_arr[idx])
    out_neighbors = Tensor(jnp.asarray(
        np.concatenate(out_n) if out_n else np.zeros(0, rown.dtype)))
    out_count = Tensor(jnp.asarray(np.asarray(out_c, np.int32)))
    if return_eids:
        assert eid_arr is not None, "return_eids requires eids"
        out_eids = Tensor(jnp.asarray(
            np.concatenate(out_e) if out_e else np.zeros(0,
                                                         eid_arr.dtype)))
        return out_neighbors, out_count, out_eids
    return out_neighbors, out_count


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Relabel center nodes + sampled neighbors to contiguous local ids
    (reference: python/paddle/geometric/reindex.py reindex_graph)."""
    import numpy as np
    xa = np.asarray(x._array if isinstance(x, Tensor) else x).reshape(-1)
    na = np.asarray(neighbors._array if isinstance(neighbors, Tensor)
                    else neighbors).reshape(-1)
    ca = np.asarray(count._array if isinstance(count, Tensor)
                    else count).reshape(-1)
    # local id order: centers first (in x order), then first-seen neighbors
    mapping = {}
    for nd in xa:
        mapping.setdefault(int(nd), len(mapping))
    for nd in na:
        mapping.setdefault(int(nd), len(mapping))
    out_nodes = np.fromiter(mapping.keys(), dtype=xa.dtype,
                            count=len(mapping))
    reindex_src = np.asarray([mapping[int(nd)] for nd in na], np.int64)
    reindex_dst = np.repeat(np.arange(len(xa), dtype=np.int64), ca)
    return (Tensor(jnp.asarray(reindex_src)),
            Tensor(jnp.asarray(reindex_dst)),
            Tensor(jnp.asarray(out_nodes)))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Relabel center nodes + per-edge-type neighbor lists with ONE shared
    id space: centers first, then neighbors in first-seen order across the
    edge types in list order; edges of every type are concatenated
    (reference: python/paddle/geometric/reindex.py reindex_heter_graph)."""
    import numpy as np
    if len(neighbors) != len(count):
        raise ValueError(
            f"neighbors and count must pair per edge type: got "
            f"{len(neighbors)} neighbor lists vs {len(count)} count lists")
    xa = np.asarray(x._array if isinstance(x, Tensor) else x).reshape(-1)
    nas = [np.asarray(n._array if isinstance(n, Tensor) else n).reshape(-1)
           for n in neighbors]
    cas = [np.asarray(c._array if isinstance(c, Tensor) else c).reshape(-1)
           for c in count]
    mapping = {}
    for nd in xa:
        mapping.setdefault(int(nd), len(mapping))
    for na in nas:
        for nd in na:
            mapping.setdefault(int(nd), len(mapping))
    out_nodes = np.fromiter(mapping.keys(), dtype=xa.dtype,
                            count=len(mapping))
    src_parts, dst_parts = [], []
    for na, ca in zip(nas, cas):
        src_parts.append(
            np.asarray([mapping[int(nd)] for nd in na], np.int64))
        dst_parts.append(np.repeat(np.arange(len(xa), dtype=np.int64), ca))
    cat = lambda parts: (np.concatenate(parts) if parts  # noqa: E731
                         else np.zeros(0, np.int64))
    return (Tensor(jnp.asarray(cat(src_parts))),
            Tensor(jnp.asarray(cat(dst_parts))),
            Tensor(jnp.asarray(out_nodes)))
