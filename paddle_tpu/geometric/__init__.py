"""Graph-NN message passing.

Reference analog: python/paddle/geometric/ (send_u_recv/send_ue_recv/
segment_* over phi graph_send_recv kernels). TPU-native: jax.ops.segment_sum
family — XLA lowers to sorted-scatter which tiles well.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op
from ..ops.registry import register, _ensure_tensor

__all__ = ["send_u_recv", "send_ue_recv", "segment_sum", "segment_mean",
           "segment_max", "segment_min"]


def _segment(name, combiner):
    def op(data, segment_ids, name=None):
        data, segment_ids = _ensure_tensor(data), _ensure_tensor(segment_ids)
        num = int(jnp.max(segment_ids._array)) + 1 \
            if segment_ids._array.size else 0

        def _f(d, s):
            return combiner(d, s.astype(jnp.int32), num)
        return apply_op(_f, data, segment_ids, op_name=op.__name__)
    op.__name__ = name
    register(name, op)
    return op


segment_sum = _segment(
    "segment_sum",
    lambda d, s, n: jax.ops.segment_sum(d, s, num_segments=n))
segment_mean = _segment(
    "segment_mean",
    lambda d, s, n: jax.ops.segment_sum(d, s, num_segments=n)
    / jnp.maximum(jax.ops.segment_sum(jnp.ones_like(d), s, num_segments=n),
                  1))
segment_max = _segment(
    "segment_max",
    lambda d, s, n: jax.ops.segment_max(d, s, num_segments=n))
segment_min = _segment(
    "segment_min",
    lambda d, s, n: jax.ops.segment_min(d, s, num_segments=n))


_REDUCERS = {
    "sum": jax.ops.segment_sum,
    "mean": None,  # handled specially
    "max": jax.ops.segment_max,
    "min": jax.ops.segment_min,
}


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    x = _ensure_tensor(x)
    src_index = _ensure_tensor(src_index)
    dst_index = _ensure_tensor(dst_index)
    n_out = out_size or x.shape[0]

    def _f(xa, si, di):
        msgs = jnp.take(xa, si.astype(jnp.int32), axis=0)
        di = di.astype(jnp.int32)
        if reduce_op == "mean":
            s = jax.ops.segment_sum(msgs, di, num_segments=n_out)
            c = jax.ops.segment_sum(jnp.ones_like(msgs), di,
                                    num_segments=n_out)
            return s / jnp.maximum(c, 1)
        return _REDUCERS[reduce_op](msgs, di, num_segments=n_out)
    return apply_op(_f, x, src_index, dst_index, op_name="send_u_recv")


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    x, y = _ensure_tensor(x), _ensure_tensor(y)
    src_index = _ensure_tensor(src_index)
    dst_index = _ensure_tensor(dst_index)
    n_out = out_size or x.shape[0]

    def _f(xa, ya, si, di):
        msgs = jnp.take(xa, si.astype(jnp.int32), axis=0)
        if message_op == "add":
            msgs = msgs + ya
        elif message_op == "mul":
            msgs = msgs * ya
        elif message_op == "sub":
            msgs = msgs - ya
        elif message_op == "div":
            msgs = msgs / ya
        di = di.astype(jnp.int32)
        if reduce_op == "mean":
            s = jax.ops.segment_sum(msgs, di, num_segments=n_out)
            c = jax.ops.segment_sum(jnp.ones_like(msgs), di,
                                    num_segments=n_out)
            return s / jnp.maximum(c, 1)
        return _REDUCERS[reduce_op](msgs, di, num_segments=n_out)
    return apply_op(_f, x, y, src_index, dst_index, op_name="send_ue_recv")
