"""paddle.signal parity (reference: python/paddle/signal.py): stft/istft."""
from __future__ import annotations

import jax.numpy as jnp

from .core.tensor import Tensor, apply_op
from .ops.registry import _ensure_tensor

__all__ = ["frame", "overlap_add", "stft", "istft"]


def frame(x, frame_length, hop_length, axis=-1, name=None):
    x = _ensure_tensor(x)

    def _f(a):
        n = a.shape[axis]
        num = 1 + (n - frame_length) // hop_length
        idx = (jnp.arange(frame_length)[None, :]
               + hop_length * jnp.arange(num)[:, None])
        moved = jnp.moveaxis(a, axis, -1)
        framed = moved[..., idx]  # [..., num, frame_length]
        framed = jnp.swapaxes(framed, -1, -2)  # [..., frame_length, num]
        return framed if axis in (-1, a.ndim - 1) else jnp.moveaxis(
            framed, (-2, -1), (axis, axis + 1))
    return apply_op(_f, x, op_name="frame")


def overlap_add(x, hop_length, axis=-1, name=None):
    x = _ensure_tensor(x)

    def _f(a):
        # a: [..., frame_length, num_frames] (axis=-1 layout)
        fl = a.shape[-2]
        num = a.shape[-1]
        n = (num - 1) * hop_length + fl
        out = jnp.zeros(a.shape[:-2] + (n,), a.dtype)
        for i in range(num):
            out = out.at[..., i * hop_length:i * hop_length + fl].add(
                a[..., :, i])
        return out
    return apply_op(_f, x, op_name="overlap_add")


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    x = _ensure_tensor(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    win_arr = window._array if window is not None else jnp.ones(win_length)
    if win_length < n_fft:
        pad = (n_fft - win_length) // 2
        win_arr = jnp.pad(win_arr, (pad, n_fft - win_length - pad))

    def _f(a):
        if center:
            a = jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(n_fft // 2,
                                                       n_fft // 2)],
                        mode=pad_mode)
        n = a.shape[-1]
        num = 1 + (n - n_fft) // hop_length
        idx = (jnp.arange(n_fft)[None, :]
               + hop_length * jnp.arange(num)[:, None])
        frames = a[..., idx] * win_arr  # [..., num, n_fft]
        spec = jnp.fft.rfft(frames, axis=-1) if onesided \
            else jnp.fft.fft(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(n_fft)
        return jnp.swapaxes(spec, -1, -2)  # [..., freq, frames]
    return apply_op(_f, x, op_name="stft")


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    x = _ensure_tensor(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    win_arr = window._array if window is not None else jnp.ones(win_length)
    if win_length < n_fft:
        pad = (n_fft - win_length) // 2
        win_arr = jnp.pad(win_arr, (pad, n_fft - win_length - pad))

    def _f(spec):
        spec = jnp.swapaxes(spec, -1, -2)  # [..., frames, freq]
        if normalized:
            spec = spec * jnp.sqrt(n_fft)
        frames = jnp.fft.irfft(spec, n=n_fft, axis=-1) if onesided \
            else jnp.real(jnp.fft.ifft(spec, axis=-1))
        frames = frames * win_arr
        num = frames.shape[-2]
        n = (num - 1) * hop_length + n_fft
        out = jnp.zeros(frames.shape[:-2] + (n,), frames.dtype)
        wsum = jnp.zeros(n, frames.dtype)
        for i in range(num):
            sl = slice(i * hop_length, i * hop_length + n_fft)
            out = out.at[..., sl].add(frames[..., i, :])
            wsum = wsum.at[sl].add(win_arr * win_arr)
        out = out / jnp.maximum(wsum, 1e-10)
        if center:
            out = out[..., n_fft // 2:-(n_fft // 2)]
        if length is not None:
            out = out[..., :length]
        return out
    return apply_op(_f, x, op_name="istft")
