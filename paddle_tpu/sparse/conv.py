"""Sparse convolution / pooling on COO voxel tensors.

Reference analog: paddle/phi/kernels/sparse/gpu/conv_kernel.cu (Conv3d
over SparseCooTensor via a rulebook of (kernel-offset, in-row, out-row)
triples + gather-GEMM-scatter) and pool_kernel.cu; python face
python/paddle/sparse/nn/layer/conv.py (Conv3D/SubmConv3D) and
pooling (MaxPool3D). Input layout matches the reference: sparse over
(N, D, H, W) (or (N, H, W) for 2-D) with a dense channel tail — a BCOO
with n_dense=1.

TPU-native: the rulebook (index matching) is host-side numpy — the
reference builds it with scatter/unique kernels too, and it is pure
integer bookkeeping on concrete indices. The feature math is the
MXU-shaped part: one gather + (Cin x Cout) GEMM + scatter-add per
kernel offset, composed with jnp so it runs on device and is
differentiable (the eager Layer records it on the autograd tape via
apply_op; loss.backward() trains the kernel).
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor, apply_op
from . import SparseCooTensor

__all__ = ["conv3d", "subm_conv3d", "conv2d", "subm_conv2d",
           "max_pool3d", "Conv3D", "SubmConv3D", "Conv2D", "SubmConv2D",
           "MaxPool3D"]


def _tuple(v, dims):
    if isinstance(v, (list, tuple)):
        assert len(v) == dims, (v, dims)
        return tuple(int(x) for x in v)
    return (int(v),) * dims


def _ravel(batch, pos, out_spatial):
    key = batch.astype(np.int64)
    for d, size in enumerate(out_spatial):
        key = key * int(size) + pos[:, d].astype(np.int64)
    return key


def _build_rulebook(idx, spatial, kernel, stride, padding, subm):
    """(out_idx [n_out, 1+dims], per-offset (in_rows, out_rows)).

    Contribution rule: out[o] += W[off] * in[o*stride - padding + off],
    so voxel q feeds output o = (q + padding - off) / stride when the
    division is exact. Submanifold: output positions == input positions
    (stride 1, implicit same-padding), the SubmConv contract.
    """
    dims = idx.shape[1] - 1
    batch, pos = idx[:, 0], idx[:, 1:]
    if subm:
        out_spatial = tuple(spatial)
        center = np.array([k // 2 for k in kernel])
    else:
        out_spatial = tuple(
            (spatial[d] + 2 * padding[d] - kernel[d]) // stride[d] + 1
            for d in range(dims))
    offs = list(np.ndindex(*kernel))
    cand = []  # per offset: (in_rows, out_keys)
    for off in offs:
        if subm:
            o = pos + center - np.array(off)
            valid = np.ones(len(pos), bool)
        else:
            o = pos + np.array(padding) - np.array(off)
            valid = np.all(o % np.array(stride) == 0, axis=1)
            o = o // np.array(stride)
        valid &= np.all((o >= 0) & (o < np.array(out_spatial)), axis=1)
        rows = np.nonzero(valid)[0]
        cand.append((rows, _ravel(batch[rows], o[rows], out_spatial)))

    if subm:
        out_idx = idx
        sort_keys = _ravel(batch, pos, out_spatial)
        order = np.argsort(sort_keys)
        sorted_keys = sort_keys[order]
    else:
        all_keys = np.unique(np.concatenate([k for _, k in cand])) \
            if cand else np.empty((0,), np.int64)
        sorted_keys = all_keys
        order = None
        # unravel back to coordinates
        out_idx = np.empty((len(all_keys), 1 + dims), idx.dtype)
        rem = all_keys
        for d in range(dims - 1, -1, -1):
            out_idx[:, 1 + d] = rem % out_spatial[d]
            rem = rem // out_spatial[d]
        out_idx[:, 0] = rem

    rulebook = []
    for rows, keys in cand:
        j = np.searchsorted(sorted_keys, keys)
        if subm:
            # membership test: the target position must itself be an
            # input voxel (submanifold outputs never dilate)
            ok = (j < len(sorted_keys)) & (sorted_keys[
                np.clip(j, 0, max(len(sorted_keys) - 1, 0))] == keys)
            rows, j = rows[ok], j[ok]
            out_rows = order[j]
        else:
            out_rows = j
        rulebook.append((rows.astype(np.int32),
                         out_rows.astype(np.int32)))
    return out_idx, out_spatial, rulebook


def _as_value_tensor(x: SparseCooTensor) -> Tensor:
    return x.values()  # tape-linked when the producer attached one


def _coalesce_map(bcoo):
    """(coalesced_idx [n_c, n_sparse], inv [nnz0]) — the rulebook must
    see SORTED UNIQUE positions while the value rows stay in the
    caller's original order (they may carry the autograd tape), so the
    kernel scatters original rows onto coalesced rows via `inv`.
    Building the rulebook from bcoo_sum_duplicates while reading
    x._bcoo.data directly would silently permute values whenever the
    input indices are unsorted (and never sum duplicates)."""
    idx0 = np.asarray(bcoo.indices)
    sizes = [int(s) for s in bcoo.shape[:idx0.shape[1]]]
    keys = idx0[:, 0].astype(np.int64)
    for d in range(1, idx0.shape[1]):
        keys = keys * sizes[d] + idx0[:, d].astype(np.int64)
    uniq, inv = np.unique(keys, return_inverse=True)
    out = np.empty((len(uniq), idx0.shape[1]), idx0.dtype)
    rem = uniq
    for d in range(idx0.shape[1] - 1, 0, -1):
        out[:, d] = rem % sizes[d]
        rem = rem // sizes[d]
    out[:, 0] = rem
    return out, inv.astype(np.int32)


def _wrap_output(out_vals: Tensor, out_idx, shape) -> SparseCooTensor:
    bcoo = jsparse.BCOO(
        (out_vals._array, jnp.asarray(out_idx, jnp.int32)),
        shape=tuple(int(s) for s in shape))
    sp = SparseCooTensor(bcoo, stop_gradient=out_vals.stop_gradient)
    # keep the tape-linked values so .values() grads flow to the kernel
    sp._values_t = out_vals
    return sp


def _sparse_conv(x, weight, bias, stride, padding, subm, dims, name):
    if not isinstance(x, SparseCooTensor):
        raise TypeError(f"{name} expects a SparseCooTensor input")
    b = x._bcoo
    if b.n_dense != 1 or b.n_sparse != 1 + dims:
        raise ValueError(
            f"{name}: input must be sparse over (N,{'DHW'[:dims]}) with "
            f"a dense channel tail; got n_sparse={b.n_sparse}, "
            f"n_dense={b.n_dense}")
    w_arr = weight._array if isinstance(weight, Tensor) else \
        jnp.asarray(weight)
    kernel = tuple(int(k) for k in w_arr.shape[:dims])
    cin, cout = int(w_arr.shape[dims]), int(w_arr.shape[dims + 1])
    if int(b.shape[-1]) != cin:
        raise ValueError(f"{name}: input channels {b.shape[-1]} != "
                         f"weight in_channels {cin}")
    stride = _tuple(stride, dims)
    padding = _tuple(padding, dims)
    if subm and stride != (1,) * dims:
        raise ValueError(f"{name}: submanifold conv requires stride 1")

    idx, inv = _coalesce_map(b)
    n_coal = len(idx)
    spatial = tuple(int(s) for s in b.shape[1:1 + dims])
    out_idx, out_spatial, rulebook = _build_rulebook(
        idx, spatial, kernel, stride, padding, subm)
    n_out = len(out_idx)
    w_flat_shape = (len(rulebook), cin, cout)

    def pure(vals, w, *maybe_bias):
        # coalesce first (sorted unique positions, duplicates summed) so
        # value rows line up with the rulebook's row numbering
        vals = jnp.zeros((n_coal, vals.shape[1]),
                         vals.dtype).at[inv].add(vals)
        wk = w.reshape(w_flat_shape)
        out = jnp.zeros((n_out, cout), vals.dtype)
        for k, (in_rows, out_rows) in enumerate(rulebook):
            if len(in_rows) == 0:
                continue
            out = out.at[out_rows].add(vals[in_rows] @ wk[k])
        if maybe_bias:
            out = out + maybe_bias[0]
        return out

    args = [_as_value_tensor(x),
            weight if isinstance(weight, Tensor) else Tensor(w_arr)]
    if bias is not None:
        args.append(bias if isinstance(bias, Tensor) else
                    Tensor(jnp.asarray(bias)))
    out_vals = apply_op(pure, *args, op_name=name)
    shape = (int(b.shape[0]), *out_spatial, cout)
    return _wrap_output(out_vals, out_idx, shape)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, data_format="NDHWC", name=None):
    """Sparse 3-D convolution (reference: paddle.sparse.nn.functional
    .conv3d over phi sparse conv_kernel). weight: (kd, kh, kw, Cin,
    Cout)."""
    if dilation not in (1, (1, 1, 1)) or groups != 1:
        raise NotImplementedError("sparse conv3d: dilation/groups == 1")
    return _sparse_conv(x, weight, bias, stride, padding, False, 3,
                        "sparse_conv3d")


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """Submanifold sparse conv: output sparsity == input sparsity."""
    if dilation not in (1, (1, 1, 1)) or groups != 1:
        raise NotImplementedError("subm_conv3d: dilation/groups == 1")
    return _sparse_conv(x, weight, bias, stride, padding, True, 3,
                        "sparse_subm_conv3d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, data_format="NHWC", name=None):
    if dilation not in (1, (1, 1)) or groups != 1:
        raise NotImplementedError("sparse conv2d: dilation/groups == 1")
    return _sparse_conv(x, weight, bias, stride, padding, False, 2,
                        "sparse_conv2d")


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    if dilation not in (1, (1, 1)) or groups != 1:
        raise NotImplementedError("subm_conv2d: dilation/groups == 1")
    return _sparse_conv(x, weight, bias, stride, padding, True, 2,
                        "sparse_subm_conv2d")


def max_pool3d(x, kernel_size, stride=None, padding=0,
               data_format="NDHWC", name=None):
    """Sparse max pooling over stored voxels (reference: phi sparse
    pool_kernel MaxPool3d — empty sites contribute nothing)."""
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse max_pool3d expects a SparseCooTensor")
    dims = 3
    b = x._bcoo
    kernel = _tuple(kernel_size, dims)
    stride = _tuple(stride if stride is not None else kernel_size, dims)
    padding = _tuple(padding, dims)
    idx, inv = _coalesce_map(b)
    n_coal = len(idx)
    spatial = tuple(int(s) for s in b.shape[1:1 + dims])
    out_idx, out_spatial, rulebook = _build_rulebook(
        idx, spatial, kernel, stride, padding, False)
    n_out = len(out_idx)
    c = int(b.shape[-1])

    def pure(vals):
        vals = jnp.zeros((n_coal, vals.shape[1]),
                         vals.dtype).at[inv].add(vals)
        out = jnp.full((n_out, c), -jnp.inf, vals.dtype)
        for in_rows, out_rows in rulebook:
            if len(in_rows) == 0:
                continue
            out = out.at[out_rows].max(vals[in_rows])
        return out

    out_vals = apply_op(pure, _as_value_tensor(x), op_name="sparse_maxpool3d")
    shape = (int(b.shape[0]), *out_spatial, c)
    return _wrap_output(out_vals, out_idx, shape)


# ---------------------------------------------------------------------------
# Layer faces (paddle.sparse.nn.Conv3D etc.)
# ---------------------------------------------------------------------------

from ..nn.layer.layers import Layer  # noqa: E402


class _ConvBase(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dims=3, subm=False, bias_attr=None,
                 dilation=1, groups=1):
        super().__init__()
        if _tuple(dilation, dims) != (1,) * dims or groups != 1:
            # the functional forms enforce this; the Layer ctor must not
            # silently compute a dilation-1/group-1 convolution instead
            raise NotImplementedError(
                f"{type(self).__name__}: dilation/groups must be 1")
        self._dims = dims
        self._subm = subm
        self._stride = stride
        self._padding = padding
        k = _tuple(kernel_size, dims)
        self.weight = self.create_parameter(
            shape=[*k, in_channels, out_channels])
        self.bias = self.create_parameter(shape=[out_channels],
                                          attr=bias_attr, is_bias=True)

    def forward(self, x):
        return _sparse_conv(x, self.weight, self.bias, self._stride,
                            self._padding, self._subm, self._dims,
                            type(self).__name__)


class Conv3D(_ConvBase):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dims=3, subm=False, bias_attr=bias_attr,
                         dilation=dilation, groups=groups)


class SubmConv3D(_ConvBase):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dims=3, subm=True, bias_attr=bias_attr,
                         dilation=dilation, groups=groups)


class Conv2D(_ConvBase):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dims=2, subm=False, bias_attr=bias_attr,
                         dilation=dilation, groups=groups)


class SubmConv2D(_ConvBase):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dims=2, subm=True, bias_attr=bias_attr,
                         dilation=dilation, groups=groups)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC", name=None):
        super().__init__()
        self._k, self._s, self._p = kernel_size, stride, padding

    def forward(self, x):
        return max_pool3d(x, self._k, self._s, self._p)
