"""Sparse tensors.

Reference analog: python/paddle/sparse/ over phi SparseCooTensor/
SparseCsrTensor kernels (paddle/phi/core/sparse_coo_tensor.h,
kernels/sparse/ 14k LoC).

TPU-native: jax.experimental.sparse BCOO is the backing representation —
XLA lowers spmm/sddmm to gather/scatter + MXU dots. The dense form is
materialized ONLY when explicitly requested (``to_dense()``/``numpy()``
or dense-only Tensor methods): creation, unary ops, add/sub/mul,
matmul, masked_matmul (true SDDMM: gather + batched dot, never the full
product), transpose/reshape/coalesce/sum and the sparse softmax all stay
on the (values, indices) representation.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "SparseCsrTensor",
           "is_same_shape", "add", "subtract", "multiply", "divide",
           "matmul", "relu", "tanh", "sqrt", "sin", "abs", "pow", "neg",
           "cast", "transpose", "sum", "coalesce", "mask_as",
           "masked_matmul", "mv", "addmm", "reshape", "nn"]

# the member descriptor for Tensor's `_array` slot: SparseCooTensor
# shadows it with a lazy property so constructing/operating on sparse
# tensors never materializes the dense form until something asks for it
_ARRAY_SLOT = Tensor.__dict__["_array"]


class SparseCooTensor(Tensor):
    """Tensor face over a BCOO array; .indices()/.values()/to_dense().
    Dense materialization is lazy (first `_array` access) and cached."""

    def __init__(self, bcoo, stop_gradient=True):
        self._bcoo = bcoo
        super().__init__(None, stop_gradient=stop_gradient)

    @property
    def _array(self):
        val = _ARRAY_SLOT.__get__(self)
        if val is None:
            val = self._bcoo.todense()
            _ARRAY_SLOT.__set__(self, val)
        return val

    @_array.setter
    def _array(self, v):
        _ARRAY_SLOT.__set__(self, v)

    # metadata must not densify
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def ndim(self):
        return self._bcoo.ndim

    @property
    def rank(self):
        return self._bcoo.ndim

    @property
    def size(self):
        n = 1
        for s in self._bcoo.shape:
            n *= int(s)
        return n

    def __len__(self):
        return int(self._bcoo.shape[0])

    def __bool__(self):
        raise ValueError(
            "truth value of a sparse tensor is ambiguous; use to_dense()")

    @property
    def dtype(self):
        return self._bcoo.dtype

    def indices(self):
        return Tensor(jnp.swapaxes(self._bcoo.indices, 0, 1))

    def values(self):
        # sparse conv/pool outputs carry their autograd-taped values so
        # loss.backward() through .values() reaches the conv kernel;
        # the fallback must keep stop_gradient, or unary ops downstream
        # silently stop recording gradients
        vt = getattr(self, "_values_t", None)
        return vt if vt is not None else Tensor(
            self._bcoo.data, stop_gradient=self.stop_gradient)

    def to_dense(self):
        return Tensor(self._bcoo.todense(), stop_gradient=self.stop_gradient)

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    @property
    def nnz(self):
        return self._bcoo.nse


class SparseCsrTensor(SparseCooTensor):
    """CSR face (reference phi SparseCsrTensor): keeps crows/cols/values
    accessors; compute rides the same BCOO backing (COO<->CSR is a row
    expansion, free at trace time on TPU where both lower to gathers)."""

    def __init__(self, bcoo, crows, cols, vals, stop_gradient=True):
        super().__init__(bcoo, stop_gradient=stop_gradient)
        self._crows = crows
        self._cols = cols
        self._vals = vals

    def crows(self):
        return Tensor(self._crows)

    def cols(self):
        return Tensor(self._cols)

    def values(self):
        return Tensor(self._vals)

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    idx = indices._array if isinstance(indices, Tensor) \
        else jnp.asarray(np.asarray(indices))
    val = values._array if isinstance(values, Tensor) \
        else jnp.asarray(np.asarray(values))
    if dtype is not None:
        from ..core.dtype import convert_dtype
        val = val.astype(convert_dtype(dtype))
    idx_t = jnp.swapaxes(idx, 0, 1).astype(jnp.int32)  # BCOO wants [nse, ndim]
    if shape is None:
        shape = tuple(int(i) + 1 for i in jnp.max(idx, axis=1))
    bcoo = jsparse.BCOO((val, idx_t), shape=tuple(int(s) for s in shape))
    return SparseCooTensor(bcoo, stop_gradient=stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    crows_a = jnp.asarray(np.asarray(
        crows._array if isinstance(crows, Tensor) else crows))
    cols_a = jnp.asarray(np.asarray(
        cols._array if isinstance(cols, Tensor) else cols))
    vals_a = values._array if isinstance(values, Tensor) \
        else jnp.asarray(np.asarray(values))
    if dtype is not None:
        from ..core.dtype import convert_dtype
        vals_a = vals_a.astype(convert_dtype(dtype))
    rows = np.repeat(np.arange(len(crows_a) - 1),
                     np.diff(np.asarray(crows_a)))
    idx_t = jnp.stack([jnp.asarray(rows, jnp.int32),
                       cols_a.astype(jnp.int32)], axis=1)
    bcoo = jsparse.BCOO((vals_a, idx_t), shape=tuple(int(s) for s in shape))
    return SparseCsrTensor(bcoo, crows_a, cols_a, vals_a,
                           stop_gradient=stop_gradient)


def _sparse_unary(op_name, fn):
    def op(x, name=None):
        from ..core.tensor import apply_op
        if isinstance(x, SparseCooTensor):
            b = x._bcoo
            # route through apply_op on the (possibly tape-linked)
            # values so stacked sparse networks backprop through
            # activations to lower conv layers
            out_vals = apply_op(fn, x.values(),
                                op_name=f"sparse_{op_name}")
            out = jsparse.BCOO((out_vals._array, b.indices),
                               shape=b.shape)
            sp = SparseCooTensor(out, stop_gradient=out_vals.stop_gradient)
            sp._values_t = out_vals
            return sp
        return Tensor(fn(x._array))
    op.__name__ = op_name
    return op


relu = _sparse_unary("relu", lambda v: jnp.maximum(v, 0))
tanh = _sparse_unary("tanh", jnp.tanh)
sqrt = _sparse_unary("sqrt", jnp.sqrt)
sin = _sparse_unary("sin", jnp.sin)
abs = _sparse_unary("abs", jnp.abs)  # noqa: A001
neg = _sparse_unary("neg", jnp.negative)


def pow(x, factor, name=None):  # noqa: A001
    return _sparse_unary("pow", lambda v: jnp.power(v, factor))(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    from ..core.dtype import convert_dtype
    if isinstance(x, SparseCooTensor):
        b = x._bcoo
        data = b.data.astype(convert_dtype(value_dtype)) \
            if value_dtype else b.data
        return SparseCooTensor(jsparse.BCOO((data, b.indices),
                                            shape=b.shape))
    return Tensor(x._array.astype(convert_dtype(value_dtype)))


def _lincomb(x, y, negate_y):
    """x +/- y for sparse operands without densifying: concatenate the
    two index/value sets and merge duplicates (the phi sparse
    elementwise-add kernel's strategy)."""
    bx, by = x._bcoo, y._bcoo
    ydata = by.data.astype(bx.data.dtype)
    if negate_y:
        ydata = jnp.negative(ydata)  # dtype-preserving (ints stay ints)
    data = jnp.concatenate([bx.data, ydata])
    idx = jnp.concatenate([bx.indices, by.indices])
    out = jsparse.bcoo_sum_duplicates(
        jsparse.BCOO((data, idx), shape=bx.shape))
    return SparseCooTensor(out)


def add(x, y, name=None):
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        return _lincomb(x, y, False)
    return Tensor(x._array + y._array)


def subtract(x, y, name=None):
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        return _lincomb(x, y, True)
    return Tensor(x._array - y._array)


def multiply(x, y, name=None):
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        out = jsparse.bcoo_multiply_sparse(x._bcoo, y._bcoo)
        return SparseCooTensor(out)
    if isinstance(x, SparseCooTensor):
        # bcoo_multiply_dense returns the new DATA vector (length nse);
        # rebuild on x's pattern
        data = jsparse.bcoo_multiply_dense(x._bcoo, y._array)
        return SparseCooTensor(jsparse.BCOO(
            (data, x._bcoo.indices), shape=x._bcoo.shape))
    return Tensor(x._array * y._array)


def divide(x, y, name=None):
    if isinstance(x, SparseCooTensor) and not isinstance(
            y, SparseCooTensor):
        data = jsparse.bcoo_multiply_dense(x._bcoo, 1.0 / y._array)
        return SparseCooTensor(jsparse.BCOO(
            (data, x._bcoo.indices), shape=x._bcoo.shape))
    if isinstance(x, SparseCooTensor):
        # sparse/sparse divides stored values, defined only when both
        # operands share one sparsity pattern — verify, loudly
        bx = jsparse.bcoo_sum_duplicates(x._bcoo)
        by = jsparse.bcoo_sum_duplicates(y._bcoo)
        if bx.nse != by.nse or not bool(
                jnp.array_equal(bx.indices, by.indices)):
            raise NotImplementedError(
                "sparse/sparse divide requires identical sparsity "
                "patterns; densify one operand instead")
        return SparseCooTensor(jsparse.BCOO(
            (bx.data / by.data, bx.indices), shape=bx.shape))
    return Tensor(x._array / y._array)


def matmul(x, y, name=None):
    """spmm: sparse @ dense -> dense (XLA gather/scatter lowering)."""
    if isinstance(x, SparseCooTensor):
        out = x._bcoo @ (y._array if isinstance(y, Tensor) else y)
        return Tensor(out)
    return Tensor(jnp.matmul(x._array, y._array))


def transpose(x, perm, name=None):
    if isinstance(x, SparseCooTensor):
        bt = jsparse.bcoo_transpose(x._bcoo, permutation=tuple(perm))
        return SparseCooTensor(bt)
    return Tensor(jnp.transpose(x._array, perm))


def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    """Reduce over stored values — implicit zeros contribute nothing, so
    no densification (reference sparse sum kernel)."""
    if isinstance(x, SparseCooTensor):
        b = x._bcoo
        if axis is None:
            out = jnp.sum(b.data)
            return Tensor(out.reshape((1,) * b.ndim) if keepdim else out)
        axes = (axis,) if isinstance(axis, int) else tuple(axis)
        axes = tuple(a % b.ndim for a in axes)
        red = jsparse.bcoo_reduce_sum(b, axes=axes)
        t = SparseCooTensor(red, stop_gradient=x.stop_gradient)
        if keepdim:
            shp = [1 if i in axes else s for i, s in enumerate(b.shape)]
            return reshape(t, shp)
        return t
    return Tensor(jnp.sum(x._array, axis=axis, keepdims=keepdim))


def is_same_shape(x, y):
    return tuple(x.shape) == tuple(y.shape)


def coalesce(x, name=None):
    """Merge duplicate indices (reference: python/paddle/sparse/unary.py
    coalesce → phi sparse coalesce kernel)."""
    assert isinstance(x, SparseCooTensor)
    b = jsparse.bcoo_sum_duplicates(x._bcoo)
    return SparseCooTensor(b, stop_gradient=x.stop_gradient)


def mask_as(x, mask, name=None):
    """Keep only the entries of dense `x` at `mask`'s sparsity pattern
    (reference: python/paddle/sparse/unary.py mask_as / sparse_mask)."""
    assert isinstance(mask, SparseCooTensor)
    xd = x._array if isinstance(x, Tensor) else jnp.asarray(x)
    b = mask._bcoo
    idx = tuple(b.indices[:, d] for d in range(b.indices.shape[1]))
    vals = xd[idx]
    return SparseCooTensor(jsparse.BCOO((vals, b.indices), shape=b.shape),
                           stop_gradient=getattr(x, "stop_gradient", True))


def masked_matmul(x, y, mask, name=None):
    """(x @ y) sampled at mask's pattern — true SDDMM: gather the needed
    rows/cols and take per-nse dots; the dense product is never formed
    (reference: python/paddle/sparse/binary.py masked_matmul → phi
    sddmm/csr kernels)."""
    xd = x._array if isinstance(x, Tensor) else jnp.asarray(x)
    yd = y._array if isinstance(y, Tensor) else jnp.asarray(y)
    b = mask._bcoo
    assert b.ndim == 2 and xd.ndim == 2 and yd.ndim == 2
    i, j = b.indices[:, 0], b.indices[:, 1]
    vals = jnp.einsum("nk,nk->n", xd[i, :], yd[:, j].T)
    return SparseCooTensor(jsparse.BCOO((vals, b.indices), shape=b.shape))


def mv(x, vec, name=None):
    """Sparse matrix × dense vector
    (reference: python/paddle/sparse/binary.py mv)."""
    assert isinstance(x, SparseCooTensor)
    v = vec._array if isinstance(vec, Tensor) else jnp.asarray(vec)
    return Tensor(x._bcoo @ v)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):  # noqa: A002
    """beta*input + alpha*(x @ y) with sparse x
    (reference: python/paddle/sparse/binary.py addmm)."""
    inp = input._array if isinstance(input, Tensor) else jnp.asarray(input)
    prod = matmul(x, y)._array
    return Tensor(beta * inp + alpha * prod)


def reshape(x, shape, name=None):
    """reference: python/paddle/sparse/unary.py reshape."""
    if isinstance(x, SparseCooTensor):
        b = jsparse.bcoo_reshape(x._bcoo,
                                 new_sizes=tuple(int(s) for s in shape))
        return SparseCooTensor(b, stop_gradient=x.stop_gradient)
    return Tensor(jnp.reshape(x._array, shape))


class _SparseReLU:
    def __call__(self, x):
        return relu(x)


class _SparseSoftmax:
    """Softmax over the STORED entries of each row — segment-reduced on
    the values, no densification (reference:
    python/paddle/sparse/nn/layer/activation.py Softmax over the csr
    row-wise kernel)."""

    def __init__(self, axis=-1):
        self.axis = axis

    def __call__(self, x):
        if isinstance(x, SparseCooTensor):
            b = jsparse.bcoo_sum_duplicates(x._bcoo)
            if b.ndim != 2 or self.axis not in (-1, 1):
                raise NotImplementedError(
                    "sparse softmax: 2-D over the last axis")
            rows = b.indices[:, 0]
            R = b.shape[0]
            m = jax.ops.segment_max(b.data, rows, num_segments=R)
            e = jnp.exp(b.data - m[rows])
            s = jax.ops.segment_sum(e, rows, num_segments=R)
            vals = e / s[rows]
            return SparseCooTensor(
                jsparse.BCOO((vals, b.indices), shape=b.shape),
                stop_gradient=x.stop_gradient)
        return Tensor(jax.nn.softmax(x._array, axis=self.axis))


import types as _types  # noqa: E402

from . import conv as _conv  # noqa: E402

nn = _types.SimpleNamespace(
    ReLU=_SparseReLU, Softmax=_SparseSoftmax,
    Conv3D=_conv.Conv3D, SubmConv3D=_conv.SubmConv3D,
    Conv2D=_conv.Conv2D, SubmConv2D=_conv.SubmConv2D,
    MaxPool3D=_conv.MaxPool3D,
    functional=_types.SimpleNamespace(
        conv3d=_conv.conv3d, subm_conv3d=_conv.subm_conv3d,
        conv2d=_conv.conv2d, subm_conv2d=_conv.subm_conv2d,
        max_pool3d=_conv.max_pool3d, relu=relu))


# remaining reference unary surface (zero-preserving fns operate on the
# nonzero values only, exactly like the phi sparse kernels)
tan = _sparse_unary("tan", jnp.tan)
asin = _sparse_unary("asin", jnp.arcsin)
atan = _sparse_unary("atan", jnp.arctan)
sinh = _sparse_unary("sinh", jnp.sinh)
asinh = _sparse_unary("asinh", jnp.arcsinh)
atanh = _sparse_unary("atanh", jnp.arctanh)
square = _sparse_unary("square", jnp.square)
log1p = _sparse_unary("log1p", jnp.log1p)
deg2rad = _sparse_unary("deg2rad", jnp.deg2rad)
rad2deg = _sparse_unary("rad2deg", jnp.rad2deg)
expm1 = _sparse_unary("expm1", jnp.expm1)

__all__ += ["tan", "asin", "atan", "sinh", "asinh", "atanh", "square",
            "log1p", "deg2rad", "rad2deg", "expm1"]
