"""Sparse tensors.

Reference analog: python/paddle/sparse/ over phi SparseCooTensor/
SparseCsrTensor kernels (paddle/phi/core/sparse_coo_tensor.h,
kernels/sparse/ 14k LoC). TPU-native: jax.experimental.sparse BCOO is the
backing representation (XLA lowers scatter/gather-based spmm); dense
round-trips are exact. Covers the creation + conversion + elementwise +
matmul surface of the reference's paddle.sparse.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "is_same_shape", "add", "subtract", "multiply", "divide",
           "matmul", "relu", "tanh", "sqrt", "sin", "abs", "pow", "neg",
           "cast", "transpose", "sum", "coalesce", "mask_as",
           "masked_matmul", "mv", "addmm", "reshape", "nn"]


class SparseCooTensor(Tensor):
    """Tensor wrapper over a BCOO array; .indices()/.values()/to_dense()."""

    def __init__(self, bcoo, stop_gradient=True):
        self._bcoo = bcoo
        super().__init__(bcoo.todense(), stop_gradient=stop_gradient)

    def indices(self):
        return Tensor(jnp.swapaxes(self._bcoo.indices, 0, 1))

    def values(self):
        return Tensor(self._bcoo.data)

    def to_dense(self):
        return Tensor(self._bcoo.todense(), stop_gradient=self.stop_gradient)

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True

    @property
    def nnz(self):
        return self._bcoo.nse


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    idx = indices._array if isinstance(indices, Tensor) \
        else jnp.asarray(np.asarray(indices))
    val = values._array if isinstance(values, Tensor) \
        else jnp.asarray(np.asarray(values))
    if dtype is not None:
        from ..core.dtype import convert_dtype
        val = val.astype(convert_dtype(dtype))
    idx_t = jnp.swapaxes(idx, 0, 1).astype(jnp.int32)  # BCOO wants [nse, ndim]
    if shape is None:
        shape = tuple(int(i) + 1 for i in jnp.max(idx, axis=1))
    bcoo = jsparse.BCOO((val, idx_t), shape=tuple(int(s) for s in shape))
    return SparseCooTensor(bcoo, stop_gradient=stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    # represent CSR via COO (BCOO backing); row expansion on host
    crows_np = np.asarray(crows._array if isinstance(crows, Tensor)
                          else crows)
    cols_np = np.asarray(cols._array if isinstance(cols, Tensor) else cols)
    rows = np.repeat(np.arange(len(crows_np) - 1), np.diff(crows_np))
    indices = np.stack([rows, cols_np])
    return sparse_coo_tensor(indices, values, shape, dtype, place,
                             stop_gradient)


def _sparse_unary(name, fn):
    def op(x, name=None):
        if isinstance(x, SparseCooTensor):
            b = x._bcoo
            out = jsparse.BCOO((fn(b.data), b.indices), shape=b.shape)
            return SparseCooTensor(out, stop_gradient=x.stop_gradient)
        return Tensor(fn(x._array))
    op.__name__ = name
    return op


relu = _sparse_unary("relu", lambda v: jnp.maximum(v, 0))
tanh = _sparse_unary("tanh", jnp.tanh)
sqrt = _sparse_unary("sqrt", jnp.sqrt)
sin = _sparse_unary("sin", jnp.sin)
abs = _sparse_unary("abs", jnp.abs)  # noqa: A001
neg = _sparse_unary("neg", jnp.negative)


def pow(x, factor, name=None):  # noqa: A001
    return _sparse_unary("pow", lambda v: jnp.power(v, factor))(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    from ..core.dtype import convert_dtype
    if isinstance(x, SparseCooTensor):
        b = x._bcoo
        data = b.data.astype(convert_dtype(value_dtype)) \
            if value_dtype else b.data
        return SparseCooTensor(jsparse.BCOO((data, b.indices),
                                            shape=b.shape))
    return Tensor(x._array.astype(convert_dtype(value_dtype)))


def _binop(name, fn):
    def op(x, y, name=None):
        xd = x.to_dense()._array if isinstance(x, SparseCooTensor) \
            else x._array
        yd = y.to_dense()._array if isinstance(y, SparseCooTensor) \
            else y._array
        dense = fn(xd, yd)
        idx = jnp.stack(jnp.nonzero(dense, size=None))
        return Tensor(dense)
    op.__name__ = name
    return op


add = _binop("add", jnp.add)
subtract = _binop("subtract", jnp.subtract)
multiply = _binop("multiply", jnp.multiply)
divide = _binop("divide", jnp.true_divide)


def matmul(x, y, name=None):
    if isinstance(x, SparseCooTensor):
        out = x._bcoo @ (y._array if isinstance(y, Tensor) else y)
        return Tensor(out)
    return Tensor(jnp.matmul(x._array, y._array))


def transpose(x, perm, name=None):
    if isinstance(x, SparseCooTensor):
        bt = jsparse.bcoo_transpose(x._bcoo, permutation=tuple(perm))
        return SparseCooTensor(bt)
    return Tensor(jnp.transpose(x._array, perm))


def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    xd = x.to_dense()._array if isinstance(x, SparseCooTensor) else x._array
    return Tensor(jnp.sum(xd, axis=axis, keepdims=keepdim))


def is_same_shape(x, y):
    return tuple(x.shape) == tuple(y.shape)


def coalesce(x, name=None):
    """Merge duplicate indices (reference: python/paddle/sparse/unary.py
    coalesce → phi sparse coalesce kernel)."""
    assert isinstance(x, SparseCooTensor)
    b = jsparse.bcoo_sum_duplicates(x._bcoo)
    return SparseCooTensor(b, stop_gradient=x.stop_gradient)


def mask_as(x, mask, name=None):
    """Keep only the entries of dense `x` at `mask`'s sparsity pattern
    (reference: python/paddle/sparse/unary.py mask_as /
    sparse_mask)."""
    assert isinstance(mask, SparseCooTensor)
    xd = x._array if isinstance(x, Tensor) else jnp.asarray(x)
    b = mask._bcoo
    idx = tuple(b.indices[:, d] for d in range(b.indices.shape[1]))
    vals = xd[idx]
    return SparseCooTensor(jsparse.BCOO((vals, b.indices), shape=b.shape),
                           stop_gradient=getattr(x, "stop_gradient", True))


def masked_matmul(x, y, mask, name=None):
    """(x @ y) sampled at mask's pattern — SDDMM
    (reference: python/paddle/sparse/binary.py masked_matmul)."""
    xd = x._array if isinstance(x, Tensor) else jnp.asarray(x)
    yd = y._array if isinstance(y, Tensor) else jnp.asarray(y)
    return mask_as(Tensor(jnp.matmul(xd, yd)), mask)


def mv(x, vec, name=None):
    """Sparse matrix × dense vector
    (reference: python/paddle/sparse/binary.py mv)."""
    assert isinstance(x, SparseCooTensor)
    v = vec._array if isinstance(vec, Tensor) else jnp.asarray(vec)
    return Tensor(x._bcoo @ v)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):  # noqa: A002
    """beta*input + alpha*(x @ y) with sparse x
    (reference: python/paddle/sparse/binary.py addmm)."""
    inp = input.to_dense()._array if isinstance(input, SparseCooTensor) \
        else (input._array if isinstance(input, Tensor)
              else jnp.asarray(input))
    prod = matmul(x, y)._array
    return Tensor(beta * inp + alpha * prod)


def reshape(x, shape, name=None):
    """reference: python/paddle/sparse/unary.py reshape."""
    if isinstance(x, SparseCooTensor):
        b = jsparse.bcoo_reshape(x._bcoo,
                                 new_sizes=tuple(int(s) for s in shape))
        return SparseCooTensor(b, stop_gradient=x.stop_gradient)
    return Tensor(jnp.reshape(x._array, shape))


class _SparseReLU:
    def __call__(self, x):
        return relu(x)


class _SparseSoftmax:
    """Softmax over the STORED entries of each row (the sparsity pattern
    comes from the indices, so explicitly-stored zeros participate —
    reference: python/paddle/sparse/nn/layer/activation.py Softmax)."""

    def __init__(self, axis=-1):
        self.axis = axis

    def __call__(self, x):
        import jax
        if isinstance(x, SparseCooTensor):
            b = jsparse.bcoo_sum_duplicates(x._bcoo)
            pattern = jnp.zeros(b.shape, bool).at[
                tuple(b.indices[:, d] for d in range(b.indices.shape[1]))
            ].set(True)
            d = b.todense()
            neg_inf = jnp.where(pattern, d, -jnp.inf)
            sm = jax.nn.softmax(neg_inf, axis=self.axis)
            vals = sm[tuple(b.indices[:, d2]
                            for d2 in range(b.indices.shape[1]))]
            return SparseCooTensor(
                jsparse.BCOO((vals, b.indices), shape=b.shape),
                stop_gradient=x.stop_gradient)
        import jax.nn
        return Tensor(jax.nn.softmax(x._array, axis=self.axis))


import types as _types  # noqa: E402

nn = _types.SimpleNamespace(ReLU=_SparseReLU, Softmax=_SparseSoftmax)
