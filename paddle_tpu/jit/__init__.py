"""JIT: graph capture and AOT export.

Reference analog: python/paddle/jit/ — @to_static (api.py:222) AST-rewrites
python control flow into ProgramDesc ops and caches ConcreteProgram per
InputSpec (program_translator.py:283/:1225); jit.save emits .pdmodel.

TPU-native: `to_static` IS `jax.jit` over the Tensor facade — tracing the
eager tape through XLA replaces the AST transformer + ProgramDesc +
InterpreterCore stack (SURVEY.md §3.3/§3.5). The per-input-spec cache
maps onto jax's compilation cache keyed by abstract shapes/dtypes.
`jit.save` exports StableHLO via jax.export plus a state_dict payload;
`jit.load` restores a callable.
"""
from .api import (to_static, not_to_static, ignore_module, TracedLayer,
                  TranslatedLayer, save, load, InputSpec,
                  enable_to_static, set_verbosity, set_code_level)

__all__ = ["to_static", "not_to_static", "ignore_module", "save", "load",
           "InputSpec", "TracedLayer", "TranslatedLayer",
           "enable_to_static", "set_verbosity", "set_code_level"]
