"""to_static / save / load implementation."""
from __future__ import annotations

import functools
import os
import pickle
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, _as_array
from ..core import dtype as dtype_mod


class InputSpec:
    """reference: python/paddle/static/input.py InputSpec."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = dtype_mod.convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype.name})"


def _tree_to_arrays(tree):
    return jax.tree_util.tree_map(
        lambda x: x._array if isinstance(x, Tensor) else x, tree,
        is_leaf=lambda x: isinstance(x, Tensor))


def _tree_to_tensors(tree):
    return jax.tree_util.tree_map(
        lambda x: Tensor(x) if isinstance(x, jax.Array) else x, tree)


class StaticFunction:
    """Traced-and-compiled callable with per-signature cache.

    The eager tape runs under jax tracing, so arbitrary Layer forward code
    (including loss.backward() + optimizer.step() on the facade) compiles
    into a single XLA program. Mutated state (parameters, buffers, RNG) must
    be functionalized by the caller or via the `mutates` hook used by
    hapi.Model.
    """

    def __init__(self, fn, input_spec=None, build_strategy=None,
                 backend=None, donate_argnums=(), lint=False):
        self._fn = fn
        self._input_spec = input_spec
        # per-function opt-in to the trace-time jaxpr lint (the global
        # switch is FLAGS_tpu_lint); checked only on new trace
        # signatures, so steady-state calls never see it
        self._lint = bool(lint)
        functools.update_wrapper(self, fn)
        if not getattr(fn, "_not_to_static", False):
            # dy2static AST pass: python if/while on tensor predicates
            # become lax.cond/while_loop via runtime-dispatch helpers
            # (reference program_translator.py:1225). convert_to_static
            # returns fn unchanged on its documented fallback cases; an
            # actual exception is a converter bug — surface it as a
            # warning and keep the unconverted function
            try:
                from .dy2static import convert_to_static
                fn = convert_to_static(fn)
            except Exception as e:  # pragma: no cover - converter bug
                import warnings
                warnings.warn(
                    f"dy2static conversion failed for "
                    f"{getattr(fn, '__qualname__', fn)}: {e!r}; "
                    "falling back to plain tracing")

        self._converted_fn = fn
        self._donate_argnums = donate_argnums
        # LRU-bounded: keyed by static-leaf VALUES, so a per-call python
        # scalar (step counter, temperature) would otherwise retain a
        # compiled closure per distinct value forever
        from collections import OrderedDict
        self._jit_cache: "OrderedDict[Any, Any]" = OrderedDict()
        self._jit_cache_cap = int(os.environ.get(
            "PADDLE_TPU_JIT_CACHE_SIZE", "128"))
        self._jit_cache_warned = False
        # AOT executables per exact call signature, filled only while
        # xmem capture is on: the signature's single compile happens via
        # jit_fn.lower().compile() so memory/cost analysis is free, and
        # subsequent same-signature calls dispatch straight to the
        # Compiled (skipping even pjit's python re-dispatch)
        self._aot_cache: "OrderedDict[Any, Any]" = OrderedDict()
        # compile/retrace observability: one entry per call signature
        # ever seen — (static key, dynamic shapes/dtypes). A second call
        # with a new signature is a tracing-cache miss (retrace), the
        # silent TPU perf killer the profiler's Compilation section and
        # jit_retraces_total metric surface.
        self._trace_sigs: set = set()
        self._trace_name = getattr(fn, "__qualname__",
                                   getattr(fn, "__name__", repr(fn)))

        def array_fn(*arrays, **kw):
            tensors = _tree_to_tensors(arrays)
            out = fn(*tensors, **kw)
            return _tree_to_arrays(out)
        # kept for concrete_program/back-compat; __call__ uses the
        # static-partitioned cache below
        self._jitted = jax.jit(array_fn, donate_argnums=donate_argnums)

    @staticmethod
    def _is_dynamic_leaf(x):
        return isinstance(x, (Tensor, jax.Array, np.ndarray))

    def __call__(self, *args, **kwargs):
        """Trace tensor/array leaves; keep every other leaf static.

        With the translator disabled (jit.enable_to_static(False) —
        the reference's ProgramTranslator().enable(False)), the
        ORIGINAL python function runs eagerly: the debugging escape
        hatch for stepping through un-traced code.

        Reference semantics: dy2static traces *tensors* into the
        program — python scalars/bools/containers are build-time values
        (a `for i in range(n)` with python n unrolls; a python bool
        branches in python). Tracing them (what a bare jax.jit of all
        args would do) both diverges from that contract and breaks
        branches whose arms differ in shape per mode. Implementation:
        partition the (args, kwargs) pytree, jit a closure over the
        static leaves, cache per (treedef, static leaves).
        """
        if not _TO_STATIC_ENABLED[0]:
            return self._fn(*args, **kwargs)
        is_tensor_leaf = lambda x: isinstance(x, Tensor)  # noqa: E731
        flat, treedef = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=is_tensor_leaf)
        dyn_set = {i for i, leaf in enumerate(flat)
                   if self._is_dynamic_leaf(leaf)}
        dyn_idx = tuple(sorted(dyn_set))
        # type(leaf) in the key: True/1/1.0 compare equal but must not
        # share a baked closure
        static_leaves = tuple((i, type(leaf), leaf)
                              for i, leaf in enumerate(flat)
                              if i not in dyn_set)
        try:
            key = (treedef, dyn_idx,
                   tuple((i, t) for i, t, _ in static_leaves),
                   tuple(leaf for _, _, leaf in static_leaves))
            hash(key)
        except TypeError:
            # unhashable static leaf: no caching, direct trace each call
            key = None
        jitted = self._jit_cache.get(key) if key is not None else None
        new_closure = jitted is None
        if jitted is not None:
            self._jit_cache.move_to_end(key)

        # donate_argnums name TOP-LEVEL positional args; remap them
        # to the positions of those args' dynamic leaves in the
        # compacted call signature (also fed to the lint hook below,
        # which can fire on a cached closure seeing a new shape sig)
        donate = ()
        if self._donate_argnums:
            spans = []
            pos = 0
            for a in args:
                n = len(jax.tree_util.tree_flatten(
                    a, is_leaf=is_tensor_leaf)[0])
                spans.append(range(pos, pos + n))
                pos += n
            donated_flat = {i for j in self._donate_argnums
                            if j < len(spans) for i in spans[j]}
            donate = tuple(k for k, i in enumerate(dyn_idx)
                           if i in donated_flat)

        if jitted is None:
            fn = self._converted_fn
            n_leaves = len(flat)

            def call_with_static(*dyn_arrays):
                # only sizes/static values are captured — never the
                # caller's Tensors (they would pin device buffers in
                # this cache entry for the StaticFunction's lifetime)
                full = [None] * n_leaves
                for i, _t, st in static_leaves:
                    full[i] = st
                for i, a in zip(dyn_idx, dyn_arrays):
                    full[i] = Tensor(a)
                a2, k2 = jax.tree_util.tree_unflatten(treedef, full)
                return _tree_to_arrays(fn(*a2, **k2))

            jitted = jax.jit(call_with_static, donate_argnums=donate)
            if key is not None:
                self._jit_cache[key] = jitted
                if len(self._jit_cache) > self._jit_cache_cap:
                    self._jit_cache.popitem(last=False)
                    if not self._jit_cache_warned:
                        self._jit_cache_warned = True
                        import warnings
                        warnings.warn(
                            f"to_static cache for "
                            f"{getattr(self._fn, '__qualname__', self._fn)}"
                            f" exceeded {self._jit_cache_cap} entries and "
                            "is evicting (LRU). A python scalar arg that "
                            "changes every call recompiles every call — "
                            "pass it as a Tensor, or raise "
                            "PADDLE_TPU_JIT_CACHE_SIZE.")
        dyn_arrays = [_as_array(flat[i]) for i in dyn_idx]
        # retrace accounting: a fresh jit closure traces on its first
        # call; an existing closure re-traces when the dynamic leaves'
        # shapes/dtypes change. Both are tracing-cache misses.
        shape_sig = tuple((getattr(a, "shape", ()),
                           str(getattr(a, "dtype", "?")))
                          for a in dyn_arrays)
        sig = (key, shape_sig)
        new_sig = new_closure or sig not in self._trace_sigs
        if new_sig:
            if len(self._trace_sigs) < 4096:
                self._trace_sigs.add(sig)
            from ..profiler import compile_tracker
            compile_tracker.record_trace(self._trace_name)
            # hang injection + phase watchdog for the trace+compile that
            # this new signature is about to pay (chaos no-op unless a
            # schedule is installed; phase no-op unless
            # FLAGS_tpu_watchdog)
            from ..testing.chaos import chaos_point
            chaos_point("jit.compile")
            # trace-time static analysis (to_static(lint=True) or
            # FLAGS_tpu_lint): lint the jaxpr of every NEW signature —
            # host callbacks in loops, f64 promotion, oversized consts,
            # donation/collective/SPMD hazards — and verify every
            # pl.pallas_call the trace reaches (Level-3 kernel checks),
            # without executing anything. lint_traced never raises into
            # the traced call.
            from ..analysis import core as _lint_core
            if self._lint or _lint_core.enabled():
                from ..analysis import jaxpr_checks as _jaxpr_checks
                _jaxpr_checks.lint_traced(jitted, dyn_arrays,
                                          name=self._trace_name,
                                          donate_argnums=donate)
        # xmem capture: compile new signatures ahead-of-time so the ONE
        # compile also yields memory_analysis/cost_analysis; an
        # unhashable static leaf (key None) never caches, so it keeps
        # the plain traced path
        compiled = self._aot_cache.get(sig) if key is not None else None
        from contextlib import nullcontext
        from ..runtime import watchdog as _watchdog
        with (_watchdog.phase("compile") if new_sig else nullcontext()):
            if compiled is None and key is not None:
                from ..profiler import xmem
                if xmem.enabled():
                    compiled = xmem.aot_compile(
                        "to_static", self._trace_name, jitted, dyn_arrays,
                        sig=shape_sig)
                    if compiled is not None:
                        self._aot_cache[sig] = compiled
                        if len(self._aot_cache) > self._jit_cache_cap:
                            self._aot_cache.popitem(last=False)
            if compiled is not None:
                self._aot_cache.move_to_end(sig)
                try:
                    out = compiled(*dyn_arrays)
                except Exception:
                    # AOT executables pin device placement/sharding,
                    # which the shape signature doesn't key on — drop
                    # the entry and let pjit handle the call
                    self._aot_cache.pop(sig, None)
                    out = jitted(*dyn_arrays)
            else:
                out = jitted(*dyn_arrays)
        # numerics watchdog (FLAGS_tpu_check_nan_inf): every to_static
        # function is a watched function. Disabled path: dict lookup.
        from ..profiler import numerics as _numerics
        if _numerics.enabled():
            self._check_numerics_out(out, args, kwargs)
        return _tree_to_tensors(out)

    def _check_numerics_out(self, out, args, kwargs):
        """Scan the call's concrete outputs for NaN/Inf; on a finding,
        re-interpret the function's jaxpr on the SAME inputs
        (numerics.localize) so the error names the first bad primitive
        and its file:line — "loss went NaN" becomes "rsqrt in layer_norm
        at llama.py:212". Fires the tensor-checker action (default
        warn; raise/collect via amp.debugging.TensorCheckerConfig)."""
        from ..profiler import numerics as _numerics
        site = f"to_static:{self._trace_name}"
        summary = _numerics._tree_summary(out)
        _numerics.record_site(site, summary is not None, summary)
        if summary is None:
            return
        from ..amp.debugging import _default_action
        report = None
        try:
            report = _numerics.localize(self._converted_fn,
                                        *args, **kwargs)
        except (TypeError, ValueError, RuntimeError, KeyError,
                AttributeError) as e:
            # localization re-interprets the jaxpr and can fail on
            # shapes/tracers the original call handled — the finding
            # itself must still be dispatched, just without a culprit
            import logging
            logging.getLogger(__name__).debug(
                "numerics localization failed at %s: %s", site, e)
        _numerics._dispatch(site, summary, _default_action(),
                            report=report)

    @property
    def concrete_program(self):
        return self._jitted

    def rollback(self):
        return self._fn


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, lint=False, **kwargs):
    """@paddle.jit.to_static parity (reference: jit/api.py:222).

    ``lint=True`` runs the paddle_tpu.analysis jaxpr checks on every new
    trace signature of this function (see docs/static_analysis.md);
    ``FLAGS_tpu_lint`` enables the same checks globally."""

    def decorate(fn_or_layer):
        from ..nn.layer.layers import Layer
        if isinstance(fn_or_layer, Layer):
            layer = fn_or_layer
            layer.forward = StaticFunction(layer.forward, input_spec,
                                           lint=lint)
            return layer
        return StaticFunction(fn_or_layer, input_spec, lint=lint)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    pass


class TracedLayer:
    """Legacy dygraph-trace API (reference: fluid/dygraph/jit.py)."""

    def __init__(self, fn):
        self._fn = fn

    @staticmethod
    def trace(layer, inputs):
        sf = StaticFunction(layer.forward)
        outs = layer(*inputs)
        return outs, TracedLayer(sf)

    def __call__(self, inputs):
        return self._fn(*inputs)


# ---------------------------------------------------------------------------
# jit.save / jit.load — AOT export via StableHLO + weights payload
# ---------------------------------------------------------------------------

def save(layer, path, input_spec=None, **configs):
    """Serialize a Layer (or StaticFunction) for serving.

    Produces:
      path + '.pdiparams'  — pickled state_dict (numpy payloads)
      path + '.pdmodel'    — StableHLO module text from jax.export (the
                             ProgramDesc analog; reference jit/api.py:773)
      path + '.meta'       — input specs + structure info
    """
    from ..nn.layer.layers import Layer
    from ..framework.io import save as fsave

    if isinstance(layer, Layer):
        forward = layer.forward
        state = layer.state_dict()
        layer.eval()

        params = {k: v._array for k, v in state.items()}

        if input_spec is None:
            raise ValueError("jit.save requires input_spec for AOT export")

        specs = [s if isinstance(s, InputSpec) else InputSpec(**s)
                 for s in input_spec]
        abstract = [jax.ShapeDtypeStruct(
            [1 if d in (-1, None) else d for d in s.shape], s.dtype)
            for s in specs]

        def pure_forward(params_in, *xs):
            sd = layer.state_dict()
            saved = {k: v._array for k, v in sd.items()}
            try:
                for k, arr in params_in.items():
                    sd[k]._set_array(arr)
                out = layer(*[Tensor(x) for x in xs])
                return _tree_to_arrays(out)
            finally:
                for k, arr in saved.items():
                    sd[k]._set_array(arr)

        from jax import export as jexport
        exported = jexport.export(jax.jit(pure_forward))(
            {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
             for k, v in params.items()}, *abstract)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path + ".pdmodel", "wb") as f:
            f.write(exported.serialize())
        fsave({k: Tensor(v) for k, v in params.items()},
              path + ".pdiparams")
        with open(path + ".meta", "wb") as f:
            pickle.dump({"input_specs": [(s.shape, s.dtype.name)
                                         for s in specs]}, f)
    elif callable(layer):
        # plain functions / StaticFunctions save too (reference:
        # jit.save(function, path, input_spec) — api.py:773 handles both)
        fn = getattr(layer, "_function", None) or \
            getattr(layer, "__wrapped__", None) or layer
        if input_spec is None:
            raise ValueError("jit.save requires input_spec for AOT export")
        specs = [s if isinstance(s, InputSpec) else InputSpec(**s)
                 for s in input_spec]
        abstract = [jax.ShapeDtypeStruct(
            [1 if d in (-1, None) else d for d in s.shape], s.dtype)
            for s in specs]

        def pure_forward(params_in, *xs):
            del params_in  # functions carry no parameters
            out = fn(*[Tensor(x) for x in xs])
            return _tree_to_arrays(out)

        from jax import export as jexport
        exported = jexport.export(jax.jit(pure_forward))({}, *abstract)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path + ".pdmodel", "wb") as f:
            f.write(exported.serialize())
        fsave({}, path + ".pdiparams")
        with open(path + ".meta", "wb") as f:
            pickle.dump({"input_specs": [(s.shape, s.dtype.name)
                                         for s in specs]}, f)
    else:
        raise TypeError("jit.save expects a Layer or callable")


class TranslatedLayer:
    """Loaded serving artifact (reference: jit/translated_layer.py)."""

    def __init__(self, exported, params):
        self._exported = exported
        self._params = params

    def __call__(self, *args):
        arrays = [_as_array(a) for a in args]
        out = self._exported.call(self._params, *arrays)
        return _tree_to_tensors(out)

    forward = __call__

    def eval(self):
        return self

    def state_dict(self):
        return {k: Tensor(v) for k, v in self._params.items()}


def load(path, **configs):
    from jax import export as jexport
    from ..framework.io import load as fload
    with open(path + ".pdmodel", "rb") as f:
        exported = jexport.deserialize(f.read())
    params_t = fload(path + ".pdiparams")
    params = {k: v._array for k, v in params_t.items()}
    return TranslatedLayer(exported, params)


# -- translator global switches (reference: jit/api.py enable_to_static,
# jit/dy2static/logging_utils set_verbosity/set_code_level) -----------------

_TO_STATIC_ENABLED = [True]


def enable_to_static(enable_to_static_bool=True):
    """Globally toggle @to_static: when False every StaticFunction runs
    its ORIGINAL python body eagerly (the step-through-debugging mode of
    the reference's ProgramTranslator().enable)."""
    _TO_STATIC_ENABLED[0] = bool(enable_to_static_bool)


def set_verbosity(level=0, also_to_stdout=False):
    """dy2static transform logging verbosity."""
    from . import dy2static
    dy2static._VERBOSITY[0] = int(level)


def set_code_level(level=100, also_to_stdout=False):
    """Print the dy2static-rewritten source of converted functions
    (reference: set_code_level)."""
    from . import dy2static
    dy2static._CODE_LEVEL[0] = int(level)
