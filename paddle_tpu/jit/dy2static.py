"""dy2static: AST conversion of python control flow for to_static.

Reference analog: python/paddle/jit/dy2static/program_translator.py:1225
(StaticFunction → FunctionSpec → convert_to_static: a ~10k-LoC AST
pipeline whose core transforms are convert_ifelse and
convert_while_loop in convert_operators.py, rewriting python `if`/
`while` into conditional_block/while ops with get_args/set_args
variable plumbing).

TPU-native version: the same source-to-source rewrite, targeting the
lax-backed ops in static.control_flow. Each `if`/`while` statement
becomes a call to a runtime helper that dispatches on the predicate at
trace time — a concrete predicate runs plain python (zero overhead,
eager semantics preserved), a traced Tensor/array predicate lowers to
lax.cond / lax.while_loop. Variables assigned inside a branch are
threaded as explicit inputs/outputs of generated closures (the
get_args/set_args analog); names that may be unbound before the branch
are seeded with an UNDEFINED sentinel the helpers refuse to return from
a taken traced branch.

Conversion contract (documented subset, mirrors the reference's
supported patterns):
- `if`/`elif`/`else` and `while` with tensor or python predicates;
- `for` over range(...) (desugared to while; other iterables unroll);
- `break`/`continue` in converted loops (lowered to carried flags with
  guarded tails — the reference break_continue_transformer strategy);
- branch/loop bodies that assign plain names (tuple targets ok);
- early `return` inside `if` chains (the reference return_transformer):
  returns lower to a single return-value name with the trailing
  statements duplicated into the non-returning paths, so a
  tensor-predicated `if ...: return a` threads through lax.cond; every
  path must then return values of one pytree structure. `return` inside
  a converted LOOP stays unsupported (the loop is left as python);
- `for` over tensors / enumerate / zip keeps python semantics and
  unrolls at trace time (Tensor.__iter__ yields rows — the reference's
  for-over-tensor contract on static shapes);
- python list `append`/`extend` in loops works while the loop unrolls
  (concrete bounds); a loop that goes traced while mutating a python
  container raises a clear error naming the container and the
  create_array/array_write alternative (list_transformer's TensorArray
  role);
- `yield` is not supported in converted blocks — functions containing
  it keep python semantics;
- unsupported shapes of code (no retrievable source, lambdas, already-
  transformed callables) fall back to plain tracing, like the
  reference's ast fallback path.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Any, Callable, Dict, Optional

__all__ = ["convert_to_static", "convert_ifelse", "convert_while_loop",
           "UNDEFINED"]


class _Undefined:
    def __repr__(self):
        return "<dy2static UNDEFINED>"


UNDEFINED = _Undefined()


def _is_traced(x) -> bool:
    from jax.core import Tracer
    arr = getattr(x, "_array", x)
    return isinstance(arr, Tracer)


# set_verbosity / set_code_level knobs (jit.api wraps these)
_VERBOSITY = [0]
_CODE_LEVEL = [0]

_ONE_SIDED_MSG = (
    "dy2static: a variable assigned in only one branch of a "
    "tensor-predicated `if` stayed undefined in the other; assign it "
    "before the `if` or in both branches")


def convert_ifelse(pred, true_fn, false_fn, vals):
    """Runtime dispatch for a rewritten `if` (convert_operators.py
    convert_ifelse analog). vals: tuple of the variables either branch
    may assign; both branches return the updated tuple. UNDEFINED leaves
    coming OUT of a taken concrete branch are handled by the generated
    `del` cleanup (restoring python's unbound-name semantics); a traced
    branch returning UNDEFINED raises the clear message during tracing,
    before jax's opaque leaf-type error could."""
    if not _is_traced(pred):
        return true_fn(*vals) if bool(
            getattr(pred, "_array", pred)) else false_fn(*vals)
    from ..static.control_flow import cond

    def checked(fn):
        def g():
            out = fn(*vals)
            if any(v is UNDEFINED for v in out):
                raise ValueError(_ONE_SIDED_MSG)
            return out
        return g

    return cond(pred, checked(true_fn), checked(false_fn))


def convert_not_any(a, b):
    """``not (a or b)`` without python short-circuiting — the operands
    may be traced break/continue flags, where ``or`` would call bool()
    on a tracer."""
    if _is_traced(a) or _is_traced(b):
        import jax.numpy as jnp
        return jnp.logical_not(jnp.logical_or(
            getattr(a, "_array", a), getattr(b, "_array", b)))
    return not (bool(getattr(a, "_array", a))
                or bool(getattr(b, "_array", b)))


def convert_and_not(cond, flag):
    """``cond and not flag`` for loop tests, traced-aware."""
    if _is_traced(cond) or _is_traced(flag):
        import jax.numpy as jnp
        return jnp.logical_and(
            getattr(cond, "_array", cond),
            jnp.logical_not(getattr(flag, "_array", flag)))
    return bool(getattr(cond, "_array", cond)) and \
        not bool(getattr(flag, "_array", flag))


def convert_assert(test, msg=None):
    """Runtime dispatch for a rewritten ``assert`` (reference:
    dy2static convert_assert -> the Assert op). Concrete predicates
    keep python semantics; a TRACED predicate becomes a host callback
    that raises at RUN time (surfaced as JaxRuntimeError carrying the
    assertion message) — instead of the bare TracerBoolConversionError
    a python assert would die with at trace time."""
    import numpy as np

    v = getattr(test, "_array", test)
    if _is_traced(test):
        import jax

        def _check(ok):
            if not np.all(np.asarray(ok)):
                m = msg() if callable(msg) else msg
                raise AssertionError(
                    m if m is not None else "Assert failed on a "
                    "traced predicate inside a to_static function")
        jax.debug.callback(_check, v)
        return
    # concrete: PYTHON truthiness ('assert items' on a non-empty list
    # must pass); np.all only for array-valued predicates, whose bool()
    # would be ambiguous
    if isinstance(v, np.ndarray) or hasattr(v, "ndim"):
        ok = bool(np.all(np.asarray(v)))
    else:
        ok = bool(test)
    if not ok:
        m = msg() if callable(msg) else msg
        raise AssertionError(m) if m is not None else AssertionError()


def convert_flag_off(flag):
    """1 when the flag is unset, 0 when set (traced-aware) — multiplies
    the for-loop index bump so `break` preserves the loop variable
    (python leaves it at the breaking iteration) while `continue` still
    advances it."""
    if _is_traced(flag):
        import jax.numpy as jnp
        return jnp.where(getattr(flag, "_array", flag), 0, 1)
    return 0 if bool(getattr(flag, "_array", flag)) else 1


def convert_while_loop(cond_fn, body_fn, vals, mutates=()):
    """Runtime dispatch for a rewritten `while`. The probe can turn
    traced MID-loop (a concrete range bound with a tensor-predicated
    break: the first iterations run eagerly until the lax.cond makes the
    flag a tracer) — re-dispatch to the traced path with the current
    carry when that happens.

    mutates: names of python containers the body mutates in place
    (lst.append(...)): legal while the loop unrolls eagerly, impossible
    once it lowers to lax.while_loop (one trace of the body would run
    the mutation once, silently losing every later iteration's element)
    — raise the clear error the reference solves with TensorArray."""
    probe = cond_fn(*vals)
    while not _is_traced(probe):
        if not bool(getattr(probe, "_array", probe)):
            return vals
        vals = body_fn(*vals)
        probe = cond_fn(*vals)
    if mutates:
        raise ValueError(
            "dy2static: a tensor-predicated while mutates python "
            f"container(s) {list(mutates)}; list operations cannot be "
            "carried through lax.while_loop — preallocate with "
            "paddle.tensor.create_array/array_write (concrete size), "
            "use a stacked tensor carry, or keep the loop bound "
            "concrete so the loop unrolls")
    if any(v is UNDEFINED for v in vals):
        raise ValueError(
            "dy2static: a loop variable of a tensor-predicated `while` "
            "is unbound before the loop; assign it first (the traced "
            "loop needs its carry defined on entry)")
    from ..static.control_flow import while_loop
    out = while_loop(lambda *a: cond_fn(*a), lambda *a: body_fn(*a),
                     list(vals))
    return tuple(out)


class _CollectAssigns(ast.NodeVisitor):
    def __init__(self):
        self.names = []

    def visit_Assign(self, node):
        for t in node.targets:
            self._collect(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._collect(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._collect(node.target)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        # nested defs are not threaded through cond/while (function
        # objects aren't jax values); they stay local to their branch
        pass

    def visit_Lambda(self, node):
        pass

    def _collect(self, target):
        if isinstance(target, ast.Name):
            if target.id not in self.names:
                self.names.append(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._collect(e)
        # attribute/subscript targets mutate objects, not names: the
        # closure sees the mutation without threading


def _assigned_names(stmts) -> list:
    c = _CollectAssigns()
    for s in stmts:
        c.visit(s)
    return c.names


_BLOCKERS = (ast.Return, ast.Break, ast.Continue, ast.Yield,
             ast.YieldFrom, ast.Global, ast.Nonlocal)


def _has_blocker(stmts) -> bool:
    """True when the block contains control-transfer statements this pass
    can't rewrite. Nested function scopes are opaque — a `return` inside
    an inner def (including the closures a previous rewrite generated)
    does not transfer control out of THIS block."""
    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, _BLOCKERS):
                return True
            if walk(child):
                return True
        return False

    for s in stmts:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(s, _BLOCKERS):
            return True
        if walk(s):
            return True
    return False


def _any_return(stmts) -> bool:
    """Return statements in this suite or nested `if` chains (loops and
    nested function scopes are opaque: their returns are handled by
    python directly / belong to the inner function)."""
    for st in stmts:
        if isinstance(st, ast.Return):
            return True
        if isinstance(st, ast.If) and (_any_return(st.body)
                                       or _any_return(st.orelse)):
            return True
    return False


def _lowered_volume(seq, budget: int) -> int:
    """Estimate how many statements _lower_returns' tail duplication
    would emit for ``seq`` — by mirroring process()'s recursion with
    counts instead of nodes. Nested returning-ifs duplicate their tail
    into BOTH arms, so the true cost is exponential in nesting depth; a
    flat count of returning ifs bounds the count, not the 2^depth
    blow-up. Clamped: any subtree pushing past ``budget`` returns
    ``budget + 1`` immediately, so the estimate itself stays O(budget).
    """
    n = 0
    for i, st in enumerate(seq):
        if n > budget:
            return n
        if isinstance(st, ast.Return):
            return n + 1
        if isinstance(st, ast.If) and (_any_return(st.body)
                                       or _any_return(st.orelse)):
            rest = seq[i + 1:]
            b = _lowered_volume(list(st.body) + rest, budget - n)
            if n + b > budget:
                return budget + 1
            e = _lowered_volume(list(st.orelse) + rest, budget - n - b)
            return n + 1 + b + e
        n += 1
    return n + 1


def _return_in_ifs(stmts) -> bool:
    # _any_return recurses into nested if chains, so one pass over the
    # top-level statements sees every convertible early return
    return any(isinstance(st, ast.If)
               and (_any_return(st.body) or _any_return(st.orelse))
               for st in stmts)


def _lower_returns(body, val):
    """Early-return lowering (reference: dy2static/return_transformer).

    Every `return e` inside the function's `if` structure becomes
    `<val> = e`, with the statements following a returning `if`
    duplicated into its non-returning paths, so control always falls to
    one final `return <val>` at the bottom. Paths that fall off the end
    assign None, matching python. Loops are untouched: a `return` inside
    them still exits the function directly (python semantics), which is
    correct because the final return is only reached by falling through.
    """
    def process(seq):
        out = []
        for i, st in enumerate(seq):
            if isinstance(st, ast.Return):
                out.append(ast.Assign(
                    targets=[ast.Name(id=val, ctx=ast.Store())],
                    value=st.value or ast.Constant(value=None)))
                return out  # anything after is unreachable
            if isinstance(st, ast.If) and (_any_return(st.body)
                                           or _any_return(st.orelse)):
                rest = seq[i + 1:]
                out.append(ast.If(
                    test=st.test,
                    body=process(list(st.body) + rest) or [ast.Pass()],
                    orelse=process(list(st.orelse) + rest)))
                return out
            out.append(st)
        # fell off the end of this path
        out.append(ast.Assign(targets=[ast.Name(id=val, ctx=ast.Store())],
                              value=ast.Constant(value=None)))
        return out

    new = process(list(body))
    new.append(ast.Return(value=ast.Name(id=val, ctx=ast.Load())))
    return new


def _reads_in(nodes):
    """Overapproximate set of names read anywhere in these nodes
    (including nested scopes — a closure read keeps a name live)."""
    reads = set()
    for node in nodes:
        for n in ast.walk(node):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                reads.add(n.id)
    return reads


def _mutated_containers(stmts):
    """Names whose in-place mutating methods are called in the block —
    candidates that cannot ride a traced loop carry."""
    muts = set()
    for s in stmts:
        for node in ast.walk(s):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("append", "extend", "insert")
                    and isinstance(node.func.value, ast.Name)):
                muts.add(node.func.value.id)
    return sorted(muts)


class _Rewriter(ast.NodeTransformer):
    """Rewrites if/while statements into helper calls with generated
    closures. Fresh names are prefixed __pt_ to stay out of user space.

    global_names: names declared `global` anywhere at this function's
    scope — they can't be threaded as closure parameters (the seed would
    shadow and the cleanup would delete the module binding), so blocks
    assigning them are left unconverted."""

    def __init__(self, global_names=()):
        self.counter = 0
        self.converted = 0  # actual conversions (fresh-name allocation
        # alone must not defeat the caller's keep-original fallback)
        self.global_names = set(global_names)
        # liveness context: the set of names read after the statement
        # being visited (None = unknown -> thread conservatively). Names
        # assigned in a branch but never read later need not be threaded
        # through lax.cond — crucial for early-return lowering, whose
        # else-absorption creates branch-local locals that would
        # otherwise trip the one-sided UNDEFINED check.
        self._live = None

    def _visit_block(self, stmts, live_after):
        """Visit a suite giving each statement its reads-after set
        (live_after=None propagates the conservative unknown)."""
        out = []
        prev_live = self._live
        for i, st in enumerate(stmts):
            self._live = None if live_after is None else (
                _reads_in(stmts[i + 1:]) | live_after)
            r = self.visit(st)
            if isinstance(r, list):
                out.extend(r)
            elif r is not None:
                out.append(r)
        self._live = prev_live
        return out

    def visit_FunctionDef(self, node):
        # each function scope gets its own liveness context; at the end
        # of the suite nothing is live (returns read their value Names,
        # which _reads_in sees)
        node.body = self._visit_block(node.body, set())
        return node

    visit_AsyncFunctionDef = visit_FunctionDef

    def _fresh(self, kind):
        self.counter += 1
        return f"__pt_{kind}_{self.counter}"

    # -- helpers -------------------------------------------------------
    def _make_fn(self, name, argnames, body_stmts, ret_names):
        args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=a) for a in argnames],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[])
        # re-seed before returning: a nested rewrite's cleanup may have
        # `del`eted a name inside this closure (else-less elif chains);
        # the sentinel flows out and the OUTER cleanup deletes it again
        reseed = [self._seed_stmt(n) for n in ret_names]
        ret = ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=n, ctx=ast.Load()) for n in ret_names],
            ctx=ast.Load()))
        return ast.FunctionDef(name=name, args=args,
                               body=list(body_stmts) + reseed + [ret],
                               decorator_list=[], returns=None,
                               type_params=[])

    def _seed_stmt(self, name):
        # x = locals().get('x', UNDEFINED) — binds possibly-unbound names
        # so they can be threaded through the generated closures
        call = ast.Call(
            func=ast.Attribute(
                value=ast.Call(func=ast.Name(id="locals", ctx=ast.Load()),
                               args=[], keywords=[]),
                attr="get", ctx=ast.Load()),
            args=[ast.Constant(value=name),
                  ast.Name(id="__pt_UNDEFINED", ctx=ast.Load())],
            keywords=[])
        return ast.Assign(
            targets=[ast.Name(id=name, ctx=ast.Store())], value=call)

    def _unpack_target(self, names):
        return ast.Tuple(elts=[ast.Name(id=n, ctx=ast.Store())
                               for n in names], ctx=ast.Store())

    def _cleanup_stmts(self, names):
        # `if x is UNDEFINED: del x` — a name no taken branch assigned
        # goes back to being unbound, so later use raises
        # UnboundLocalError exactly like the unconverted python would
        out = []
        for n in names:
            test = ast.Compare(
                left=ast.Name(id=n, ctx=ast.Load()), ops=[ast.Is()],
                comparators=[ast.Name(id="__pt_UNDEFINED",
                                      ctx=ast.Load())])
            out.append(ast.If(
                test=test,
                body=[ast.Delete(targets=[
                    ast.Name(id=n, ctx=ast.Del())])],
                orelse=[]))
        return out

    # -- break/continue lowering (loop_transformer's flag rewrite) -----
    def _loop_interrupts_present(self, stmts):
        """Break/Continue belonging to THIS loop: found in the block but
        not inside a nested loop or function scope."""
        def walk(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda,
                                      ast.For, ast.While, ast.AsyncFor)):
                    continue
                if isinstance(child, (ast.Break, ast.Continue)):
                    return True
                if walk(child):
                    return True
            return False
        return any(isinstance(s, (ast.Break, ast.Continue)) or walk(s)
                   for s in stmts)

    def _lower_loop_interrupts(self, stmts, brk, cont):
        """Rewrite this loop's break/continue into flag assignments and
        guard trailing statements so control falls to the loop bottom —
        the reference's break_continue_transformer strategy. Statements
        inside nested loops/functions are left alone (they belong to the
        inner scope). Returns (lowered_stmts, may_interrupt)."""
        def set_flag(name):
            return ast.Assign(
                targets=[ast.Name(id=name, ctx=ast.Store())],
                value=ast.Constant(value=True))

        def no_flags():
            return ast.Call(
                func=ast.Name(id="__pt_not_any", ctx=ast.Load()),
                args=[ast.Name(id=brk, ctx=ast.Load()),
                      ast.Name(id=cont, ctx=ast.Load())],
                keywords=[])

        acc: list = []
        may_any = False
        for st in reversed(stmts):
            if isinstance(st, ast.Break):
                lowered, may = [set_flag(brk)], True
            elif isinstance(st, ast.Continue):
                lowered, may = [set_flag(cont)], True
            elif isinstance(st, ast.If):
                b, mb = self._lower_loop_interrupts(st.body, brk, cont)
                o, mo = self._lower_loop_interrupts(st.orelse, brk, cont)
                lowered = [ast.If(test=st.test, body=b or [ast.Pass()],
                                  orelse=o)]
                may = mb or mo
            else:
                lowered, may = [st], False
            if may and acc:
                acc = [ast.If(test=no_flags(), body=acc, orelse=[])]
            acc = lowered + acc
            may_any = may_any or may
        return acc, may_any

    @staticmethod
    def _seed_read_name(st):
        """The generated seed `x = locals().get('x', UNDEF)` reads x even
        though no Name-load appears; recognize it so carry analysis sees
        the read."""
        if (isinstance(st, ast.Assign) and isinstance(st.value, ast.Call)
                and isinstance(st.value.func, ast.Attribute)
                and st.value.func.attr == "get"
                and isinstance(st.value.func.value, ast.Call)
                and isinstance(st.value.func.value.func, ast.Name)
                and st.value.func.value.func.id == "locals"
                and st.value.args
                and isinstance(st.value.args[0], ast.Constant)):
            return st.value.args[0].value
        return None

    def _iteration_locals(self, stmts, names):
        """Subset of ``names`` that every iteration (re)binds by a
        top-level Assign before any read: per-iteration temporaries (a
        desugared inner loop's stop/step/loop-var), not loop state.
        Dropping them from the carry is what lets NESTED range loops
        convert — their temporaries would otherwise enter the outer
        traced carry as UNDEFINED seeds."""
        candidate = set(names)
        defined: set = set()
        must_carry: set = set()

        def loads_of(node):
            return {n.id for n in ast.walk(node)
                    if isinstance(n, ast.Name)
                    and isinstance(n.ctx, ast.Load)}

        for st in stmts:
            seed_name = self._seed_read_name(st)
            if seed_name is not None:
                if seed_name in candidate and seed_name not in defined:
                    must_carry.add(seed_name)
                defined.add(seed_name)
                continue
            if isinstance(st, ast.Assign):
                reads = loads_of(st.value)
                for t in st.targets:
                    if not isinstance(t, ast.Name):
                        reads |= loads_of(t)
                must_carry |= (reads & candidate) - defined
                for t in st.targets:
                    if isinstance(t, ast.Name):
                        defined.add(t.id)
            elif isinstance(st, ast.AugAssign):
                reads = loads_of(st.value)
                if isinstance(st.target, ast.Name):
                    reads.add(st.target.id)
                must_carry |= (reads & candidate) - defined
            else:
                must_carry |= (loads_of(st) & candidate) - defined
        return {n for n in candidate
                if n in defined and n not in must_carry}

    # -- transforms ----------------------------------------------------
    def visit_Assert(self, node):
        """assert test, msg -> __pt_assert(test, msg): traced
        predicates become run-time checks instead of trace-time
        TracerBoolConversionErrors (reference: convert_assert)."""
        self.generic_visit(node)
        # the message rides in a lambda: python evaluates an assert's
        # message LAZILY (only on failure) — `assert not errs, errs[0]`
        # must not crash on the passing path
        msg_arg = ast.Constant(value=None) if node.msg is None else \
            ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[],
                                   kwonlyargs=[], kw_defaults=[],
                                   defaults=[]),
                body=node.msg)
        call = ast.Expr(value=ast.Call(
            func=ast.Name(id="__pt_assert", ctx=ast.Load()),
            args=[node.test, msg_arg], keywords=[]))
        self.converted += 1
        return ast.copy_location(call, node)

    def visit_If(self, node):
        live = self._live
        node.body = self._visit_block(node.body, live)
        node.orelse = self._visit_block(node.orelse, live)
        if _has_blocker(node.body) or _has_blocker(node.orelse):
            return node
        names = _assigned_names(node.body + node.orelse)
        # the global check must see every assigned name — a dead-store
        # global would be filtered from the carry below, but converting
        # would still move its assignment into a closure scope where the
        # missing `global` declaration makes it a local write
        if any(n in self.global_names for n in names):
            return node
        if live is not None:
            # branch-local names nothing ever reads again need not ride
            # the lax.cond carry (and must not: assigned one-sided from
            # an unbound start they would trip the UNDEFINED check)
            names = [n for n in names if n in live]
        if not names:
            return node
        self.converted += 1
        tname, fname = self._fresh("true"), self._fresh("false")
        stmts = [self._seed_stmt(n) for n in names]
        stmts.append(self._make_fn(tname, names, node.body, names))
        stmts.append(self._make_fn(fname, names, node.orelse or [ast.Pass()],
                                   names))
        call = ast.Call(
            func=ast.Name(id="__pt_convert_ifelse", ctx=ast.Load()),
            args=[node.test,
                  ast.Name(id=tname, ctx=ast.Load()),
                  ast.Name(id=fname, ctx=ast.Load()),
                  ast.Tuple(elts=[ast.Name(id=n, ctx=ast.Load())
                                  for n in names], ctx=ast.Load())],
            keywords=[])
        stmts.append(ast.Assign(targets=[self._unpack_target(names)],
                                value=call))
        stmts.extend(self._cleanup_stmts(names))
        return stmts

    def visit_For(self, node):
        """`for <name> in range(...)` desugars to the while machinery
        (reference: dy2static loop_transformer's for->while lowering), so
        traced loop bounds work. Other iterables keep python semantics —
        they unroll at trace time, which is correct for static
        containers."""
        live = self._live
        # visit the suites in place FIRST: every bail below returns
        # `node`, and nested conversions must survive the bail
        inner_live = None if live is None else (live | _reads_in([node]))
        node.body = self._visit_block(node.body, inner_live)
        node.orelse = self._visit_block(node.orelse, inner_live)
        if (node.orelse
                or not isinstance(node.target, ast.Name)
                or not isinstance(node.iter, ast.Call)
                or not isinstance(node.iter.func, ast.Name)
                or node.iter.func.id != "range"
                or node.iter.keywords
                or not 1 <= len(node.iter.args) <= 3):
            # non-range loops keep python semantics (trace-time unroll)
            return node
        body_stmts = list(node.body)
        flag_pre: list = []
        flag_test = None
        if self._loop_interrupts_present(body_stmts):
            # lower here (not in visit_While) so the index bump below
            # stays UNGUARDED: `continue` must still advance the loop var
            brk, cont = self._fresh("brk"), self._fresh("cont")
            if inner_live is not None:
                inner_live = inner_live | {brk, cont}
            body_stmts, _ = self._lower_loop_interrupts(body_stmts,
                                                        brk, cont)
            body_stmts = [ast.Assign(
                targets=[ast.Name(id=cont, ctx=ast.Store())],
                value=ast.Constant(value=False))] \
                + self._visit_block(body_stmts, inner_live)
            flag_pre = [ast.Assign(
                targets=[ast.Name(id=n, ctx=ast.Store())],
                value=ast.Constant(value=False)) for n in (brk, cont)]
            flag_test = ast.Name(id=brk, ctx=ast.Load())
        if _has_blocker(body_stmts):
            return node
        var = node.target.id
        a = node.iter.args
        start = a[0] if len(a) >= 2 else ast.Constant(value=0)
        stop = a[1] if len(a) >= 2 else a[0]
        step = a[2] if len(a) == 3 else ast.Constant(value=1)
        stop_n, step_n = self._fresh("stop"), self._fresh("step")
        pre = [
            ast.Assign(targets=[ast.Name(id=stop_n, ctx=ast.Store())],
                       value=stop),
            ast.Assign(targets=[ast.Name(id=step_n, ctx=ast.Store())],
                       value=step),
            ast.Assign(targets=[ast.Name(id=var, ctx=ast.Store())],
                       value=start),
        ]
        # (stop - i) * step > 0 — one comparison, correct for both signs
        test = ast.Compare(
            left=ast.BinOp(
                left=ast.BinOp(
                    left=ast.Name(id=stop_n, ctx=ast.Load()),
                    op=ast.Sub(),
                    right=ast.Name(id=var, ctx=ast.Load())),
                op=ast.Mult(),
                right=ast.Name(id=step_n, ctx=ast.Load())),
            ops=[ast.Gt()], comparators=[ast.Constant(value=0)])
        if flag_test is not None:
            test = ast.Call(
                func=ast.Name(id="__pt_and_not", ctx=ast.Load()),
                args=[test, flag_test], keywords=[])
        step_expr = ast.Name(id=step_n, ctx=ast.Load())
        if flag_test is not None:
            # break preserves the loop var (bump * 0 when brk set);
            # continue still advances (cont does not zero the bump)
            step_expr = ast.BinOp(
                left=step_expr, op=ast.Mult(),
                right=ast.Call(
                    func=ast.Name(id="__pt_flag_off", ctx=ast.Load()),
                    args=[ast.Name(id=flag_test.id, ctx=ast.Load())],
                    keywords=[]))
        bump = ast.Assign(
            targets=[ast.Name(id=var, ctx=ast.Store())],
            value=ast.BinOp(left=ast.Name(id=var, ctx=ast.Load()),
                            op=ast.Add(), right=step_expr))
        loop = ast.While(test=test, body=body_stmts + [bump],
                         orelse=[])
        lowered = self.visit_While(loop)
        return pre + flag_pre + (lowered if isinstance(lowered, list)
                                 else [lowered])

    def visit_While(self, node):
        live = self._live
        inner_live = None if live is None else (live | _reads_in([node]))
        # visit the suites in place FIRST: every bail below returns
        # `node`, and nested conversions must survive the bail
        node.body = self._visit_block(node.body, inner_live)
        node.orelse = self._visit_block(node.orelse, inner_live)
        if node.orelse:
            return node
        work, pre = node, []
        if self._loop_interrupts_present(node.body):
            brk, cont = self._fresh("brk"), self._fresh("cont")
            # the synthesized test/guards read the flags: they must stay
            # live (and in the carry) even though the pre-lowering AST
            # never mentions them
            if inner_live is not None:
                inner_live = inner_live | {brk, cont}
            lowered, _ = self._lower_loop_interrupts(node.body, brk, cont)
            body = [ast.Assign(
                targets=[ast.Name(id=cont, ctx=ast.Store())],
                value=ast.Constant(value=False))] \
                + self._visit_block(lowered, inner_live)
            test = ast.Call(
                func=ast.Name(id="__pt_and_not", ctx=ast.Load()),
                args=[node.test, ast.Name(id=brk, ctx=ast.Load())],
                keywords=[])
            work = ast.While(test=test, body=body, orelse=[])
            pre = [ast.Assign(
                targets=[ast.Name(id=n, ctx=ast.Store())],
                value=ast.Constant(value=False)) for n in (brk, cont)]
        if _has_blocker(work.body):
            return node  # other control transfers remain unconvertible
        all_names = _assigned_names(work.body)
        local = self._iteration_locals(work.body, all_names)
        # the loop test runs before the body each iteration: names it
        # reads are loop state regardless of body-local rebinding
        local -= {n.id for n in ast.walk(work.test)
                  if isinstance(n, ast.Name)
                  and isinstance(n.ctx, ast.Load)}
        names = [n for n in all_names if n not in local]
        if any(n in self.global_names for n in names):
            return node  # see visit_If: globals must not enter closures
        if inner_live is not None:
            # dead stores (assigned, never read in the loop or after)
            # stay out of the carry: unbound before the loop they would
            # poison a traced carry with UNDEFINED seeds
            names = [n for n in names if n in inner_live]
        if not names:
            return node
        self.converted += 1
        cname, bname = self._fresh("cond"), self._fresh("body")
        stmts = pre + [self._seed_stmt(n) for n in names]
        cond_fn = self._make_fn(cname, names, [], [])
        cond_fn.body = [ast.Return(value=work.test)]
        stmts.append(cond_fn)
        stmts.append(self._make_fn(bname, names, work.body, names))
        muts = _mutated_containers(work.body)
        call = ast.Call(
            func=ast.Name(id="__pt_convert_while", ctx=ast.Load()),
            args=[ast.Name(id=cname, ctx=ast.Load()),
                  ast.Name(id=bname, ctx=ast.Load()),
                  ast.Tuple(elts=[ast.Name(id=n, ctx=ast.Load())
                                  for n in names], ctx=ast.Load())],
            keywords=[ast.keyword(
                arg="mutates",
                value=ast.Tuple(elts=[ast.Constant(value=m) for m in muts],
                                ctx=ast.Load()))] if muts else [])
        stmts.append(ast.Assign(targets=[self._unpack_target(names)],
                                value=call))
        stmts.extend(self._cleanup_stmts(names))
        return stmts


def _is_to_static_decorator(node) -> bool:
    """Syntactically recognize @to_static / @paddle.jit.to_static
    (optionally called) so exactly those are stripped from the rewrite."""
    if isinstance(node, ast.Call):
        node = node.func
    while isinstance(node, ast.Attribute):
        if node.attr == "to_static":
            return True
        node = node.value
    return isinstance(node, ast.Name) and node.id == "to_static"


def convert_to_static(fn: Callable) -> Callable:
    """Source-rewrite fn's control flow; returns fn unchanged when the
    source is unavailable, nothing needs rewriting, or the function's
    shape is outside the supported subset (closures, foreign decorators)
    — the reference's fallback behavior.

    Bound methods are converted through their underlying function and
    re-bound to the same instance.
    """
    if inspect.ismethod(fn):
        import types
        converted = convert_to_static(fn.__func__)
        if converted is fn.__func__:
            return fn
        return types.MethodType(converted, fn.__self__)
    if getattr(fn, "__pt_dy2static__", False):
        return fn
    if getattr(fn, "__closure__", None):
        # recompiling would freeze cell contents at conversion time —
        # later mutations of the closed-over variables would go unseen.
        # Closure-carrying functions keep plain tracing.
        return fn
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    # strip only the to_static decorator (re-decorating would recurse);
    # any OTHER decorator in the source would be silently dropped by a
    # rewrite, so its presence disables conversion instead
    kept = [d for d in fdef.decorator_list
            if not _is_to_static_decorator(d)]
    if kept:
        return fn
    fdef.decorator_list = []

    # names declared `global` anywhere in this function (not in nested
    # defs) must never be threaded through generated closures
    global_names = set()
    for node in ast.walk(fdef):
        if isinstance(node, ast.Global):
            global_names.update(node.names)

    # early-return lowering first: once returns inside if chains become
    # assignments to one value name, the rewriter below can thread those
    # ifs through lax.cond like any other branch assignment
    # Guard-style returns (body returns immediately) duplicate nothing;
    # deep returns in BOTH arms double the tail per nesting level, so
    # cap the ESTIMATED EMITTED VOLUME (not the flat count of returning
    # ifs — 8 shallow guards are fine, 8 nested both-arm returns would
    # be ~256x tail copies) before falling back to unconverted (python)
    # semantics for the whole function.
    lowered_returns = False
    if _return_in_ifs(fdef.body) and \
            _lowered_volume(fdef.body, 512) <= 512:
        fdef.body = _lower_returns(fdef.body, "__pt_retval")
        lowered_returns = True

    rewriter = _Rewriter(global_names)
    new_tree = rewriter.visit(tree)
    if rewriter.converted == 0 and not lowered_returns:
        return fn  # nothing converted — keep the original object
    ast.fix_missing_locations(new_tree)

    # execute against the REAL module globals so `global` writes land in
    # the module and later global rebindings stay visible; only the
    # handful of __pt_* helpers are added (underscore-prefixed, stable)
    glb: Dict[str, Any] = fn.__globals__
    glb.setdefault("__pt_convert_ifelse", convert_ifelse)
    glb.setdefault("__pt_convert_while", convert_while_loop)
    glb.setdefault("__pt_UNDEFINED", UNDEFINED)
    glb.setdefault("__pt_not_any", convert_not_any)
    glb.setdefault("__pt_and_not", convert_and_not)
    glb.setdefault("__pt_flag_off", convert_flag_off)
    glb.setdefault("__pt_assert", convert_assert)
    loc: Dict[str, Any] = {}
    code = compile(new_tree, filename=f"<dy2static {fn.__qualname__}>",
                   mode="exec")
    exec(code, glb, loc)
    out = loc[fdef.name]
    out = functools.wraps(fn)(out)
    out.__pt_dy2static__ = True
    if _CODE_LEVEL[0] > 0 or _VERBOSITY[0] >= 3:
        print(f"--- dy2static transformed code of "
              f"{fn.__qualname__} ---")
        print(ast.unparse(new_tree))
    return out
