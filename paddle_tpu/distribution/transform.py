"""Bijective transforms (reference: python/paddle/distribution/transform.py
— Transform base with forward/inverse/log_det_jacobian, AffineTransform,
ExpTransform, SigmoidTransform, TanhTransform, PowerTransform,
AbsTransform, ChainTransform, SoftmaxTransform, StickBreakingTransform,
IndependentTransform, ReshapeTransform, StackTransform).

Pure-jnp elementwise math; every transform also drives
TransformedDistribution's log_prob/sample."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["Type", "Transform", "AbsTransform", "AffineTransform",
           "ChainTransform", "ExpTransform", "IndependentTransform",
           "PowerTransform", "ReshapeTransform", "SigmoidTransform",
           "SoftmaxTransform", "StackTransform", "StickBreakingTransform",
           "TanhTransform"]


def _arr(x):
    return x._array if isinstance(x, Tensor) else jnp.asarray(x)


class Type:
    BIJECTION = "bijection"
    INJECTION = "injection"
    SURJECTION = "surjection"
    OTHER = "other"


class Transform:
    _type = Type.OTHER

    def forward(self, x):
        return Tensor(self._forward(_arr(x)))

    def inverse(self, y):
        return Tensor(self._inverse(_arr(y)))

    def forward_log_det_jacobian(self, x):
        return Tensor(self._forward_log_det_jacobian(_arr(x)))

    def inverse_log_det_jacobian(self, y):
        y = _arr(y)
        return Tensor(-self._forward_log_det_jacobian(self._inverse(y)))

    def forward_shape(self, shape):
        return list(shape)

    def inverse_shape(self, shape):
        return list(shape)

    # event dimensionality consumed/produced (0 = elementwise)
    _domain_event_dim = 0
    _codomain_event_dim = 0


class AbsTransform(Transform):
    """y = |x| (reference: transform.py AbsTransform). Surjective — the
    conventional inverse returns the positive branch."""
    _type = Type.SURJECTION

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y

    def _forward_log_det_jacobian(self, x):
        return jnp.zeros_like(x)


class AffineTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, power):
        self.power = _arr(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jax_sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        return -jnp.logaddexp(0.0, -x) - jnp.logaddexp(0.0, x)


def jax_sigmoid(x):
    return 1.0 / (1.0 + jnp.exp(-x))


class TanhTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _forward_log_det_jacobian(self, x):
        # log(1 - tanh^2 x) = 2 (log2 - x - softplus(-2x))
        return 2.0 * (jnp.log(2.0) - x - jnp.logaddexp(0.0, -2.0 * x))


class SoftmaxTransform(Transform):
    """x → softmax(x) over the last axis; inverse is log (up to an
    additive constant) — reference: transform.py SoftmaxTransform."""
    _type = Type.OTHER
    _domain_event_dim = 1
    _codomain_event_dim = 1

    def _forward(self, x):
        z = x - jnp.max(x, axis=-1, keepdims=True)
        e = jnp.exp(z)
        return e / jnp.sum(e, axis=-1, keepdims=True)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError(
            "SoftmaxTransform is not a bijection; no log-det")


class StickBreakingTransform(Transform):
    """R^{K-1} → K-simplex via stick breaking
    (reference: transform.py StickBreakingTransform)."""
    _type = Type.BIJECTION
    _domain_event_dim = 1
    _codomain_event_dim = 1

    def _forward(self, x):
        k = x.shape[-1]
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=x.dtype))
        z = jax_sigmoid(x - offset)
        z_cumprod = jnp.cumprod(1 - z, axis=-1)
        head = z * jnp.concatenate(
            [jnp.ones_like(z[..., :1]), z_cumprod[..., :-1]], axis=-1)
        tail = z_cumprod[..., -1:]
        return jnp.concatenate([head, tail], axis=-1)

    def _inverse(self, y):
        k = y.shape[-1] - 1
        y_crop = y[..., :-1]
        rem = 1.0 - jnp.cumsum(y_crop, axis=-1)
        rem = jnp.concatenate([jnp.ones_like(y_crop[..., :1]),
                               rem[..., :-1]], axis=-1)
        z = y_crop / rem
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=y.dtype))
        return jnp.log(z) - jnp.log1p(-z) + offset

    def _forward_log_det_jacobian(self, x):
        k = x.shape[-1]
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=x.dtype))
        z = x - offset
        # sum over event dim of log sigmoid'(z) + log remaining stick
        log_sig = -jnp.logaddexp(0.0, -z)
        log_one_minus_sig = -jnp.logaddexp(0.0, z)
        cum = jnp.cumsum(log_one_minus_sig[..., :-1], axis=-1)
        cum = jnp.concatenate([jnp.zeros_like(cum[..., :1]), cum], axis=-1)
        return jnp.sum(log_sig + log_one_minus_sig + cum, axis=-1)

    def forward_shape(self, shape):
        return list(shape[:-1]) + [shape[-1] + 1]

    def inverse_shape(self, shape):
        return list(shape[:-1]) + [shape[-1] - 1]


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)
        self._type = Type.BIJECTION if all(
            t._type == Type.BIJECTION for t in self.transforms) \
            else Type.OTHER

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    @property
    def _domain_event_dim(self):
        # reference transform.py:581-606 (ChainTransform._domain): the
        # chain's input event rank is the max lower bound propagated
        # backwards through each transform's rank delta
        event_rank = self.transforms[-1]._codomain_event_dim
        for t in reversed(self.transforms):
            event_rank -= t._codomain_event_dim - t._domain_event_dim
            event_rank = max(event_rank, t._domain_event_dim)
        return event_rank

    @property
    def _codomain_event_dim(self):
        event_rank = self.transforms[0]._domain_event_dim
        for t in self.transforms:
            event_rank += t._codomain_event_dim - t._domain_event_dim
            event_rank = max(event_rank, t._codomain_event_dim)
        return event_rank

    def _forward_log_det_jacobian(self, x):
        # reference transform.py:556-565: each component's ldj is summed
        # over (chain event rank - component domain rank) trailing dims so
        # every term is reduced to the same batch shape; the running rank
        # tracks shape-changing components
        total = 0.0
        event_rank = self._domain_event_dim
        for t in self.transforms:
            total = total + _sum_event(t._forward_log_det_jacobian(x),
                                       event_rank - t._domain_event_dim)
            x = t._forward(x)
            event_rank += t._codomain_event_dim - t._domain_event_dim
        return total

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return shape

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return shape


def _sum_event(x, event_dim):
    for _ in range(event_dim):
        x = jnp.sum(x, axis=-1)
    return x


class IndependentTransform(Transform):
    """Reinterprets batch dims of a base transform as event dims
    (reference: transform.py IndependentTransform)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        self._type = base._type
        self._domain_event_dim = base._domain_event_dim + self.rank
        self._codomain_event_dim = base._codomain_event_dim + self.rank

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _forward_log_det_jacobian(self, x):
        return _sum_event(self.base._forward_log_det_jacobian(x),
                          self.rank)


class ReshapeTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        self._domain_event_dim = len(self.in_event_shape)
        self._codomain_event_dim = len(self.out_event_shape)

    def _forward(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return x.reshape(batch + self.out_event_shape)

    def _inverse(self, y):
        batch = y.shape[:y.ndim - len(self.out_event_shape)]
        return y.reshape(batch + self.in_event_shape)

    def _forward_log_det_jacobian(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch, x.dtype)

    def forward_shape(self, shape):
        n = len(self.in_event_shape)
        return list(shape[:-n]) + list(self.out_event_shape)

    def inverse_shape(self, shape):
        n = len(self.out_event_shape)
        return list(shape[:-n]) + list(self.in_event_shape)


class StackTransform(Transform):
    """Applies a list of transforms along slices of `axis`
    (reference: transform.py StackTransform)."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = axis

    def _map(self, fn_name, x):
        parts = jnp.split(x, len(self.transforms), axis=self.axis)
        outs = [getattr(t, fn_name)(p.squeeze(self.axis))
                for t, p in zip(self.transforms, parts)]
        return jnp.stack(outs, axis=self.axis)

    def _forward(self, x):
        return self._map("_forward", x)

    def _inverse(self, y):
        return self._map("_inverse", y)

    def _forward_log_det_jacobian(self, x):
        return self._map("_forward_log_det_jacobian", x)
