"""Probability distributions.

Reference analog: python/paddle/distribution/ (Distribution base, Normal,
Uniform, Categorical, Bernoulli, Beta, Dirichlet, Multinomial, kl_divergence
registry).
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op
from ..framework.random import next_key
from ..ops.registry import _ensure_tensor

__all__ = ["Distribution", "ExponentialFamily", "Normal", "Uniform",
           "Categorical", "Bernoulli", "Beta", "Dirichlet", "Exponential",
           "Gamma", "Gumbel", "Laplace", "LogNormal", "Multinomial",
           "Independent", "TransformedDistribution", "kl_divergence",
           "register_kl", "transform"]


def _arr(x):
    if isinstance(x, Tensor):
        return x._array
    return jnp.asarray(np.asarray(x, dtype=np.float32))


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return list(self._batch_shape)

    @property
    def event_shape(self):
        return list(self._event_shape)

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return Tensor(jnp.exp(self.log_prob(value)._array))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self._batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(self.scale ** 2, self._batch_shape))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        z = jax.random.normal(next_key(), shp)
        return Tensor(self.loc + self.scale * z)

    def log_prob(self, value):
        v = _arr(value)
        var = self.scale ** 2
        return Tensor(-((v - self.loc) ** 2) / (2 * var)
                      - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        e = 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)
        return Tensor(jnp.broadcast_to(e, self._batch_shape))


class LogNormal(Normal):
    def sample(self, shape=()):
        return Tensor(jnp.exp(super().sample(shape)._array))

    def log_prob(self, value):
        v = _arr(value)
        logv = jnp.log(v)
        base = super().log_prob(Tensor(logv))._array
        return Tensor(base - logv)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _arr(low)
        self.high = _arr(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape,
                                              self.high.shape))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        u = jax.random.uniform(next_key(), shp)
        return Tensor(self.low + (self.high - self.low) * u)

    def log_prob(self, value):
        v = _arr(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return Tensor(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs = _arr(probs)
        super().__init__(jnp.shape(self.probs))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return Tensor(jax.random.bernoulli(
            next_key(), jnp.broadcast_to(self.probs, shp)).astype(
            jnp.float32))

    def log_prob(self, value):
        v = _arr(value)
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return Tensor(v * jnp.log(p) + (1 - v) * jnp.log1p(-p))

    def entropy(self):
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _arr(logits)
        super().__init__(jnp.shape(self.logits)[:-1])

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return Tensor(jax.random.categorical(
            next_key(), self.logits, shape=shp).astype(jnp.int64))

    def log_prob(self, value):
        v = _arr(value).astype(jnp.int32)
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return Tensor(jnp.take_along_axis(logp, v[..., None],
                                          axis=-1)[..., 0])

    def probs(self, value):
        return Tensor(jnp.exp(self.log_prob(value)._array))

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return Tensor(-jnp.sum(jnp.exp(logp) * logp, axis=-1))


class Beta(Distribution):
    def __init__(self, alpha, beta):
        self.alpha = _arr(alpha)
        self.beta = _arr(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape,
                                              self.beta.shape))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return Tensor(jax.random.beta(next_key(), self.alpha, self.beta,
                                      shape=shp))

    def log_prob(self, value):
        from jax.scipy.special import betaln
        v = _arr(value)
        return Tensor((self.alpha - 1) * jnp.log(v)
                      + (self.beta - 1) * jnp.log1p(-v)
                      - betaln(self.alpha, self.beta))

    @property
    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta))


class Dirichlet(Distribution):
    def __init__(self, concentration):
        self.concentration = _arr(concentration)
        super().__init__(jnp.shape(self.concentration)[:-1],
                         jnp.shape(self.concentration)[-1:])

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return Tensor(jax.random.dirichlet(next_key(), self.concentration,
                                           shape=shp))

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        v = _arr(value)
        a = self.concentration
        return Tensor(jnp.sum((a - 1) * jnp.log(v), axis=-1)
                      + gammaln(jnp.sum(a, axis=-1))
                      - jnp.sum(gammaln(a), axis=-1))


class Exponential(Distribution):
    def __init__(self, rate):
        self.rate = _arr(rate)
        super().__init__(jnp.shape(self.rate))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return Tensor(jax.random.exponential(next_key(), shp) / self.rate)

    def log_prob(self, value):
        v = _arr(value)
        return Tensor(jnp.log(self.rate) - self.rate * v)

    def entropy(self):
        return Tensor(1 - jnp.log(self.rate))


class Gamma(Distribution):
    def __init__(self, concentration, rate):
        self.concentration = _arr(concentration)
        self.rate = _arr(rate)
        super().__init__(jnp.broadcast_shapes(self.concentration.shape,
                                              self.rate.shape))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return Tensor(jax.random.gamma(next_key(), self.concentration,
                                       shape=shp) / self.rate)

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        v = _arr(value)
        a, b = self.concentration, self.rate
        return Tensor(a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v
                      - gammaln(a))


class Laplace(Distribution):
    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return Tensor(jax.random.laplace(next_key(), shp) * self.scale
                      + self.loc)

    def log_prob(self, value):
        v = _arr(value)
        return Tensor(-jnp.abs(v - self.loc) / self.scale
                      - jnp.log(2 * self.scale))

    def entropy(self):
        return Tensor(1 + jnp.log(2 * self.scale))


class Multinomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = total_count
        self.probs_arr = _arr(probs)
        super().__init__(jnp.shape(self.probs_arr)[:-1],
                         jnp.shape(self.probs_arr)[-1:])

    def sample(self, shape=()):
        n_cat = self.probs_arr.shape[-1]
        draws = jax.random.categorical(
            next_key(), jnp.log(self.probs_arr),
            shape=tuple(shape) + self._batch_shape + (self.total_count,))
        onehot = jax.nn.one_hot(draws, n_cat)
        return Tensor(jnp.sum(onehot, axis=-2))

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        v = _arr(value)
        logits = jnp.log(self.probs_arr)
        return Tensor(gammaln(self.total_count + 1)
                      - jnp.sum(gammaln(v + 1), axis=-1)
                      + jnp.sum(v * logits, axis=-1))


class ExponentialFamily(Distribution):
    """Marker base for exponential-family distributions; entropy via the
    Bregman-divergence identity is replaced by each subclass's closed
    form (reference: python/paddle/distribution/exponential_family.py)."""


class Gumbel(Distribution):
    """reference: python/paddle/distribution/gumbel.py."""

    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return Tensor(self.loc + self.scale * 0.57721566490153286)

    @property
    def variance(self):
        return Tensor((math.pi ** 2 / 6) * self.scale ** 2)

    @property
    def stddev(self):
        return Tensor(jnp.sqrt((math.pi ** 2 / 6)) * self.scale)

    def sample(self, shape=()):
        shp = tuple(shape) + self._batch_shape
        return Tensor(jax.random.gumbel(next_key(), shp) * self.scale
                      + self.loc)

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        z = (_arr(value) - self.loc) / self.scale
        return Tensor(-(z + jnp.exp(-z)) - jnp.log(self.scale))

    def entropy(self):
        return Tensor(jnp.log(self.scale) + 1.0 + 0.57721566490153286
                      + jnp.zeros(self._batch_shape))


class Independent(Distribution):
    """Reinterprets the rightmost `reinterpreted_batch_rank` batch dims of
    `base` as event dims (reference:
    python/paddle/distribution/independent.py)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        assert 0 < self.rank <= len(base.batch_shape), \
            "reinterpreted_batch_rank must be in (0, len(batch_shape)]"
        bshape = tuple(base.batch_shape)
        super().__init__(bshape[:len(bshape) - self.rank],
                         bshape[len(bshape) - self.rank:]
                         + tuple(base.event_shape))

    def sample(self, shape=()):
        return self.base.sample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)._array
        for _ in range(self.rank):
            lp = jnp.sum(lp, axis=-1)
        return Tensor(lp)

    def entropy(self):
        e = self.base.entropy()._array
        for _ in range(self.rank):
            e = jnp.sum(e, axis=-1)
        return Tensor(e)


class TransformedDistribution(Distribution):
    """Push a base distribution through a chain of transforms
    (reference: python/paddle/distribution/transformed_distribution.py)."""

    def __init__(self, base, transforms):
        from . import transform as T
        self.base = base
        self.transforms = list(transforms)
        for t in self.transforms:
            assert isinstance(t, T.Transform), \
                "transforms must be distribution.transform.Transform"
        shape = tuple(base.batch_shape) + tuple(base.event_shape)
        for t in self.transforms:
            shape = tuple(t.forward_shape(shape))
        # conservatively treat everything beyond base batch as event
        nb = len(base.batch_shape)
        super().__init__(shape[:nb], shape[nb:])

    def sample(self, shape=()):
        x = self.base.sample(shape)._array
        for t in self.transforms:
            x = t._forward(x)
        return Tensor(x)

    def rsample(self, shape=()):
        x = self.base.rsample(shape)._array
        for t in self.transforms:
            x = t._forward(x)
        return Tensor(x)

    def log_prob(self, value):
        # event_dim bookkeeping follows the standard transformed-dist
        # recursion: a transform's log-det comes back with its OWN domain
        # event dims already reduced, so only the surplus event dims (from
        # the overall event shape) are summed here — never both.
        from .transform import _sum_event
        y = _arr(value)
        lp = 0.0
        event_dim = len(self._event_shape)
        for t in reversed(self.transforms):
            x = t._inverse(y)
            event_dim += t._domain_event_dim - t._codomain_event_dim
            lp = lp - _sum_event(t._forward_log_det_jacobian(x),
                                 event_dim - t._domain_event_dim)
            y = x
        base_lp = _sum_event(self.base.log_prob(Tensor(y))._array,
                             event_dim - len(self.base.event_shape))
        return Tensor(lp + base_lp)


_KL_REGISTRY = {}


def register_kl(cls_p, cls_q):
    def deco(fn):
        _KL_REGISTRY[(cls_p, cls_q)] = fn
        return fn
    return deco


def kl_divergence(p, q):
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is not None:
        return fn(p, q)
    raise NotImplementedError(
        f"kl_divergence not registered for {type(p).__name__}, "
        f"{type(q).__name__}")


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    logp = jax.nn.log_softmax(p.logits, axis=-1)
    logq = jax.nn.log_softmax(q.logits, axis=-1)
    return Tensor(jnp.sum(jnp.exp(logp) * (logp - logq), axis=-1))


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    return Tensor(jnp.log((q.high - q.low) / (p.high - p.low)))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    pp = jnp.clip(p.probs, 1e-7, 1 - 1e-7)
    qq = jnp.clip(q.probs, 1e-7, 1 - 1e-7)
    return Tensor(pp * jnp.log(pp / qq)
                  + (1 - pp) * jnp.log((1 - pp) / (1 - qq)))


from . import transform  # noqa: E402,F401 — paddle.distribution.transform
