"""paddle.device parity (reference: python/paddle/device/__init__.py).

cuda submodule maps onto the TPU runtime: streams are XLA-managed; the
synchronize/memory APIs expose PJRT equivalents.
"""
from __future__ import annotations

import jax

from ..core.place import (set_device, get_device, device_count, CPUPlace,
                          TPUPlace, CustomPlace, is_compiled_with_cuda,
                          is_compiled_with_tpu, XPUPlace, IPUPlace,
                          MLUPlace, NPUPlace, is_compiled_with_xpu,
                          is_compiled_with_ipu, is_compiled_with_cinn,
                          is_compiled_with_rocm, is_compiled_with_npu,
                          is_compiled_with_mlu, get_cudnn_version)

__all__ = ["set_device", "get_device", "get_all_device_type",
           "get_available_device", "device_count", "synchronize",
           "is_compiled_with_cuda", "is_compiled_with_tpu", "cuda", "Stream",
           "Event", "XPUPlace", "IPUPlace", "MLUPlace", "NPUPlace",
           "is_compiled_with_xpu", "is_compiled_with_ipu",
           "is_compiled_with_cinn", "is_compiled_with_rocm",
           "is_compiled_with_npu", "is_compiled_with_mlu",
           "get_cudnn_version", "memory_stats", "memory_allocated",
           "max_memory_allocated", "memory_reserved"]


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def synchronize(device=None):
    """Block until all queued work completes (cudaDeviceSynchronize
    analog); XLA exposes this per-array, so sync a trivial computation."""
    import jax.numpy as jnp
    jnp.zeros(()).block_until_ready()


class Stream:
    """XLA orders work per-device automatically; Stream is an API-parity
    no-op handle (reference: paddle/fluid/pybind/cuda_streams_py.cc)."""

    def __init__(self, device=None, priority=None):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        self._t = None

    def record(self, stream=None):
        import time
        synchronize()
        self._t = time.perf_counter()

    def synchronize(self):
        synchronize()

    def elapsed_time(self, end):
        return (end._t - self._t) * 1000.0


# -- memory introspection ---------------------------------------------------
# Live PJRT allocator stats for the *requested* device (not always chip 0),
# merged with xmem's analysis-derived static peaks so the numbers are
# meaningful even on backends whose allocator doesn't track a peak (CPU
# PJRT returns no peak_bytes_in_use; pre-flight/hardware-free runs have no
# live allocations at all).

def _resolve_jax_device(device=None) -> jax.Device:
    """None -> current place's device; int -> ordinal into jax.devices();
    str / Place / jax.Device via core.place parsing."""
    from ..core.place import _current_place, _parse_device
    if isinstance(device, jax.Device):
        return device
    if isinstance(device, int):
        devs = jax.devices()
        return devs[device % len(devs)]
    place = _current_place() if device is None else _parse_device(device)
    try:
        return place.device
    except RuntimeError:
        return jax.devices()[0]


def memory_stats(device=None) -> dict:
    """PJRT allocator stats for `device` merged with compile-time analysis.

    Returns the backend's native keys (bytes_in_use, bytes_reserved, ...)
    plus:
      live_peak_bytes_in_use   allocator-tracked peak as reported (0 if
                               the backend doesn't track one)
      xmem_static_peak_bytes   largest per-executable HBM peak captured by
                               profiler.xmem (args+outputs+temps+code)
      xmem_generated_code_bytes  total executable code size captured
      peak_bytes_in_use        max(live peak, static peak)
    """
    dev = _resolve_jax_device(device)
    try:
        stats = dict(dev.memory_stats() or {})
    except Exception:
        stats = {}
    from ..profiler import xmem
    stats.setdefault("bytes_in_use", 0)
    live_peak = stats.get("peak_bytes_in_use", 0)
    static_peak = xmem.max_static_peak()
    stats["live_peak_bytes_in_use"] = live_peak
    stats["xmem_static_peak_bytes"] = static_peak
    stats["xmem_generated_code_bytes"] = xmem.total_generated_code()
    stats["peak_bytes_in_use"] = max(live_peak, static_peak)
    return stats


def memory_allocated(device=None) -> int:
    return memory_stats(device).get("bytes_in_use", 0)


def max_memory_allocated(device=None) -> int:
    return memory_stats(device).get("peak_bytes_in_use", 0)


def memory_reserved(device=None) -> int:
    s = memory_stats(device)
    return s.get("bytes_reserved", s.get("bytes_in_use", 0))


class _CudaNamespace:
    """paddle.device.cuda / paddle.cuda parity routed to the TPU chip."""

    Stream = Stream
    Event = Event

    @staticmethod
    def device_count():
        return device_count()

    @staticmethod
    def synchronize(device=None):
        synchronize()

    @staticmethod
    def current_stream(device=None):
        return Stream()

    @staticmethod
    def stream_guard(stream):
        import contextlib
        return contextlib.nullcontext()

    @staticmethod
    def memory_allocated(device=None):
        return memory_allocated(device)

    @staticmethod
    def max_memory_allocated(device=None):
        return max_memory_allocated(device)

    @staticmethod
    def memory_reserved(device=None):
        return memory_reserved(device)

    @staticmethod
    def memory_stats(device=None):
        return memory_stats(device)

    @staticmethod
    def empty_cache():
        pass


cuda = _CudaNamespace()


# -- custom-device + stream surface (reference: device/__init__.py) --------

def get_all_custom_device_type():
    """CustomDevice plugin types: the PJRT plugin fills that role here
    (SURVEY §5.1#4), so non-CPU platforms report as custom types."""
    return sorted({d.platform for d in jax.devices()
                   if d.platform != "cpu"})


def get_available_custom_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()
            if d.platform != "cpu"]


def is_compiled_with_custom_device(device_type):
    return device_type in get_all_custom_device_type()


_CURRENT_STREAM = Stream()


def current_stream(device=None):
    """XLA orders work per device; ONE logical stream exists."""
    return _CURRENT_STREAM


def set_stream(stream):
    global _CURRENT_STREAM
    prev, _CURRENT_STREAM = _CURRENT_STREAM, stream
    return prev


class stream_guard:  # noqa: N801 — reference spelling
    def __init__(self, stream):
        self._stream = stream
        self._prev = None

    def __enter__(self):
        self._prev = set_stream(self._stream)
        return self._stream

    def __exit__(self, *exc):
        set_stream(self._prev)
        return False


__all__ += ["get_all_custom_device_type", "get_available_custom_device",
            "is_compiled_with_custom_device", "current_stream",
            "set_stream", "stream_guard"]
