"""High-level Model API.

Reference analog: python/paddle/hapi/model.py (Model.fit at :1706,
evaluate/predict, prepare) + callbacks.py (ProgBarLogger, ModelCheckpoint).
"""
from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor, to_tensor, no_grad
from ..metric import Metric

__all__ = ["Model", "summary"]


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = callbacks or []

    def __getattr__(self, name):
        def call(*args, **kwargs):
            for cb in self.callbacks:
                if hasattr(cb, name):
                    getattr(cb, name)(*args, **kwargs)
        return call


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False

    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) \
                else [metrics]
        return self

    # -- single-step APIs --------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = self._as_list(inputs)
        labels = self._as_list(labels)
        outputs = self.network(*inputs)
        losses = self._compute_loss(outputs, labels)
        total = losses[0] if len(losses) == 1 else sum(losses[1:], losses[0])
        total.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = self._update_metrics(outputs, labels)
        loss_vals = [float(l.item()) for l in losses]
        self._record_train_loss(loss_vals)
        return (loss_vals, metrics) if metrics else loss_vals

    @staticmethod
    def _record_train_loss(loss_vals):
        """Loss telemetry + tensor-checker step advance. Disabled path:
        two dict lookups."""
        from ..amp import debugging as _debugging
        _debugging.advance_step()
        from ..profiler import metrics as _metrics
        if not _metrics.enabled():
            return
        import math
        total = float(sum(loss_vals))
        _metrics.counter("train_batches_total",
                         "train_batch calls").inc()
        _metrics.gauge("train_loss", "Last train_batch total loss"
                       ).set(total)
        from ..profiler import numerics as _numerics
        _numerics.note("train_loss", total)
        if not math.isfinite(total):
            _metrics.counter("nonfinite_loss_steps_total",
                             "train_batch steps with NaN/Inf loss").inc()
            _numerics.record_site(
                "hapi.train_batch:loss", True,
                {"nan": int(math.isnan(total)),
                 "inf": int(math.isinf(total)), "size": len(loss_vals),
                 "shape": (len(loss_vals),), "dtype": "float32"})

    @no_grad()
    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = self._as_list(inputs)
        labels = self._as_list(labels)
        outputs = self.network(*inputs)
        losses = self._compute_loss(outputs, labels)
        metrics = self._update_metrics(outputs, labels)
        loss_vals = [float(l.item()) for l in losses]
        return (loss_vals, metrics) if metrics else loss_vals

    @no_grad()
    def predict_batch(self, inputs):
        self.network.eval()
        inputs = self._as_list(inputs)
        outputs = self.network(*inputs)
        return [o.numpy() if isinstance(o, Tensor) else o
                for o in self._as_list(outputs)]

    # -- loops -------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None, accumulate_grad_batches=1, num_iters=None,
            handle_preemption=False):
        loader = self._as_loader(train_data, batch_size, shuffle, drop_last,
                                 num_workers)
        eval_loader = self._as_loader(eval_data, batch_size, False, False,
                                      num_workers) if eval_data is not None \
            else None
        cbs = CallbackList(callbacks)
        for cb in cbs.callbacks:
            cb.set_model(self)
            cb.set_params({"epochs": epochs, "batch_size": batch_size,
                           "verbose": verbose, "metrics": [
                               m.name() for m in self._metrics]})
        history = {"loss": []}
        self.stop_training = False
        it = 0
        # opt-in preemption contract (fleet/elastic exit-101 protocol):
        # SIGTERM/SIGINT finishes the current batch, saves a final
        # checkpoint into save_dir, and exits RELAUNCH_EXIT_CODE so an
        # elastic supervisor respawns the job for free
        preempt = None
        if handle_preemption:
            from ..distributed.fault_tolerance import PreemptionHandler
            preempt = PreemptionHandler()
        cbs.on_train_begin()
        try:
            self._fit_loop(loader, eval_loader, epochs, eval_freq,
                           save_dir, save_freq, verbose, log_freq,
                           accumulate_grad_batches, num_iters, history,
                           cbs, preempt)
        finally:
            if preempt is not None:
                preempt.uninstall()
            cbs.on_train_end({"loss": history["loss"][-1]
                              if history["loss"] else None})
        return history

    def _metric_logs(self):
        logs = {}
        for m in self._metrics:
            name, val = m.name(), m.accumulate()
            if isinstance(name, (list, tuple)):  # multi-topk Accuracy
                vals = val if isinstance(val, (list, tuple)) \
                    else [val] * len(name)
                logs.update(dict(zip(name, vals)))
            else:
                logs[name] = val
        return logs

    def _preempt_exit(self, preempt, save_dir, verbose):
        """Final synchronous checkpoint, then exit 101 for relaunch."""
        if save_dir is not None:
            self.save(f"{save_dir}/preempted")
        if verbose:
            print("preemption: checkpoint saved, exiting for relaunch",
                  flush=True)
        preempt.exit_for_relaunch()  # raises SystemExit(101)

    def _fit_loop(self, loader, eval_loader, epochs, eval_freq, save_dir,
                  save_freq, verbose, log_freq, accumulate_grad_batches,
                  num_iters, history, cbs, preempt=None):
        it = 0
        for epoch in range(epochs):
            for m in self._metrics:
                m.reset()
            cbs.on_epoch_begin(epoch)
            t0 = time.time()
            epoch_losses = []
            for step, batch in enumerate(loader):
                cbs.on_train_batch_begin(step)
                ins, labs = self._split_batch(batch)
                res = self.train_batch(ins, labs,
                                       update=(step + 1)
                                       % accumulate_grad_batches == 0)
                loss_vals = res[0] if isinstance(res, tuple) else res
                epoch_losses.append(loss_vals[0])
                it += 1
                logs = {"loss": float(loss_vals[0])}
                logs.update(self._metric_logs())
                cbs.on_train_batch_end(step, logs)
                if preempt is not None and preempt.requested():
                    self._preempt_exit(preempt, save_dir, verbose)
                if verbose and step % log_freq == 0:
                    msg = (f"Epoch {epoch + 1}/{epochs} step {step} "
                           f"loss: {loss_vals[0]:.4f}")
                    for m in self._metrics:
                        msg += f" {m.name()}: {self._fmt(m.accumulate())}"
                    print(msg, flush=True)
                if num_iters is not None and it >= num_iters:
                    break
            if hasattr(self._optimizer, "_lr") and hasattr(
                    self._optimizer._lr, "step"):
                self._optimizer._lr.step()
            history["loss"].append(float(np.mean(epoch_losses)))
            epoch_logs = {"loss": history["loss"][-1]}
            epoch_logs.update(self._metric_logs())
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_res = self.evaluate(eval_loader, verbose=verbose)
                if isinstance(eval_res, dict):
                    epoch_logs.update({
                        f"eval_{k}": (v[0] if isinstance(v, (list, tuple))
                                      and len(v) == 1 else v)
                        for k, v in eval_res.items()})
            cbs.on_epoch_end(epoch, epoch_logs)
            if save_dir is not None and (epoch + 1) % save_freq == 0:
                self.save(f"{save_dir}/epoch_{epoch}")
            if verbose:
                print(f"Epoch {epoch + 1} done in {time.time() - t0:.1f}s "
                      f"mean loss {history['loss'][-1]:.4f}", flush=True)
            if self.stop_training:  # EarlyStopping contract
                break
            if num_iters is not None and it >= num_iters:
                break

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        loader = self._as_loader(eval_data, batch_size, False, False,
                                 num_workers)
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            ins, labs = self._split_batch(batch)
            res = self.eval_batch(ins, labs)
            loss_vals = res[0] if isinstance(res, tuple) else res
            losses.append(loss_vals[0])
        out = {"loss": [float(np.mean(losses))] if losses else [0.0]}
        for m in self._metrics:
            out[m.name() if isinstance(m.name(), str) else
                m.name()[0]] = m.accumulate()
        if verbose:
            print("Eval " + " ".join(f"{k}: {v}" for k, v in out.items()),
                  flush=True)
        return out

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = self._as_loader(test_data, batch_size, False, False,
                                 num_workers)
        outputs = []
        for batch in loader:
            ins, _ = self._split_batch(batch)
            outputs.append(self.predict_batch(ins))
        if stack_outputs and outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs])
                    for i in range(n_out)]
        return outputs

    # -- persistence -------------------------------------------------------
    def save(self, path, training=True):
        from ..framework.io import save as fsave
        fsave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fsave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as fload
        self.network.set_state_dict(fload(path + ".pdparams"))
        if not reset_optimizer and self._optimizer is not None:
            import os
            if os.path.exists(path + ".pdopt"):
                self._optimizer.set_state_dict(fload(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        return summary(self.network, input_size, dtype)

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _as_list(x):
        if x is None:
            return []
        return list(x) if isinstance(x, (list, tuple)) else [x]

    @staticmethod
    def _fmt(v):
        if isinstance(v, (list, tuple)):
            return "/".join(f"{x:.4f}" for x in v)
        return f"{v:.4f}"

    def _as_loader(self, data, batch_size, shuffle, drop_last, num_workers):
        from ..io import DataLoader, Dataset
        if data is None:
            return None
        if isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              drop_last=drop_last, num_workers=num_workers)
        return data  # assume iterable of batches

    def _split_batch(self, batch):
        if isinstance(batch, (list, tuple)):
            if len(batch) >= 2:
                return self._to_tensors(batch[:-1]), \
                    self._to_tensors([batch[-1]])
            return self._to_tensors(batch), []
        return self._to_tensors([batch]), []

    @staticmethod
    def _to_tensors(items):
        out = []
        for x in items:
            if isinstance(x, Tensor):
                out.append(x)
            else:
                out.append(to_tensor(np.asarray(x)))
        return out

    def _compute_loss(self, outputs, labels):
        outs = self._as_list(outputs)
        if self._loss is None:
            return [outs[0]]
        loss = self._loss(*(outs + labels))
        return self._as_list(loss)

    def _update_metrics(self, outputs, labels):
        outs = self._as_list(outputs)
        res = []
        for m in self._metrics:
            state = m.compute(*(outs + labels))
            r = m.update(*(state if isinstance(state, (list, tuple))
                           else [state]))
            res.append(r)
        return res


def summary(net, input_size=None, dtypes=None, input=None):  # noqa: A002
    """paddle.summary parity — parameter table + count."""
    lines = []
    total = 0
    trainable = 0
    for name, p in net.named_parameters():
        n = int(np.prod(p.shape)) if p.shape else 1
        total += n
        if not p.stop_gradient:
            trainable += n
        lines.append(f"{name:60s} {str(p.shape):24s} {n:>12,d}")
    report = "\n".join(lines)
    report += (f"\nTotal params: {total:,}\nTrainable params: {trainable:,}"
               f"\nNon-trainable params: {total - trainable:,}")
    print(report, flush=True)
    return {"total_params": total, "trainable_params": trainable}
