"""Callbacks (reference: python/paddle/hapi/callbacks.py)."""
from __future__ import annotations


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0 and logs:
            print(f"step {step}: " + " ".join(
                f"{k}={v}" for k, v in logs.items()), flush=True)


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model and self.save_dir and epoch % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = min_delta
        self.wait = 0
        self.best = None
        self.mode = "min" if mode in ("auto", "min") else "max"

    def on_epoch_end(self, epoch, logs=None):
        if not logs or self.monitor not in logs:
            return
        cur = logs[self.monitor]
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        improved = (self.best is None
                    or (self.mode == "min" and cur < self.best - self.min_delta)
                    or (self.mode == "max" and cur > self.best + self.min_delta))
        if improved:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience and self.model:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch
