"""Callbacks (reference: python/paddle/hapi/callbacks.py)."""
from __future__ import annotations


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0 and logs:
            print(f"step {step}: " + " ".join(
                f"{k}={v}" for k, v in logs.items()), flush=True)


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model and self.save_dir and epoch % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = min_delta
        self.wait = 0
        self.best = None
        self.mode = "min" if mode in ("auto", "min") else "max"

    def on_epoch_end(self, epoch, logs=None):
        if not logs or self.monitor not in logs:
            return
        cur = logs[self.monitor]
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        improved = (self.best is None
                    or (self.mode == "min" and cur < self.best - self.min_delta)
                    or (self.mode == "max" and cur > self.best + self.min_delta))
        if improved:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience and self.model:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch


class ScalarWriter:
    """Append-only JSONL scalar log — the VisualDL LogWriter analog
    (reference: VisualDLCallback in python/paddle/hapi/callbacks.py:772
    writing via visualdl.LogWriter). JSONL instead of the VisualDL
    protobuf format: no service dependency, trivially consumed by pandas
    or a TensorBoard converter."""

    def __init__(self, logdir):
        import os
        os.makedirs(logdir, exist_ok=True)
        self._path = os.path.join(logdir, "scalars.jsonl")
        self._f = open(self._path, "a", buffering=1)

    def add_scalar(self, tag, value, step):
        import json
        self._f.write(json.dumps(
            {"tag": tag, "value": float(value), "step": int(step)}) + "\n")

    def close(self):
        self._f.close()


class ScalarLogger(Callback):
    """Per-step scalar + metrics-registry JSONL logger (the VisualDL
    `LogWriter` analogue for headless runs). Each record is one JSON
    object per line in ``<run_dir>/scalars.jsonl``:

        {"step": 7, "scalars": {"loss": 1.93, ...},
         "metrics": {...metrics.snapshot()...}}

    The ``metrics`` field is included when ``FLAGS_tpu_metrics`` is on
    (and ``with_metrics`` isn't False), so one file carries the loss
    curve AND the numerics telemetry (grad norms, loss scale, step
    latencies) — trivially consumed by pandas/jq or re-emitted to
    TensorBoard. Usable two ways: as a hapi callback (Model.fit), or
    directly from a manual loop via ``logger.log(step, loss=...)``.
    """

    def __init__(self, run_dir, log_freq=1, with_metrics=True):
        super().__init__()
        import os
        self.run_dir = run_dir
        self.log_freq = max(int(log_freq), 1)
        self.with_metrics = with_metrics
        os.makedirs(run_dir, exist_ok=True)
        self.path = os.path.join(run_dir, "scalars.jsonl")
        self._f = None
        self._step = 0

    def _file(self):
        if self._f is None:
            self._f = open(self.path, "a", buffering=1)
        return self._f

    def log(self, step, **scalars):
        """Append one record; non-numeric scalars are dropped."""
        import json
        clean = {}
        for k, v in scalars.items():
            if isinstance(v, (list, tuple)) and len(v) == 1:
                v = v[0]
            try:
                clean[k] = float(v)
            except (TypeError, ValueError):
                pass
        record = {"step": int(step), "scalars": clean}
        if self.with_metrics:
            from ..profiler import metrics as _metrics
            if _metrics.enabled():
                record["metrics"] = _metrics.snapshot()
        self._file().write(json.dumps(record) + "\n")

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        if self._step % self.log_freq == 0:
            self.log(self._step, **(logs or {}))

    def on_train_end(self, logs=None):
        self.close()

    def close(self):
        if self._f is not None:
            self._f.close()
            self._f = None


class VisualDL(Callback):
    """Scalar-logging callback (reference callbacks.py:772 VisualDL):
    records per-step train metrics and per-epoch eval metrics through
    ScalarWriter, plus device memory stats when the backend exposes
    them."""

    def __init__(self, log_dir="vdl_log"):
        super().__init__()
        self.log_dir = log_dir
        self._writer = None
        self._step = 0

    def _w(self):
        if self._writer is None:
            self._writer = ScalarWriter(self.log_dir)
        return self._writer

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        for k, v in (logs or {}).items():
            try:
                self._w().add_scalar(f"train/{k}", float(v), self._step)
            except (TypeError, ValueError):
                pass
        try:
            import jax
            stats = jax.devices()[0].memory_stats() or {}
            if "bytes_in_use" in stats:
                self._w().add_scalar("sys/bytes_in_use",
                                     stats["bytes_in_use"], self._step)
        # genuinely best-effort: not every PJRT backend implements
        # memory_stats, and a telemetry miss must never fail a train step
        except Exception:  # tpu-lint: disable=except-pass
            pass

    def on_epoch_end(self, epoch, logs=None):
        # epoch logs mix TRAIN epoch means with eval_* results; keep the
        # namespaces separate so eval curves really are eval
        for k, v in (logs or {}).items():
            if isinstance(v, (list, tuple)) and len(v) == 1:
                v = v[0]
            tag = (f"eval/{k[5:]}" if k.startswith("eval_")
                   else f"train_epoch/{k}")
            try:
                self._w().add_scalar(tag, float(v), epoch)
            except (TypeError, ValueError):
                pass

    def on_train_end(self, logs=None):
        if self._writer is not None:
            self._writer.close()
            self._writer = None
