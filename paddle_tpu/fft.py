"""paddle.fft parity (reference: python/paddle/fft.py) over jnp.fft."""
from __future__ import annotations

import jax.numpy as jnp

from .core.tensor import apply_op
from .ops.registry import _ensure_tensor

__all__ = ["fft", "ifft", "fft2", "ifft2", "fftn", "ifftn", "rfft", "irfft",
           "rfft2", "irfft2", "rfftn", "irfftn", "hfft", "ihfft",
           "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def _fft1(name, jfn):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        x = _ensure_tensor(x)
        return apply_op(lambda a: jfn(a, n=n, axis=axis, norm=norm), x,
                        op_name=op.__name__)
    op.__name__ = name
    return op


def _fftn(name, jfn):
    def op(x, s=None, axes=None, norm="backward", name=None):
        x = _ensure_tensor(x)
        return apply_op(lambda a: jfn(a, s=s, axes=axes, norm=norm), x,
                        op_name=op.__name__)
    op.__name__ = name
    return op


fft = _fft1("fft", jnp.fft.fft)
ifft = _fft1("ifft", jnp.fft.ifft)
rfft = _fft1("rfft", jnp.fft.rfft)
irfft = _fft1("irfft", jnp.fft.irfft)
hfft = _fft1("hfft", jnp.fft.hfft)
ihfft = _fft1("ihfft", jnp.fft.ihfft)
fftn = _fftn("fftn", jnp.fft.fftn)
ifftn = _fftn("ifftn", jnp.fft.ifftn)
rfftn = _fftn("rfftn", jnp.fft.rfftn)
irfftn = _fftn("irfftn", jnp.fft.irfftn)


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return fftn(x, s, axes, norm)


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ifftn(x, s, axes, norm)


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return rfftn(x, s, axes, norm)


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return irfftn(x, s, axes, norm)


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor
    return Tensor(jnp.fft.fftfreq(n, d))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor
    return Tensor(jnp.fft.rfftfreq(n, d))


def fftshift(x, axes=None, name=None):
    x = _ensure_tensor(x)
    return apply_op(lambda a: jnp.fft.fftshift(a, axes=axes), x,
                    op_name="fftshift")


def ifftshift(x, axes=None, name=None):
    x = _ensure_tensor(x)
    return apply_op(lambda a: jnp.fft.ifftshift(a, axes=axes), x,
                    op_name="ifftshift")
