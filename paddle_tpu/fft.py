"""paddle.fft parity (reference: python/paddle/fft.py) over jnp.fft."""
from __future__ import annotations

import jax.numpy as jnp

from .core.tensor import apply_op
from .ops.registry import _ensure_tensor

__all__ = ["fft", "ifft", "fft2", "ifft2", "fftn", "ifftn", "rfft", "irfft",
           "rfft2", "irfft2", "rfftn", "irfftn", "hfft", "ihfft",
           "hfft2", "ihfft2", "hfftn", "ihfftn",
           "fftfreq", "rfftfreq", "fftshift", "ifftshift",
           "fft_c2c", "fft_r2c", "fft_c2r",
           "fftn_c2c", "fftn_r2c", "fftn_c2r"]


def _fft1(name, jfn):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        x = _ensure_tensor(x)
        return apply_op(lambda a: jfn(a, n=n, axis=axis, norm=norm), x,
                        op_name=op.__name__)
    op.__name__ = name
    return op


def _fftn(name, jfn):
    def op(x, s=None, axes=None, norm="backward", name=None):
        x = _ensure_tensor(x)
        return apply_op(lambda a: jfn(a, s=s, axes=axes, norm=norm), x,
                        op_name=op.__name__)
    op.__name__ = name
    return op


fft = _fft1("fft", jnp.fft.fft)
ifft = _fft1("ifft", jnp.fft.ifft)
rfft = _fft1("rfft", jnp.fft.rfft)
irfft = _fft1("irfft", jnp.fft.irfft)
hfft = _fft1("hfft", jnp.fft.hfft)
ihfft = _fft1("ihfft", jnp.fft.ihfft)
fftn = _fftn("fftn", jnp.fft.fftn)
ifftn = _fftn("ifftn", jnp.fft.ifftn)
rfftn = _fftn("rfftn", jnp.fft.rfftn)
irfftn = _fftn("irfftn", jnp.fft.irfftn)


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return fftn(x, s, axes, norm)


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ifftn(x, s, axes, norm)


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return rfftn(x, s, axes, norm)


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return irfftn(x, s, axes, norm)


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor
    return Tensor(jnp.fft.fftfreq(n, d))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .core.tensor import Tensor
    return Tensor(jnp.fft.rfftfreq(n, d))


def _swap_norm(norm):
    # the Hermitian transforms are conjugate-flipped real transforms with
    # forward/backward normalization exchanged (numpy hfft identity:
    # hfft(a, n) == irfft(conj(a), n) * n  for norm="backward")
    return {None: "forward", "backward": "forward",
            "forward": "backward", "ortho": "ortho"}[norm]


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    """N-D FFT of Hermitian-symmetric input → real output
    (reference: python/paddle/fft.py:782 hfftn → fftn_c2r kernel)."""
    x = _ensure_tensor(x)
    return apply_op(
        lambda a: jnp.fft.irfftn(jnp.conj(a), s=s, axes=axes,
                                 norm=_swap_norm(norm)),
        x, op_name="hfftn")


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    """N-D inverse FFT of a real-spectrum signal → Hermitian output
    (reference: python/paddle/fft.py:831 ihfftn → fftn_r2c kernel)."""
    x = _ensure_tensor(x)
    return apply_op(
        lambda a: jnp.conj(jnp.fft.rfftn(a, s=s, axes=axes,
                                         norm=_swap_norm(norm))),
        x, op_name="ihfftn")


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return hfftn(x, s, axes, norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ihfftn(x, s, axes, norm)


# low-level entry points (reference fft.py:1432-1660 — the kernel-shaped
# API paddle exposes publicly; forward=False runs the inverse transform)

def fft_c2c(x, n, axis, norm, forward, name=None):
    return (fft if forward else ifft)(x, n=n, axis=axis, norm=norm)


def fft_r2c(x, n, axis, norm, forward, onesided, name=None):
    if not onesided:
        return (fft if forward else ifft)(x, n=n, axis=axis, norm=norm)
    if forward:
        return rfft(x, n=n, axis=axis, norm=norm)
    return ihfft(x, n=n, axis=axis, norm=norm)


def fft_c2r(x, n, axis, norm, forward, name=None):
    if forward:
        return hfft(x, n=n, axis=axis, norm=norm)
    return irfft(x, n=n, axis=axis, norm=norm)


def fftn_c2c(x, s, axes, norm, forward, name=None):
    return (fftn if forward else ifftn)(x, s=s, axes=axes, norm=norm)


def fftn_r2c(x, s, axes, norm, forward, onesided, name=None):
    if not onesided:
        return (fftn if forward else ifftn)(x, s=s, axes=axes, norm=norm)
    if forward:
        return rfftn(x, s=s, axes=axes, norm=norm)
    return ihfftn(x, s=s, axes=axes, norm=norm)


def fftn_c2r(x, s, axes, norm, forward, name=None):
    if forward:
        return hfftn(x, s=s, axes=axes, norm=norm)
    return irfftn(x, s=s, axes=axes, norm=norm)


def fftshift(x, axes=None, name=None):
    x = _ensure_tensor(x)
    return apply_op(lambda a: jnp.fft.fftshift(a, axes=axes), x,
                    op_name="fftshift")


def ifftshift(x, axes=None, name=None):
    x = _ensure_tensor(x)
    return apply_op(lambda a: jnp.fft.ifftshift(a, axes=axes), x,
                    op_name="ifftshift")
