"""Wave-file IO (reference: python/paddle/audio/backends/backend.py
load/save/info over soundfile).

Implemented on the stdlib `wave` module (16-bit PCM) so the API works in
hermetic environments; returns numpy arrays shaped [channels, frames]
like the reference with `channels_first=True`."""
from __future__ import annotations

import wave
from collections import namedtuple

import numpy as np

__all__ = ["AudioInfo", "info", "load", "save"]

AudioInfo = namedtuple(
    "AudioInfo",
    ["sample_rate", "num_samples", "num_channels", "bits_per_sample",
     "encoding"])


def info(filepath: str) -> AudioInfo:
    with wave.open(filepath, "rb") as w:
        return AudioInfo(w.getframerate(), w.getnframes(),
                         w.getnchannels(), w.getsampwidth() * 8,
                         "PCM_S")


def load(filepath: str, frame_offset=0, num_frames=-1,
         normalize=True, channels_first=True):
    """Returns (data, sample_rate); data float32 in [-1, 1] when
    `normalize` else int16."""
    with wave.open(filepath, "rb") as w:
        if w.getsampwidth() != 2:
            raise ValueError(
                f"only 16-bit PCM wav is supported, got "
                f"{w.getsampwidth() * 8}-bit: {filepath!r}")
        sr = w.getframerate()
        nch = w.getnchannels()
        total = w.getnframes()
        frame_offset = min(frame_offset, total)
        w.setpos(frame_offset)
        remaining = total - frame_offset
        n = remaining if num_frames < 0 else min(num_frames, remaining)
        raw = w.readframes(n)
    data = np.frombuffer(raw, dtype=np.int16).reshape(-1, nch)
    if normalize:
        data = (data.astype(np.float32) / 32768.0)
    if channels_first:
        data = data.T
    return data, sr


def save(filepath: str, src, sample_rate: int, channels_first=True,
         bits_per_sample=16):
    assert bits_per_sample == 16, "only 16-bit PCM supported"
    data = np.asarray(src)
    if channels_first:
        data = data.T  # -> [frames, channels]
    if data.dtype != np.int16:
        data = np.clip(data, -1.0, 1.0)
        data = (data * 32767.0).astype(np.int16)
    with wave.open(filepath, "wb") as w:
        w.setnchannels(data.shape[1] if data.ndim > 1 else 1)
        w.setsampwidth(2)
        w.setframerate(sample_rate)
        w.writeframes(data.tobytes())
