"""Audio feature layers (reference:
python/paddle/audio/features/layers.py — Spectrogram:25,
MelSpectrogram:107, LogMelSpectrogram:207, MFCC:310)."""
from __future__ import annotations

import jax.numpy as jnp

from .. import nn, signal
from ..core.tensor import Tensor, apply_op
from . import functional as F

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(nn.Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True,
                 pad_mode="reflect", dtype="float32"):
        super().__init__()
        assert power > 0, "power must be positive"
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.fft_window = Tensor(
            F.get_window(window, self.win_length, fftbins=True,
                         dtype=dtype))

    def forward(self, x):
        spec = signal.stft(x, self.n_fft, self.hop_length, self.win_length,
                           window=self.fft_window, center=self.center,
                           pad_mode=self.pad_mode)
        power = self.power
        return apply_op(
            lambda s: jnp.abs(s) ** power, spec, op_name="spec_power")


class MelSpectrogram(nn.Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", dtype="float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                        window, power, center, pad_mode,
                                        dtype)
        self.n_mels = n_mels
        self.fbank_matrix = Tensor(F.compute_fbank_matrix(
            sr=sr, n_fft=n_fft, n_mels=n_mels, f_min=f_min, f_max=f_max,
            htk=htk, norm=norm, dtype=dtype))

    def forward(self, x):
        spec = self._spectrogram(x)  # [..., freq, frames]
        return apply_op(
            lambda fb, s: jnp.einsum("mf,...ft->...mt", fb, s),
            self.fbank_matrix, spec, op_name="mel_project")


class LogMelSpectrogram(nn.Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self._melspectrogram(x)
        return apply_op(
            lambda m: F.power_to_db(m, self.ref_value, self.amin,
                                    self.top_db),
            mel, op_name="power_to_db")


class MFCC(nn.Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        assert n_mfcc <= n_mels, "n_mfcc cannot be larger than n_mels"
        self._log_melspectrogram = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db, dtype)
        self.dct_matrix = Tensor(F.create_dct(n_mfcc, n_mels, dtype=dtype))

    def forward(self, x):
        logmel = self._log_melspectrogram(x)
        return apply_op(
            lambda d, m: jnp.einsum("mk,...mt->...kt", d, m),
            self.dct_matrix, logmel, op_name="dct_project")
