"""paddle.audio parity (reference: python/paddle/audio/__init__.py).

functional (mel/fft frequency math, filterbanks, windows), features
(Spectrogram/MelSpectrogram/LogMelSpectrogram/MFCC layers), and wav-file
backends (stdlib `wave`-based load/save/info — the reference shells out to
soundfile, unavailable here)."""
from . import functional  # noqa: F401
from . import features  # noqa: F401
from .backends import info, load, save  # noqa: F401

__all__ = ["functional", "features", "backends", "load", "info", "save"]
