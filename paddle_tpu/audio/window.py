"""Window functions (reference: python/paddle/audio/functional/window.py
`get_window`)."""
from __future__ import annotations

import math

import jax.numpy as jnp

__all__ = ["get_window"]


def _extend(needs_trunc, win_length):
    return (win_length + 1, True) if needs_trunc else (win_length, False)


def _truncate(w, needs_trunc):
    return w[:-1] if needs_trunc else w


def _cosine_sum(coeffs, M, sym):
    M_ext, trunc = _extend(not sym, M)
    n = jnp.arange(M_ext, dtype=jnp.float32)
    w = jnp.zeros(M_ext, jnp.float32)
    for i, a in enumerate(coeffs):
        w = w + a * jnp.cos(2 * math.pi * i * n / (M_ext - 1))
    return _truncate(w, trunc)


def get_window(window, win_length, fftbins=True, dtype="float32"):
    """'hann'|'hamming'|'blackman'|'bartlett'|'bohman'|'cosine'|
    ('gaussian', std)|('exponential', center, tau)|('kaiser', beta)|
    ('tukey', alpha) — reference window.py:get_window."""
    sym = not fftbins
    if isinstance(window, (tuple, list)):
        name, *args = window
    else:
        name, args = window, []

    if name == "hann":
        w = _cosine_sum([0.5, -0.5], win_length, sym)
    elif name == "hamming":
        w = _cosine_sum([0.54, -0.46], win_length, sym)
    elif name == "blackman":
        w = _cosine_sum([0.42, -0.5, 0.08], win_length, sym)
    elif name == "bartlett":
        M, trunc = _extend(not sym, win_length)
        n = jnp.arange(M, dtype=jnp.float32)
        w = _truncate(1.0 - jnp.abs(2 * n / (M - 1) - 1.0), trunc)
    elif name == "bohman":
        M, trunc = _extend(not sym, win_length)
        x = jnp.abs(jnp.linspace(-1, 1, M))
        w = (1 - x) * jnp.cos(math.pi * x) + jnp.sin(math.pi * x) / math.pi
        w = _truncate(w.at[0].set(0.0).at[-1].set(0.0), trunc)
    elif name == "cosine":
        M, trunc = _extend(not sym, win_length)
        n = jnp.arange(M, dtype=jnp.float32)
        w = _truncate(jnp.sin(math.pi / M * (n + 0.5)), trunc)
    elif name == "gaussian":
        std = args[0] if args else 7.0
        M, trunc = _extend(not sym, win_length)
        n = jnp.arange(M, dtype=jnp.float32) - (M - 1) / 2
        w = _truncate(jnp.exp(-0.5 * (n / std) ** 2), trunc)
    elif name == "exponential":
        center = args[0] if len(args) > 0 and args[0] is not None else None
        tau = args[1] if len(args) > 1 else 1.0
        M, trunc = _extend(not sym, win_length)
        if center is None:
            center = (M - 1) / 2
        n = jnp.arange(M, dtype=jnp.float32)
        w = _truncate(jnp.exp(-jnp.abs(n - center) / tau), trunc)
    elif name == "kaiser":
        beta = args[0] if args else 12.0
        M, trunc = _extend(not sym, win_length)
        n = jnp.arange(M, dtype=jnp.float32)
        alpha = (M - 1) / 2.0
        import jax.scipy.special as jss  # i0 via jax
        w = _truncate(jss.i0(beta * jnp.sqrt(
            jnp.clip(1 - ((n - alpha) / alpha) ** 2, 0, 1))) / jss.i0(
                jnp.asarray(beta)), trunc)
    elif name == "tukey":
        alpha = args[0] if args else 0.5
        M, trunc = _extend(not sym, win_length)
        n = jnp.arange(M, dtype=jnp.float32)
        width = alpha * (M - 1) / 2.0
        w = jnp.ones(M, jnp.float32)
        left = n < width
        right = n > (M - 1) - width
        w = jnp.where(left, 0.5 * (1 + jnp.cos(
            math.pi * (n / width - 1))), w)
        w = jnp.where(right, 0.5 * (1 + jnp.cos(
            math.pi * ((n - (M - 1)) / width + 1))), w)
        w = _truncate(w, trunc)
    else:
        raise ValueError(f"unsupported window: {window!r}")
    return w.astype(dtype)
