"""paddle.audio.functional parity (reference:
python/paddle/audio/functional/functional.py).

Pure jnp implementations — filterbank construction is host-side-cacheable
constant math; the per-batch transforms (stft/mel projection) are dense
matmuls that XLA maps onto the MXU."""
from __future__ import annotations

import math

import jax.numpy as jnp

from .window import get_window  # noqa: F401  (re-exported)

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct",
           "get_window"]


def hz_to_mel(freq, htk=False):
    """reference: functional.py:22."""
    scalar = not hasattr(freq, "ndim")
    f = jnp.asarray(freq, jnp.float32)
    if htk:
        mel = 2595.0 * jnp.log10(1.0 + f / 700.0)
        return float(mel) if scalar else mel
    f_min, f_sp = 0.0, 200.0 / 3
    mel = (f - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    mel = jnp.where(f >= min_log_hz,
                    min_log_mel + jnp.log(f / min_log_hz) / logstep, mel)
    return float(mel) if scalar else mel


def mel_to_hz(mel, htk=False):
    """reference: functional.py:78."""
    scalar = not hasattr(mel, "ndim")
    m = jnp.asarray(mel, jnp.float32)
    if htk:
        hz = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
        return float(hz) if scalar else hz
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * m
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    freqs = jnp.where(m >= min_log_mel,
                      min_log_hz * jnp.exp(logstep * (m - min_log_mel)),
                      freqs)
    return float(freqs) if scalar else freqs


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                    dtype="float32"):
    """reference: functional.py:123."""
    low = hz_to_mel(f_min, htk)
    high = hz_to_mel(f_max, htk)
    mels = jnp.linspace(low, high, n_mels)
    return mel_to_hz(mels, htk).astype(dtype)


def fft_frequencies(sr, n_fft, dtype="float32"):
    """reference: functional.py:163."""
    return jnp.linspace(0.0, sr / 2.0, 1 + n_fft // 2).astype(dtype)


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """Triangular mel filterbank [n_mels, 1 + n_fft//2]
    (reference: functional.py:186)."""
    if f_max is None:
        f_max = sr / 2.0
    fftfreqs = fft_frequencies(sr, n_fft)
    melfreqs = mel_frequencies(n_mels + 2, f_min, f_max, htk)
    fdiff = jnp.diff(melfreqs)
    ramps = melfreqs[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0.0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (melfreqs[2:n_mels + 2] - melfreqs[:n_mels])
        weights = weights * enorm[:, None]
    return weights.astype(dtype)


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    """reference: functional.py:259."""
    if amin <= 0:
        raise ValueError("amin must be strictly positive")
    if ref_value <= 0:
        raise ValueError("ref_value must be strictly positive")
    x = jnp.asarray(spect)
    log_spec = 10.0 * jnp.log10(jnp.maximum(amin, x))
    log_spec = log_spec - 10.0 * math.log10(max(amin, ref_value))
    if top_db is not None:
        if top_db < 0:
            raise ValueError("top_db must be non-negative")
        log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
    return log_spec


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """DCT-II basis [n_mels, n_mfcc] (reference: functional.py:303)."""
    n = jnp.arange(n_mels, dtype=jnp.float32)
    k = jnp.arange(n_mfcc, dtype=jnp.float32)[None, :]
    dct = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm is None:
        dct = dct * 2.0
    else:
        assert norm == "ortho"
        dct = dct * jnp.where(k == 0, math.sqrt(1.0 / n_mels),
                              math.sqrt(2.0 / n_mels))
    return dct.astype(dtype)
