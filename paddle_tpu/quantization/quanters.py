"""Quanters and observers.

Reference analog: python/paddle/quantization/base_quanter.py:25
(BaseQuanter), quanters/abs_max.py:25/:94 (FakeQuanterWithAbsMaxObserver
factory + layer), imperative/ptq_quantizer.py (the PTQ observer family).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op
from ..nn.layer.layers import Layer
from .functional import fake_quant_dequant

__all__ = ["BaseQuanter", "quanter", "QuanterFactory",
           "FakeQuanterWithAbsMaxObserver",
           "FakeQuanterWithAbsMaxObserverLayer",
           "AbsmaxObserver", "MovingAverageAbsmaxObserver", "KLObserver"]


class BaseQuanter(Layer):
    """reference: base_quanter.py:25 — abstract fake-quant layer exposing
    scales/zero_points/bit_length/quant_axis for export."""

    def scales(self):
        raise NotImplementedError

    def zero_points(self):  # symmetric schemes: always zero
        return None

    @property
    def bit_length(self):
        return getattr(self, "_bits", 8)

    @property
    def quant_axis(self):
        return getattr(self, "_quant_axis", None)


class QuanterFactory:
    """reference: factory.py:52 — holds (cls, args) and instantiates per
    wrapped layer; lets QuantConfig carry configured-but-unbuilt quanters."""

    def __init__(self, cls, *args, **kwargs):
        self.cls = cls
        self.args = args
        self.kwargs = kwargs

    def _instance(self, layer=None):
        return self.cls(*self.args, **self.kwargs)


def quanter(class_name):
    """reference: factory.py:73 — decorator declaring a factory alias for a
    quanter layer class; the factory lands in this module's namespace."""
    def wrap(cls):
        def make(*args, **kwargs):
            return QuanterFactory(cls, *args, **kwargs)
        make.__name__ = class_name
        globals()[class_name] = make
        return cls
    return wrap


class FakeQuanterWithAbsMaxObserverLayer(BaseQuanter):
    """Moving-average absmax fake quanter
    (reference: quanters/abs_max.py:94)."""

    def __init__(self, layer=None, moving_rate=0.9, bit_length=8,
                 quant_axis=None, dtype="float32", name=None):
        super().__init__()
        self._moving_rate = moving_rate
        self._bits = bit_length
        self._quant_axis = quant_axis
        self.register_buffer("_scale", Tensor(jnp.ones([], jnp.float32)))
        self.register_buffer("_state", Tensor(jnp.ones([], jnp.float32)))
        self.register_buffer("_accum", Tensor(jnp.ones([], jnp.float32)))
        # flips on the first training-mode observation; the int8 freeze
        # refuses quanters that never saw data (scale would be the
        # meaningless init of 1.0). A BUFFER so it survives the
        # state_dict roundtrip — a QAT model restored from checkpoint
        # must still be freezable.
        self.register_buffer("_seen_data",
                             Tensor(jnp.zeros([], jnp.float32)))

    @property
    def _updated(self) -> bool:
        return bool(float(np.asarray(self._seen_data._array)) > 0)

    def _absmax(self, arr):
        if self._quant_axis is None:
            return jnp.max(jnp.abs(arr)).astype(jnp.float32)
        axes = tuple(i for i in range(arr.ndim) if i != self._quant_axis)
        return jnp.max(jnp.abs(arr), axis=axes).astype(jnp.float32)

    def forward(self, x):
        if self.training:
            self._seen_data._array = jnp.ones([], jnp.float32)
            absmax = self._absmax(x._array)
            if self._scale._array.shape != absmax.shape:
                # first per-channel observation: grow the scalar buffers
                self._state._array = jnp.ones_like(absmax)
                self._accum._array = jnp.ones_like(absmax)
            r = self._moving_rate
            state = self._state._array * r + 1.0
            accum = self._accum._array * r + absmax
            self._state._array = state
            self._accum._array = accum
            self._scale._array = accum / state
        return apply_op(fake_quant_dequant, x, self._scale._array,
                        op_name="fake_quant", bits=self._bits,
                        quant_axis=self._quant_axis)

    def scales(self):
        return Tensor(self._scale._array)


# the reference's public factory name
@quanter("FakeQuanterWithAbsMaxObserver")
class _FQAbsMax(FakeQuanterWithAbsMaxObserverLayer):
    pass


class AbsmaxObserver(BaseQuanter):
    """PTQ collector: tracks the max |x| seen; forward is identity
    (reference: imperative/ptq_quantizer.py AbsmaxQuantizer)."""

    def __init__(self, bit_length=8, quant_axis=None):
        super().__init__()
        self._bits = bit_length
        self._quant_axis = quant_axis
        self.register_buffer("_scale", Tensor(jnp.zeros([], jnp.float32)))

    def forward(self, x):
        if self._quant_axis is None:
            absmax = jnp.max(jnp.abs(x._array)).astype(jnp.float32)
        else:
            axes = tuple(i for i in range(x._array.ndim)
                         if i != self._quant_axis)
            absmax = jnp.max(jnp.abs(x._array), axis=axes).astype(
                jnp.float32)
            if self._scale._array.ndim == 0:
                self._scale._array = jnp.zeros_like(absmax)
        self._scale._array = jnp.maximum(self._scale._array, absmax)
        return x

    def scales(self):
        return Tensor(self._scale._array)


class KLObserver(BaseQuanter):
    """PTQ collector choosing the clip threshold by KL divergence
    (reference: imperative/ptq_quantizer.py KLQuantizer; the TensorRT
    entropy-calibration algorithm).

    Absmax calibration lets one outlier blow up the scale and waste the
    int8 range on values that never occur; KL picks the threshold T
    whose clipped-and-quantized distribution stays closest (min KL) to
    the observed one. Keeps a bounded reservoir sample of |x| across
    calibration batches; ``scales()`` runs an iterative range-shrinking
    entropy search once and caches the result.
    """

    _RESERVOIR = 200_000

    def __init__(self, bit_length=8, bins=2048):
        super().__init__()
        self._bits = bit_length
        self._bins = int(bins)
        self._samples = np.zeros(0, np.float32)  # reservoir of |x|
        self._seen = 0
        self._rng = np.random.default_rng(0)
        self.register_buffer("_scale", Tensor(jnp.zeros([], jnp.float32)))
        self._dirty = False

    def forward(self, x):
        a = np.abs(np.asarray(x._array, np.float32)).reshape(-1)
        self._seen += a.size
        # bounded reservoir: a single coarse histogram loses the bulk's
        # resolution when one outlier stretches the range; raw samples
        # let scales() iterate the range down (uniform via subsampling)
        if self._samples.size + a.size <= self._RESERVOIR:
            self._samples = np.concatenate([self._samples, a])
        else:
            keep = self._RESERVOIR - self._samples.size
            if keep > 0:
                self._samples = np.concatenate(
                    [self._samples,
                     self._rng.choice(a, size=keep, replace=False)])
            else:
                # replace a fraction proportional to the new batch
                n_rep = max(1, int(self._RESERVOIR * a.size
                                   / max(self._seen, 1)))
                n_rep = min(n_rep, a.size, self._RESERVOIR)
                idx = self._rng.choice(self._RESERVOIR, size=n_rep,
                                       replace=False)
                self._samples[idx] = self._rng.choice(
                    a, size=n_rep, replace=False)
        self._dirty = True
        return x

    def _kl_search(self, hist: np.ndarray, bin_w: float,
                   bins: int) -> float:
        """One entropy-calibration pass: for candidate bin counts i,
        clip the tail into bin i-1, quantize the head into 2^(bits-1)
        levels, keep the i minimizing KL(P || Q)."""
        n_levels = 2 ** (self._bits - 1)  # 128 magnitude levels
        best_i, best_kl = bins, np.inf
        for i in range(n_levels, bins + 1, 8):
            p = hist[:i].astype(np.float64).copy()
            p[i - 1] += hist[i:].sum()
            psum = p.sum()
            if psum == 0:
                continue
            p /= psum
            q = np.zeros(i, np.float64)
            for c in np.array_split(np.arange(i), n_levels):
                seg = hist[c]
                nz = seg > 0
                if nz.any():
                    q[c[nz]] = seg.sum() / nz.sum()
            qsum = q.sum()
            if qsum == 0:
                continue
            q /= qsum
            mask = p > 0
            kl = float(np.sum(p[mask] * np.log(
                p[mask] / np.maximum(q[mask], 1e-12))))
            if kl < best_kl:
                best_kl, best_i = kl, i
        return best_i * bin_w

    def _kl_threshold(self) -> float:
        s = self._samples
        if s.size == 0:
            return 0.0
        rng_hi = float(s.max())
        if rng_hi == 0.0:
            return 0.0
        # with few samples the sparse histogram makes KL noise-dominated
        # and over-aggressive; (a) size the bins to the sample count,
        # (b) never clip more than 0.01% of the observed mass (the
        # HistQuantizer-style percentile floor)
        bins = int(min(self._bins, max(256, s.size // 4)))
        floor = float(np.quantile(s, 1.0 - 1e-4))
        # iterate: each round histograms the CLIPPED samples over the
        # previous threshold, recovering bulk resolution an outlier-
        # stretched first range destroyed
        for _ in range(4):
            hist, _ = np.histogram(np.minimum(s, rng_hi),
                                   bins=bins, range=(0.0, rng_hi))
            t = self._kl_search(hist.astype(np.float64),
                                rng_hi / bins, bins)
            t = max(t, floor)
            if t >= rng_hi * 0.95:
                break
            rng_hi = t
        return max(rng_hi, floor)

    def scales(self):
        if self._dirty:
            self._scale._array = jnp.asarray(self._kl_threshold(),
                                             jnp.float32)
            self._dirty = False
        return Tensor(self._scale._array)


class MovingAverageAbsmaxObserver(BaseQuanter):
    """PTQ collector with EMA smoothing
    (reference: imperative/ptq_quantizer.py KLQuantizer-family sibling)."""

    def __init__(self, bit_length=8, moving_rate=0.9):
        super().__init__()
        self._bits = bit_length
        self._moving_rate = moving_rate
        self.register_buffer("_scale", Tensor(jnp.zeros([], jnp.float32)))
        self._seen = False

    def forward(self, x):
        absmax = jnp.max(jnp.abs(x._array)).astype(jnp.float32)
        if not self._seen:
            self._scale._array = absmax
            self._seen = True
        else:
            r = self._moving_rate
            self._scale._array = self._scale._array * r + absmax * (1 - r)
        return x

    def scales(self):
        return Tensor(self._scale._array)
