"""Quanters and observers.

Reference analog: python/paddle/quantization/base_quanter.py:25
(BaseQuanter), quanters/abs_max.py:25/:94 (FakeQuanterWithAbsMaxObserver
factory + layer), imperative/ptq_quantizer.py (the PTQ observer family).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op
from ..nn.layer.layers import Layer
from .functional import fake_quant_dequant

__all__ = ["BaseQuanter", "quanter", "QuanterFactory",
           "FakeQuanterWithAbsMaxObserver",
           "FakeQuanterWithAbsMaxObserverLayer",
           "AbsmaxObserver", "MovingAverageAbsmaxObserver"]


class BaseQuanter(Layer):
    """reference: base_quanter.py:25 — abstract fake-quant layer exposing
    scales/zero_points/bit_length/quant_axis for export."""

    def scales(self):
        raise NotImplementedError

    def zero_points(self):  # symmetric schemes: always zero
        return None

    @property
    def bit_length(self):
        return getattr(self, "_bits", 8)

    @property
    def quant_axis(self):
        return getattr(self, "_quant_axis", None)


class QuanterFactory:
    """reference: factory.py:52 — holds (cls, args) and instantiates per
    wrapped layer; lets QuantConfig carry configured-but-unbuilt quanters."""

    def __init__(self, cls, *args, **kwargs):
        self.cls = cls
        self.args = args
        self.kwargs = kwargs

    def _instance(self, layer=None):
        return self.cls(*self.args, **self.kwargs)


def quanter(class_name):
    """reference: factory.py:73 — decorator declaring a factory alias for a
    quanter layer class; the factory lands in this module's namespace."""
    def wrap(cls):
        def make(*args, **kwargs):
            return QuanterFactory(cls, *args, **kwargs)
        make.__name__ = class_name
        globals()[class_name] = make
        return cls
    return wrap


class FakeQuanterWithAbsMaxObserverLayer(BaseQuanter):
    """Moving-average absmax fake quanter
    (reference: quanters/abs_max.py:94)."""

    def __init__(self, layer=None, moving_rate=0.9, bit_length=8,
                 quant_axis=None, dtype="float32", name=None):
        super().__init__()
        self._moving_rate = moving_rate
        self._bits = bit_length
        self._quant_axis = quant_axis
        self.register_buffer("_scale", Tensor(jnp.ones([], jnp.float32)))
        self.register_buffer("_state", Tensor(jnp.ones([], jnp.float32)))
        self.register_buffer("_accum", Tensor(jnp.ones([], jnp.float32)))
        # flips on the first training-mode observation; the int8 freeze
        # refuses quanters that never saw data (scale would be the
        # meaningless init of 1.0)
        self._updated = False

    def _absmax(self, arr):
        if self._quant_axis is None:
            return jnp.max(jnp.abs(arr)).astype(jnp.float32)
        axes = tuple(i for i in range(arr.ndim) if i != self._quant_axis)
        return jnp.max(jnp.abs(arr), axis=axes).astype(jnp.float32)

    def forward(self, x):
        if self.training:
            self._updated = True
            absmax = self._absmax(x._array)
            if self._scale._array.shape != absmax.shape:
                # first per-channel observation: grow the scalar buffers
                self._state._array = jnp.ones_like(absmax)
                self._accum._array = jnp.ones_like(absmax)
            r = self._moving_rate
            state = self._state._array * r + 1.0
            accum = self._accum._array * r + absmax
            self._state._array = state
            self._accum._array = accum
            self._scale._array = accum / state
        return apply_op(fake_quant_dequant, x, self._scale._array,
                        op_name="fake_quant", bits=self._bits,
                        quant_axis=self._quant_axis)

    def scales(self):
        return Tensor(self._scale._array)


# the reference's public factory name
@quanter("FakeQuanterWithAbsMaxObserver")
class _FQAbsMax(FakeQuanterWithAbsMaxObserverLayer):
    pass


class AbsmaxObserver(BaseQuanter):
    """PTQ collector: tracks the max |x| seen; forward is identity
    (reference: imperative/ptq_quantizer.py AbsmaxQuantizer)."""

    def __init__(self, bit_length=8, quant_axis=None):
        super().__init__()
        self._bits = bit_length
        self._quant_axis = quant_axis
        self.register_buffer("_scale", Tensor(jnp.zeros([], jnp.float32)))

    def forward(self, x):
        if self._quant_axis is None:
            absmax = jnp.max(jnp.abs(x._array)).astype(jnp.float32)
        else:
            axes = tuple(i for i in range(x._array.ndim)
                         if i != self._quant_axis)
            absmax = jnp.max(jnp.abs(x._array), axis=axes).astype(
                jnp.float32)
            if self._scale._array.ndim == 0:
                self._scale._array = jnp.zeros_like(absmax)
        self._scale._array = jnp.maximum(self._scale._array, absmax)
        return x

    def scales(self):
        return Tensor(self._scale._array)


class MovingAverageAbsmaxObserver(BaseQuanter):
    """PTQ collector with EMA smoothing
    (reference: imperative/ptq_quantizer.py KLQuantizer-family sibling)."""

    def __init__(self, bit_length=8, moving_rate=0.9):
        super().__init__()
        self._bits = bit_length
        self._moving_rate = moving_rate
        self.register_buffer("_scale", Tensor(jnp.zeros([], jnp.float32)))
        self._seen = False

    def forward(self, x):
        absmax = jnp.max(jnp.abs(x._array)).astype(jnp.float32)
        if not self._seen:
            self._scale._array = absmax
            self._seen = True
        else:
            r = self._moving_rate
            self._scale._array = self._scale._array * r + absmax * (1 - r)
        return x

    def scales(self):
        return Tensor(self._scale._array)
