"""Fake-quantization primitives with straight-through gradients.

Reference analog: the fake_quantize_* / fake_channel_wise_quantize ops
(paddle/fluid/operators/fake_quantize_op.cc) that back
FakeQuanterWithAbsMaxObserverLayer (python/paddle/quantization/quanters/
abs_max.py:94).

TPU-native design: fake quant-dequant is a pure elementwise function —
XLA fuses it into the surrounding matmul/conv, so a QAT step costs almost
nothing extra on the MXU. The straight-through estimator is a
jax.custom_vjp that passes gradients inside the clipping range and zeros
them outside (the saturating-STE formulation).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["fake_quant_dequant", "quant_tensor", "dequant_tensor"]


@jax.custom_vjp
def _fqd(x, scale, qmax):
    s = jnp.maximum(scale, 1e-9)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax)
    return q * s / qmax


def _fqd_fwd(x, scale, qmax):
    return _fqd(x, scale, qmax), (x, scale)


def _fqd_bwd(res, g):
    x, scale = res
    inside = (jnp.abs(x) <= jnp.maximum(scale, 1e-9)).astype(g.dtype)
    return g * inside, None, None


_fqd.defvjp(_fqd_fwd, _fqd_bwd)


def fake_quant_dequant(x, scale, bits=8, quant_axis=None):
    """Quantize-dequantize `x` symmetrically to `bits` with saturating STE.

    `scale` is the absmax (per-tensor scalar, or per-channel along
    `quant_axis` with broadcast-ready shape)."""
    qmax = float(2 ** (bits - 1) - 1)
    if quant_axis is not None and jnp.ndim(scale) > 0:
        shape = [1] * x.ndim
        shape[quant_axis] = -1
        scale = jnp.reshape(scale, shape)
    return _fqd(x, scale, qmax)


def quant_tensor(x, scale, bits=8):
    """True quantization to int (for export); no gradient."""
    qmax = 2 ** (bits - 1) - 1
    s = jnp.maximum(scale, 1e-9)
    out_dtype = jnp.int8 if bits <= 8 else \
        jnp.int16 if bits <= 16 else jnp.int32
    return jnp.clip(jnp.round(x / s * qmax), -qmax, qmax).astype(out_dtype)


def dequant_tensor(q, scale, bits=8, dtype=jnp.float32):
    qmax = 2 ** (bits - 1) - 1
    return q.astype(dtype) * (scale / qmax)
