"""QuantConfig (reference: python/paddle/quantization/config.py:59).

Maps layers → (activation quanter, weight quanter) by three precedence
levels: per-layer instance, per-name prefix, per-type; plus a global
default. Also carries custom quanted-layer mappings."""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple, Union

from ..nn.layer.layers import Layer
from .quanters import QuanterFactory

__all__ = ["QuantConfig", "SingleLayerConfig"]


class SingleLayerConfig:
    """reference: config.py:34."""

    def __init__(self, activation: Optional[QuanterFactory],
                 weight: Optional[QuanterFactory]):
        self.activation = activation
        self.weight = weight

    def __str__(self):
        return f"activation: {self.activation}\nweight: {self.weight}"


class QuantConfig:
    def __init__(self, activation: Optional[QuanterFactory] = None,
                 weight: Optional[QuanterFactory] = None):
        self._global_config = SingleLayerConfig(activation, weight) \
            if (activation is not None or weight is not None) else None
        self._layer_configs: List[Tuple[List[Layer], SingleLayerConfig]] = []
        self._name_configs: List[Tuple[List[str], SingleLayerConfig]] = []
        self._type_configs: Dict[type, SingleLayerConfig] = {}
        self._qat_layer_mapping: Dict[type, type] = {}
        self._customized_leaves: List[type] = []

    # -- registration (reference: config.py add_layer_config:101,
    #    add_name_config:145, add_type_config:189) --
    def add_layer_config(self, layer: Union[Layer, List[Layer]],
                         activation=None, weight=None):
        layers = layer if isinstance(layer, list) else [layer]
        self._layer_configs.append(
            (layers, SingleLayerConfig(activation, weight)))

    def add_name_config(self, layer_name: Union[str, List[str]],
                        activation=None, weight=None):
        names = layer_name if isinstance(layer_name, list) else [layer_name]
        self._name_configs.append(
            (names, SingleLayerConfig(activation, weight)))

    def add_type_config(self, layer_type: Union[type, List[type]],
                        activation=None, weight=None):
        types = layer_type if isinstance(layer_type, list) else [layer_type]
        cfg = SingleLayerConfig(activation, weight)
        for t in types:
            assert isinstance(t, type) and issubclass(t, Layer)
            self._type_configs[t] = cfg

    def add_qat_layer_mapping(self, source: type, target: type):
        """reference: config.py:233 — replace `source` layers with the
        custom quantization-aware `target` during QAT.quantize."""
        assert isinstance(source, type) and issubclass(source, Layer)
        self._qat_layer_mapping[source] = target

    def add_customized_leaf(self, layer_type: type):
        self._customized_leaves.append(layer_type)

    @property
    def qat_layer_mappings(self):
        return dict(self._qat_layer_mapping)

    @property
    def customized_leaves(self):
        return list(self._customized_leaves)

    # -- resolution --
    def _get_config_by_layer(self, layer: Layer,
                             full_name: str = "") -> Optional[
                                 SingleLayerConfig]:
        for layers, cfg in self._layer_configs:
            if any(l is layer for l in layers):
                return cfg
        for names, cfg in self._name_configs:
            if any(full_name == n or full_name.startswith(n + ".")
                   or full_name.endswith("." + n) for n in names):
                return cfg
        cfg = self._type_configs.get(type(layer))
        if cfg is not None:
            return cfg
        return self._global_config

    def _is_quantifiable(self, layer: Layer) -> bool:
        from ..nn.layer.common import Linear
        from ..nn.layer.conv import Conv1D, Conv2D, Conv3D
        quantables = (Linear, Conv1D, Conv2D, Conv3D)
        return isinstance(layer, quantables) or \
            type(layer) in self._qat_layer_mapping
