"""paddle.quantization parity — QAT/PTQ over pure XLA-fused fake-quant.

Reference: python/paddle/quantization/ (QuantConfig, QAT, quanters) and
python/paddle/quantization/imperative (ImperativePTQ).

Export scope note: the reference's ONNX-format quantized-model export
(paddle2onnx path) is out of scope here — no onnx runtime exists in this
environment, and the TPU serving boundary is the StableHLO artifact
jit.save produces. A converted (fake-quant-folded) model exports through
jit.save like any other; quantized-operator interchange beyond that
rides StableHLO's quantized types when a consumer needs it."""
from .functional import (  # noqa: F401
    fake_quant_dequant, quant_tensor, dequant_tensor)
from .quanters import (  # noqa: F401
    BaseQuanter, quanter, QuanterFactory, FakeQuanterWithAbsMaxObserver,
    FakeQuanterWithAbsMaxObserverLayer, AbsmaxObserver,
    MovingAverageAbsmaxObserver, KLObserver)
from .config import QuantConfig, SingleLayerConfig  # noqa: F401
from .qat import (  # noqa: F401
    QAT, PTQ, QuantedWrapper, ObserveWrapper, quant_aware, convert)
from .quantized_layers import (  # noqa: F401
    QuantizedLinear, QuantizedConv2D)

__all__ = [
    "fake_quant_dequant", "quant_tensor", "dequant_tensor",
    "BaseQuanter", "quanter", "QuanterFactory",
    "FakeQuanterWithAbsMaxObserver", "FakeQuanterWithAbsMaxObserverLayer",
    "AbsmaxObserver", "MovingAverageAbsmaxObserver", "KLObserver",
    "QuantConfig", "SingleLayerConfig",
    "QAT", "PTQ", "QuantedWrapper", "ObserveWrapper", "quant_aware",
    "convert", "QuantizedLinear", "QuantizedConv2D",
]
