"""QAT / PTQ model transforms and quanted-layer wrappers.

Reference analog: python/paddle/quantization/qat.py:22 (QAT.quantize),
wrapper.py:20 (ObserveWrapper), imperative/ptq.py (ImperativePTQ).

TPU-native design: "quantize" is a pure model-to-model transform that
wraps matmul/conv layers with fake-quant layers; the fake-quant math is
elementwise and fuses into the XLA graph, so QAT trains at nearly full
speed on the MXU. `convert` freezes observers and bakes weight scales for
int8 export via jit.save's StableHLO path."""
from __future__ import annotations

import copy
import logging

from ..nn.layer.layers import Layer
from .config import QuantConfig
from .quanters import QuanterFactory

__all__ = ["QAT", "PTQ", "QuantedWrapper", "ObserveWrapper",
           "quant_aware", "convert"]


class QuantedWrapper(Layer):
    """Wraps a Linear/Conv layer: fake-quants the activation and the
    weight, then runs the original layer with the quantized weight (the
    reference's QuantedLinear/QuantedConv2D in nn/quant/quant_layers.py)."""

    def __init__(self, layer: Layer, activation_quanter=None,
                 weight_quanter=None):
        super().__init__()
        self._layer = layer
        self.activation_quanter = activation_quanter
        self.weight_quanter = weight_quanter

    def forward(self, x, *args, **kwargs):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        if self.weight_quanter is not None and hasattr(self._layer,
                                                       "weight"):
            w = self._layer.weight
            qw = self.weight_quanter(w)
            # substitute the quantized TENSOR (not just its array) so the
            # inner layer's ops consume the fake-quant tape node and the
            # STE backward reaches w; swapping w._array would sever it
            object.__setattr__(self._layer, "weight", qw)
            try:
                return self._layer(x, *args, **kwargs)
            finally:
                object.__setattr__(self._layer, "weight", w)
        return self._layer(x, *args, **kwargs)


class ObserveWrapper(Layer):
    """reference: wrapper.py:20 — observe-only wrapper used by PTQ."""

    def __init__(self, observer, observed: Layer, observe_input=True):
        super().__init__()
        self._observer = observer
        self._observed = observed
        self._observe_input = observe_input

    def forward(self, x, *args, **kwargs):
        if self._observe_input:
            x = self._observer(x)
            return self._observed(x, *args, **kwargs)
        out = self._observed(x, *args, **kwargs)
        return self._observer(out)


def _make(factory):
    if factory is None:
        return None
    if isinstance(factory, QuanterFactory):
        return factory._instance()
    return factory() if isinstance(factory, type) else factory


def _transform(model: Layer, config: QuantConfig, wrapper_cls,
               full_name=""):
    for name, sub in list(model._sub_layers.items()):
        child_name = f"{full_name}.{name}" if full_name else name
        mapped = config.qat_layer_mappings.get(type(sub))
        if mapped is not None:
            model._sub_layers[name] = mapped(sub)
            continue
        if config._is_quantifiable(sub):
            cfg = config._get_config_by_layer(sub, child_name)
            if cfg is not None and (cfg.activation is not None
                                    or cfg.weight is not None):
                model._sub_layers[name] = wrapper_cls(
                    sub, _make(cfg.activation), _make(cfg.weight))
                continue
        _transform(sub, config, wrapper_cls, child_name)
    return model


class QAT:
    """reference: qat.py:22."""

    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model: Layer, inplace=False) -> Layer:
        assert model.training, \
            "QAT.quantize expects a train-mode model (call model.train())"
        if not inplace:
            model = copy.deepcopy(model)
        return _transform(model, self._config, QuantedWrapper)

    def convert(self, model: Layer, inplace=False, to_int8=False) -> Layer:
        return convert(model, inplace=inplace, to_int8=to_int8)


class PTQ:
    """Post-training quantization: insert observers, run calibration data
    through the model, then convert (reference: imperative/ptq.py)."""

    def __init__(self, config: QuantConfig = None):
        if config is None:
            from .quanters import QuanterFactory, AbsmaxObserver
            config = QuantConfig(
                activation=QuanterFactory(AbsmaxObserver),
                weight=QuanterFactory(AbsmaxObserver))
        self._config = config

    def quantize(self, model: Layer, inplace=False) -> Layer:
        if not inplace:
            model = copy.deepcopy(model)
        model.eval()
        return _transform(model, self._config, QuantedWrapper)

    def convert(self, model: Layer, inplace=False, to_int8=False) -> Layer:
        return convert(model, inplace=inplace, to_int8=to_int8)


def convert(model: Layer, inplace=False, to_int8=False) -> Layer:
    """Freeze quanters.

    Default: replace each QuantedWrapper by its inner layer with the
    weight fake-quantized in place (the exported StableHLO carries the
    quantization error) and record scales as buffers.

    ``to_int8=True`` — the QuantizationFreezePass form: wrappers whose
    BOTH quanters hold scales (PTQ-calibrated or QAT-trained) become
    int8 inference layers (quantized_layers.QuantizedLinear /
    QuantizedConv2D): int8 weights in the artifact, activation
    quantization at the calibrated scale, int8 matmul compute for
    Linear. The converted model exports via jit.save and serves on the
    python Predictor and the C ABI unchanged.
    """
    from ..core.tensor import Tensor
    if not inplace:
        model = copy.deepcopy(model)
    for name, sub in list(model._sub_layers.items()):
        if isinstance(sub, QuantedWrapper):
            if to_int8 and _to_int8_layer(model, name, sub):
                continue
            inner = sub._layer
            wq = sub.weight_quanter
            if wq is not None and hasattr(inner, "weight"):
                # bake quantization error directly from the observed scale
                # (observer-type quanters have identity forwards, so calling
                # wq(weight) would be a no-op for PTQ)
                from .functional import fake_quant_dequant
                inner.weight._array = fake_quant_dequant(
                    inner.weight._array, wq.scales()._array,
                    bits=wq.bit_length, quant_axis=wq.quant_axis)
                try:
                    inner.register_buffer("weight_scale",
                                          Tensor(wq.scales()._array))
                except (AttributeError, ValueError, TypeError) as e:
                    # quanter never observed / exposes no scales: the
                    # bake above already happened, only the exported
                    # scale buffer is skipped
                    logging.getLogger(__name__).debug(
                        "convert: no weight_scale buffer for %s: %r",
                        name, e)
            aq = sub.activation_quanter
            if aq is not None:
                try:
                    inner.register_buffer("activation_scale",
                                          Tensor(aq.scales()._array))
                except (AttributeError, ValueError, TypeError) as e:
                    logging.getLogger(__name__).debug(
                        "convert: no activation_scale buffer for %s: %r",
                        name, e)
            model._sub_layers[name] = inner
        else:
            convert(sub, inplace=True, to_int8=to_int8)
    return model


def _to_int8_layer(model, name, wrapper) -> bool:
    """Try the int8 freeze for one wrapper; False -> fall back to the
    fake-quant bake (missing scales, unsupported layer/axis)."""
    import numpy as np

    from ..nn.layer.common import Linear
    from .quantized_layers import QuantizedConv2D, QuantizedLinear
    try:
        from ..nn.layer.conv import Conv2D
    except ImportError:  # pragma: no cover
        Conv2D = ()

    wq, aq = wrapper.weight_quanter, wrapper.activation_quanter
    if wq is None or aq is None:
        return False
    act_scale = np.asarray(aq.scales()._array)
    if float(np.max(np.abs(act_scale))) == 0.0:
        raise ValueError(
            f"convert(to_int8=True): layer {name!r} has an all-zero "
            "activation scale — run calibration batches through the "
            "observed model (PTQ) or train the QAT model first; "
            "freezing now would saturate every activation to +-127")
    for q, what in ((aq, "activation"), (wq, "weight")):
        # fake quanters init their scale to a plausible-looking 1.0;
        # only the _updated flag distinguishes trained from untouched
        if getattr(q, "_updated", None) is False:
            raise ValueError(
                f"convert(to_int8=True): layer {name!r}'s {what} "
                "quanter never observed data (scale is its init, not a "
                "measurement) — train the QAT model before freezing")
    inner = wrapper._layer
    try:
        if isinstance(inner, Linear):
            model._sub_layers[name] = QuantizedLinear.from_observed(
                inner, wq, aq)
            return True
        if Conv2D and isinstance(inner, Conv2D):
            model._sub_layers[name] = QuantizedConv2D.from_observed(
                inner, wq, aq)
            return True
    except ValueError as e:
        import warnings
        warnings.warn(f"convert(to_int8=True): {name!r} falls back to "
                      f"fake-quant baking: {e}")
    return False


def quant_aware(model: Layer, config: QuantConfig = None,
                inplace=False) -> Layer:
    """Convenience one-call QAT entry (the paddleslim-style API)."""
    if config is None:
        from .quanters import FakeQuanterWithAbsMaxObserver
        q = FakeQuanterWithAbsMaxObserver()
        config = QuantConfig(activation=q, weight=q)
    return QAT(config).quantize(model, inplace=inplace)
