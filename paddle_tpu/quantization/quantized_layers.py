"""Int8 inference layers — the deploy form of QAT/PTQ-calibrated models.

Reference analog: the QuantizationFreezePass + AddQuantDequantPass
product (static/quantization/quantization_pass.py:103,1827): ops in the
served program consume int8-quantized activations against int8 weights,
with calibrated (PTQ) or trained (QAT) scales baked in.

TPU-native design: instead of IR passes inserting quant/dequant ops
into a ProgramDesc, ``quantization.convert(model, to_int8=True)``
replaces each calibrated QuantedWrapper with one of these layers; the
whole model then exports through the ordinary ``jit.save`` StableHLO
path and serves on the python Predictor and the C ABI unchanged.

- ``QuantizedLinear`` computes in REAL int8: the activation quantizes
  at the calibrated scale, the int8 x int8 matmul accumulates in int32
  (``preferred_element_type`` — the MXU's native int8 path), and one
  fused rescale dequantizes the result.
- ``QuantizedConv2D`` stores int8 weights and quant-dequants the
  activation at its calibrated scale (the AddQuantDequantPass form);
  the conv itself runs in float after weight dequant — int8 conv
  lowering is not portable across XLA backends, so the numerics of
  int8 serving are kept while the op stays compilable everywhere.

Scale convention matches quantization.functional: ``scale`` is the
observed absmax; q = round(x / scale * qmax), x ~ q * scale / qmax.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op
from ..nn.layer.layers import Layer

__all__ = ["QuantizedLinear", "QuantizedConv2D"]


def _qmax(bits: int) -> float:
    return float(2 ** (bits - 1) - 1)


class QuantizedLinear(Layer):
    """y = dequant(int8(x) @ int8(W)) + b with per-out-channel weight
    scales (weight layout [in, out], scale shape [out] or scalar)."""

    def __init__(self, qweight, w_scale, act_scale, bias=None, bits=8):
        super().__init__()
        self._bits = int(bits)
        self.register_buffer("qweight", Tensor(jnp.asarray(qweight,
                                                           jnp.int8)))
        self.register_buffer("w_scale",
                             Tensor(jnp.asarray(w_scale, jnp.float32)))
        self.register_buffer("act_scale",
                             Tensor(jnp.asarray(act_scale, jnp.float32)))
        if bias is not None:
            self.register_buffer("bias",
                                 Tensor(jnp.asarray(bias, jnp.float32)))
        else:
            self.bias = None

    @classmethod
    def from_observed(cls, layer, weight_quanter, act_quanter):
        """Build from a calibrated/trained QuantedWrapper's pieces: a
        Linear plus its weight/activation quanters (both must hold
        scales — PTQ-observed or QAT-trained)."""
        from .functional import quant_tensor

        w = layer.weight._array
        ws = jnp.asarray(weight_quanter.scales()._array, jnp.float32)
        if ws.ndim > 0 and weight_quanter.quant_axis not in (1, None):
            raise ValueError(
                "QuantizedLinear needs per-OUT-channel weight scales "
                f"(quant_axis=1) or per-tensor; got quant_axis="
                f"{weight_quanter.quant_axis} — the per-in scale does "
                "not factor out of an int8 contraction")
        sa = jnp.asarray(act_quanter.scales()._array, jnp.float32)
        if sa.ndim > 0:
            raise ValueError(
                "QuantizedLinear needs a PER-TENSOR activation scale; "
                f"got shape {tuple(sa.shape)} (per-channel act quant "
                "does not factor out of the int8 contraction)")
        q = quant_tensor(w, ws if ws.ndim == 0 else ws[None, :],
                         bits=weight_quanter.bit_length)
        bias = getattr(layer, "bias", None)
        return cls(q, ws, act_quanter.scales()._array,
                   bias=None if bias is None else bias._array,
                   bits=weight_quanter.bit_length)

    def forward(self, x):
        from .functional import quant_tensor

        qmax = _qmax(self._bits)
        bits = self._bits
        qw = self.qweight._array
        ws = self.w_scale._array
        sa = self.act_scale._array
        b = None if self.bias is None else self.bias._array

        def f(xa):
            xq = quant_tensor(xa, sa, bits=bits)
            y32 = jax.lax.dot_general(
                xq, qw, (((xa.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            # one fused rescale: (sa/qmax) * (ws/qmax), ws broadcasts
            # over the output channel
            y = y32.astype(jnp.float32) * (jnp.maximum(sa, 1e-9) / qmax) \
                * (jnp.maximum(ws, 1e-9) / qmax)
            return y if b is None else y + b
        return apply_op(f, x, op_name="quantized_linear")

    def extra_repr(self):
        return (f"in={self.qweight.shape[0]}, out={self.qweight.shape[1]}"
                f", bits={self._bits}, int8_compute=True")


class QuantizedConv2D(Layer):
    """Conv with int8-stored weights + activation quant-dequant at the
    calibrated scale; see module docstring for why the conv itself runs
    in float."""

    def __init__(self, conv, qweight, w_scale, act_scale, bits=8):
        super().__init__()
        self._conv = conv
        self._bits = int(bits)
        self.register_buffer("qweight", Tensor(jnp.asarray(qweight,
                                                           jnp.int8)))
        self.register_buffer("w_scale",
                             Tensor(jnp.asarray(w_scale, jnp.float32)))
        self.register_buffer("act_scale",
                             Tensor(jnp.asarray(act_scale, jnp.float32)))
        # the float weight is rebuilt from int8 at forward; drop the
        # original parameter so the exported artifact carries int8 only
        self._conv.weight = None

    @classmethod
    def from_observed(cls, layer, weight_quanter, act_quanter):
        from .functional import quant_tensor

        sa = jnp.asarray(act_quanter.scales()._array, jnp.float32)
        if sa.ndim > 0:
            raise ValueError(
                "QuantizedConv2D needs a PER-TENSOR activation scale; "
                f"got shape {tuple(sa.shape)}")
        w = layer.weight._array
        ws = jnp.asarray(weight_quanter.scales()._array, jnp.float32)
        axis = weight_quanter.quant_axis
        if ws.ndim > 0:
            shape = [1] * w.ndim
            shape[0 if axis is None else axis] = -1
            q = quant_tensor(w, jnp.reshape(ws, shape),
                             bits=weight_quanter.bit_length)
        else:
            q = quant_tensor(w, ws, bits=weight_quanter.bit_length)
        self_ = cls(layer, q, ws, act_quanter.scales()._array,
                    bits=weight_quanter.bit_length)
        self_._w_quant_axis = axis
        return self_

    def forward(self, x):
        from .functional import dequant_tensor, fake_quant_dequant

        qmax_bits = self._bits
        ws = self.w_scale._array
        if ws.ndim > 0:
            shape = [1] * self.qweight._array.ndim
            shape[getattr(self, "_w_quant_axis", 0) or 0] = -1
            ws = jnp.reshape(ws, shape)
        w = dequant_tensor(self.qweight._array, ws, bits=qmax_bits)
        xa = apply_op(
            lambda a: fake_quant_dequant(a, self.act_scale._array,
                                         bits=qmax_bits),
            x, op_name="quant_dequant_act")
        self._conv.weight = Tensor(w)
        try:
            return self._conv(xa)
        finally:
            self._conv.weight = None
