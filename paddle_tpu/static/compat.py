"""paddle.static long-tail parity: program persistence, places,
scopes, legacy executor shells, EMA.

Reference analogs: python/paddle/static/io.py (save/load/serialize_*),
fluid/executor.py scope plumbing, fluid/compiler.py (CompiledProgram /
BuildStrategy / ExecutionStrategy / ParallelExecutor), incubate EMA,
fluid/layers control Print.

TPU-native collapses, stated openly:
- Program persistence rides Program.state_dict + framework.io; the
  serialized "program" is the pickled op-free state (the executable
  graph re-derives from python source on this stack — StableHLO export
  via jit.save is the cross-process graph format).
- One logical device pool: *_places() return the places that exist.
- CompiledProgram/ParallelExecutor/BuildStrategy/ExecutionStrategy are
  accepted-and-forwarded shells: XLA owns scheduling/fusion decisions
  the legacy knobs used to steer.
- IPU entry points raise: another vendor's accelerator, genuinely out
  of scope for a TPU-native build (reference gates them behind
  is_compiled_with_ipu, which is False here).
"""
from __future__ import annotations

import contextlib
import os
import pickle
from typing import Optional

import numpy as np

__all__ = [
    "append_backward", "global_scope", "scope_guard", "Scope",
    "BuildStrategy",
    "CompiledProgram", "ExecutionStrategy", "ParallelExecutor", "Print",
    "WeightNormParamAttr", "ExponentialMovingAverage", "save", "load",
    "serialize_program", "serialize_persistables", "save_to_file",
    "deserialize_program", "deserialize_persistables", "load_from_file",
    "normalize_program", "load_program_state", "set_program_state",
    "cpu_places", "cuda_places", "xpu_places", "npu_places",
    "mlu_places", "Variable", "create_global_var", "create_parameter",
    "accuracy", "auc", "device_guard", "ipu_shard_guard",
    "IpuCompiledProgram", "IpuStrategy", "set_ipu_shard",
    "ctr_metric_bundle", "exponential_decay",
]


def _default_prog(program=None):
    if program is not None:
        return getattr(program, "_program", program)
    from .program import default_main_program
    return default_main_program()


# -- backward / scope ------------------------------------------------------

def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """reference: fluid/backward.py append_backward — registers the
    training objective on the program; the Executor computes gradients
    in-graph when it runs (minimize() without the optimizer half).
    Returns the (param, grad-placeholder) pairs."""
    from .program import recording_program
    prog = recording_program()
    if prog is None:
        raise RuntimeError("append_backward needs an active static "
                           "program (enable_static + program_guard)")
    params = parameter_list or [t for t in prog._captured()
                                if not t.stop_gradient]
    prog._opt = (None, loss)  # Executor: grads computed, no update
    return [(p, None) for p in params]


class Scope:
    """Name -> variable view over a Program (fluid Scope analog)."""

    def __init__(self, program=None):
        self._program = program

    def find_var(self, name):
        try:
            return _default_prog(self._program).var(name)
        except KeyError:
            return None

    var = find_var


_GLOBAL_SCOPE = Scope()


def global_scope():
    return _GLOBAL_SCOPE


@contextlib.contextmanager
def scope_guard(scope):
    global _GLOBAL_SCOPE
    prev, _GLOBAL_SCOPE = _GLOBAL_SCOPE, scope
    try:
        yield scope
    finally:
        _GLOBAL_SCOPE = prev


# -- legacy executor shells -------------------------------------------------

_WARNED_KNOBS = set()


def _warn_once(key, msg):
    """One warning per swallowed-knob site per process: the legacy
    shells accept configuration XLA now owns — silently dropping it hid
    real tuning intent (users set BuildStrategy.fuse_* and saw nothing)."""
    if key in _WARNED_KNOBS:
        return
    _WARNED_KNOBS.add(key)
    import warnings
    warnings.warn(msg, UserWarning, stacklevel=3)


class _AttrBag:
    def __init__(self, **kw):
        self.__dict__.update(kw)
        if kw:
            self._note_swallowed(", ".join(sorted(kw)))

    def __setattr__(self, k, v):
        self.__dict__[k] = v
        self._note_swallowed(k)

    def _note_swallowed(self, what):
        name = type(self).__name__
        _warn_once(name, f"{name}.{what} is accepted for API parity "
                   "but has no effect on this stack: XLA owns the "
                   "fusion/scheduling decisions these knobs steered")


class BuildStrategy(_AttrBag):
    """Accepted for parity; XLA makes the fusion/layout decisions the
    legacy pass flags steered."""


class ExecutionStrategy(_AttrBag):
    """Accepted for parity; the jit-replay Executor has no thread-pool
    knobs to set."""


class CompiledProgram:
    """reference: compiler.py CompiledProgram — here a transparent
    proxy: Executor.run compiles per feed signature already."""

    def __init__(self, program, build_strategy=None):
        self._program = _default_prog(program)
        self._build_strategy = build_strategy
        if build_strategy is not None:
            _warn_once("CompiledProgram.build_strategy",
                       "CompiledProgram ignores build_strategy: XLA "
                       "makes the fusion/placement decisions here")

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, places=None):
        _warn_once("CompiledProgram.with_data_parallel",
                   "with_data_parallel is a no-op on this stack: "
                   "data parallelism comes from mesh axis 'dp' "
                   "(paddle.distributed init_mesh), not executor replicas")
        return self

    def __getattr__(self, name):
        return getattr(self.__dict__["_program"], name)


class ParallelExecutor:
    """Legacy pre-2.0 API: delegates to the modern Executor (the
    reference itself deprecates it toward CompiledProgram)."""

    def __init__(self, use_cuda=False, loss_name=None,
                 main_program=None, **kw):
        from . import Executor
        if kw:
            _warn_once("ParallelExecutor.kwargs",
                       f"ParallelExecutor ignores {sorted(kw)}: it "
                       "delegates to the modern Executor (one logical "
                       "device; XLA schedules)")
        self._exe = Executor()
        self._prog = _default_prog(main_program)

    def run(self, fetch_list=None, feed=None, return_numpy=True):
        return self._exe.run(self._prog, feed=feed,
                             fetch_list=fetch_list,
                             return_numpy=return_numpy)


def Print(input, first_n=-1, message=None, summarize=20,  # noqa: A002,N802
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """reference: layers/control_flow Print op — prints the tensor at
    RUN time (jax.debug.print inside traced programs) and passes the
    value through."""
    import jax

    from ..core.tensor import apply_op

    def _f(a):
        jax.debug.print((message or "Print") + ": {}", a)
        return a
    return apply_op(_f, input, op_name="print")


# -- persistence ------------------------------------------------------------

def _state_np(program):
    return {k: np.asarray(getattr(v, "_array", v))
            for k, v in _default_prog(program).state_dict().items()}


def serialize_program(feed_vars=None, fetch_vars=None, program=None):
    prog = _default_prog(program)
    meta = {"feeds": sorted(prog._feeds), "n_ops": len(prog._ops),
            "note": "graph re-derives from python; state is the "
                    "persisted half (jit.save exports StableHLO)"}
    return pickle.dumps(meta)


def serialize_persistables(feed_vars=None, fetch_vars=None, program=None):
    return pickle.dumps(_state_np(program))


def save_to_file(path, content):
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def deserialize_program(data):
    return pickle.loads(data)


def deserialize_persistables(program, data, executor=None):
    state = pickle.loads(data)
    _default_prog(program).set_state_dict(state)
    return state


def save(program, model_path, protocol=4, **configs):
    """reference: static.save — <prefix>.pdparams + .pdmodel pair."""
    save_to_file(model_path + ".pdparams",
                 pickle.dumps(_state_np(program), protocol=protocol))
    save_to_file(model_path + ".pdmodel", serialize_program(
        program=program))


def load(program, model_path, executor=None, var_list=None):
    with open(model_path + ".pdparams", "rb") as f:
        state = pickle.load(f)
    _default_prog(program).set_state_dict(state)
    return state


def load_program_state(model_path, var_list=None):
    with open(model_path + ".pdparams", "rb") as f:
        return pickle.load(f)


def set_program_state(program, state_dict):
    _default_prog(program).set_state_dict(state_dict)


def normalize_program(program, feed_vars=None, fetch_vars=None):
    """The inference-normalization pass (prune feeds/backward) maps to
    clone(for_test=True) on this stack."""
    return _default_prog(program).clone(for_test=True)


# -- places / variables -----------------------------------------------------

def cpu_places(device_count=None):
    from ..core.place import CPUPlace
    n = device_count or int(os.environ.get("CPU_NUM", 1))
    return [CPUPlace() for _ in range(n)]


def _accel_places(kind, device_ids=None):
    import warnings

    from ..core.place import _default_place
    warnings.warn(f"{kind}_places on a TPU-native build: returning the "
                  "available accelerator places")
    ids = device_ids if device_ids is not None else [0]
    return [_default_place() for _ in ids]


def cuda_places(device_ids=None):
    return _accel_places("cuda", device_ids)


def xpu_places(device_ids=None):
    return _accel_places("xpu", device_ids)


def npu_places(device_ids=None):
    return _accel_places("npu", device_ids)


def mlu_places(device_ids=None):
    return _accel_places("mlu", device_ids)


def _variable():
    from ..core.tensor import Tensor
    return Tensor


Variable = None  # bound below (import-order: Tensor needs core ready)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    import jax.numpy as jnp

    from ..core.tensor import Tensor
    t = Tensor(jnp.full(tuple(shape), value, dtype=jnp.dtype(dtype)))
    t.name = name
    t.stop_gradient = True
    return t


def create_parameter(shape, dtype, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    import numpy as _np

    from ..core.tensor import Tensor
    if default_initializer is not None:
        from ..nn.layer.layers import Layer
        helper = Layer()
        return helper.create_parameter(list(shape), attr=attr,
                                       is_bias=is_bias,
                                       default_initializer=default_initializer)
    if is_bias:
        arr = _np.zeros(tuple(shape), _np.dtype(dtype))
    else:
        # draw from the framework RNG stream (paddle.seed controls it,
        # each call advances it) — a fixed default_rng(0) here gave
        # every created parameter the identical values
        import jax as _jax
        from ..framework.random import next_key
        arr = (_np.asarray(_jax.random.normal(next_key(), tuple(shape)))
               * 0.02).astype(_np.dtype(dtype))
    t = Tensor(arr)
    t.stop_gradient = False
    t.name = name
    return t


# -- metrics / misc ---------------------------------------------------------

def accuracy(input, label, k=1, correct=None, total=None):  # noqa: A002
    from ..core.tensor import Tensor, apply_op
    import jax.numpy as jnp

    def _f(lg, y):
        topk = jnp.argsort(-lg, axis=-1)[..., :k]
        hit = (topk == y.reshape(-1, 1)).any(-1)
        return jnp.mean(hit.astype(jnp.float32))
    return apply_op(_f, input, label, op_name="accuracy")


def auc(input, label, curve="ROC", num_thresholds=4095,  # noqa: A002
        topk=1, slide_steps=1):
    from ..core.tensor import apply_op
    import jax.numpy as jnp

    def _f(p, y):
        # rank-statistic AUC (Mann-Whitney U); p: positive-class score
        score = p[..., 1] if p.ndim > 1 and p.shape[-1] == 2 else \
            p.reshape(-1)
        y = y.reshape(-1).astype(jnp.float32)
        order = jnp.argsort(score)
        ranks = jnp.zeros_like(score).at[order].set(
            jnp.arange(1, score.shape[0] + 1, dtype=score.dtype))
        n_pos = jnp.sum(y)
        n_neg = y.shape[0] - n_pos
        u = jnp.sum(ranks * y) - n_pos * (n_pos + 1) / 2
        return u / jnp.maximum(n_pos * n_neg, 1.0)
    return apply_op(_f, input, label, op_name="auc")


def ctr_metric_bundle(input, label, ins_tag_weight=None):  # noqa: A002
    """reference: static/nn/metric ctr_metric_bundle — (auc, batch_auc)
    pair for CTR models; one pool on a single-job build."""
    a = auc(input, label)
    return a, a


@contextlib.contextmanager
def device_guard(device=None):
    """reference: device_guard('cpu'/'gpu') op placement hint — XLA
    places ops; the guard is accepted and ignored."""
    yield


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """reference: fluid layers exponential_decay —
    lr(step) = learning_rate * decay_rate ** (step / decay_steps),
    with the exponent floored when staircase. Returns the modern
    scheduler: StepDecay IS the staircase form; the smooth form maps
    onto ExponentialDecay through the per-step gamma
    decay_rate ** (1 / decay_steps)."""
    if decay_steps <= 0:
        raise ValueError(
            f"decay_steps must be a positive integer, got {decay_steps}")
    if staircase:
        from ..optimizer.lr import StepDecay
        return StepDecay(learning_rate=learning_rate,
                         step_size=int(decay_steps), gamma=decay_rate)
    from ..optimizer.lr import ExponentialDecay
    return ExponentialDecay(learning_rate=learning_rate,
                            gamma=decay_rate ** (1.0 / decay_steps))


class WeightNormParamAttr:
    """reference: fluid/param_attr.py WeightNormParamAttr — carries the
    weight-norm dim; apply weight norm with nn.utils.weight_norm on
    this stack (the ParamAttr route needs the legacy op rewriter)."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.trainable = trainable


class ExponentialMovingAverage:
    """reference: incubate ExponentialMovingAverage over program
    parameters: shadow = decay * shadow + (1 - decay) * param, with
    apply()/restore() swaps."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = float(decay)
        self._shadow = {}
        self._backup = {}

    def update(self, parameters=None):
        params = parameters
        if params is None:
            from .program import recording_program
            prog = recording_program() or _default_prog()
            params = [t for t in prog._captured() if not t.stop_gradient]
        import numpy as _np
        for i, p in enumerate(params):
            key = getattr(p, "name", None) or f"p{i}"
            cur = _np.asarray(getattr(p, "_array", p))
            prev = self._shadow.get(key)
            self._shadow[key] = cur.copy() if prev is None else \
                self._decay * prev + (1 - self._decay) * cur
            self._shadow.setdefault("__obj__" + key, p)

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        import jax.numpy as jnp
        for key, val in list(self._shadow.items()):
            if key.startswith("__obj__"):
                continue
            p = self._shadow["__obj__" + key]
            self._backup[key] = p._array
            p._set_array(jnp.asarray(val))
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        for key, arr in self._backup.items():
            self._shadow["__obj__" + key]._set_array(arr)
        self._backup.clear()


# -- IPU: out of scope -------------------------------------------------------

_IPU_MSG = ("IPU support is out of scope for a TPU-native build "
            "(reference gates these behind is_compiled_with_ipu(), "
            "False here); target TPU via the ordinary jit/static path")


def ipu_shard_guard(index=-1, stage=-1):
    raise NotImplementedError(_IPU_MSG)


def set_ipu_shard(call_func, index=-1, stage=-1):
    raise NotImplementedError(_IPU_MSG)


class IpuStrategy:
    def __init__(self):
        raise NotImplementedError(_IPU_MSG)


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        raise NotImplementedError(_IPU_MSG)


def _late_bind():
    global Variable
    from ..core.tensor import Tensor
    Variable = Tensor


_late_bind()
