"""Control-flow ops: cond / while_loop / switch_case / case.

Reference analog: paddle/fluid/operators/controlflow/
(conditional_block_op.cc, while_op.cc) surfaced as
python/paddle/static/nn/control_flow.py (cond:392, While/while_loop:1049,
switch_case:1211) and converted from python syntax by
jit/dy2static/program_translator.py:1225.

TPU-native tracing contract (replaces the dy2static AST rewrite):

- EAGER (concrete predicate): plain python dispatch — only the taken
  branch runs, autograd flows through the tape exactly like any op.
- TRACED (predicate is a jax tracer, i.e. inside ``to_static``/``jit``):
  lowers to ``lax.cond`` / ``lax.while_loop`` / ``lax.switch``. Both
  branches are traced (XLA compiles both; one executes), so branch
  outputs must match in structure/shape/dtype. ``cond``/``switch_case``
  differentiate through jax autodiff; ``while_loop`` is
  forward-differentiable only (XLA's while has no reverse-mode
  transpose — same contract as jax; use a bounded loop or ``lax.scan``
  patterns when you need gradients).

Data-dependent python ``if x > 0:`` on a traced Tensor raises jax's
TracerBoolConversionError — rewrite it with these ops, which is the same
contract the reference enforces in static graphs (python ``if`` on a
Variable silently takes one branch there; dy2static exists to rewrite
it to cond). Here the error is loud instead of silent.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor, to_tensor

__all__ = ["cond", "while_loop", "switch_case", "case"]


def _arr(x):
    return x._array if isinstance(x, Tensor) else jnp.asarray(x)


def _is_traced(a):
    from jax.core import Tracer
    return isinstance(a, Tracer)


def _to_arrays(tree):
    return jax.tree_util.tree_map(
        lambda t: _arr(t), tree,
        is_leaf=lambda t: isinstance(t, Tensor))


def _to_tensors(tree):
    return jax.tree_util.tree_map(Tensor, tree)


def cond(pred, true_fn: Callable, false_fn: Callable, name=None,
         return_names=None):
    """Run ``true_fn()`` if pred else ``false_fn()``
    (reference static/nn/control_flow.py:392)."""
    p = _arr(pred)
    if not _is_traced(p):
        return true_fn() if bool(p) else false_fn()

    def wrap(fn):
        return lambda: _to_arrays(fn())

    out = lax.cond(p, wrap(true_fn), wrap(false_fn))
    return _to_tensors(out)


def while_loop(cond_fn: Callable, body_fn: Callable,
               loop_vars: Sequence, is_test=False, name=None):
    """Iterate ``body_fn(*vars)`` while ``cond_fn(*vars)``
    (reference static/nn/control_flow.py:1049)."""
    arrs = [_arr(v) for v in loop_vars]
    traced = any(map(_is_traced, arrs)) or _is_traced(_arr(
        cond_fn(*loop_vars)))
    if not traced:
        vals = list(loop_vars)
        while bool(_arr(cond_fn(*vals))):
            out = body_fn(*vals)
            vals = list(out) if isinstance(out, (tuple, list)) else [out]
        return vals

    def acond(carry):
        return _arr(cond_fn(*_to_tensors(list(carry))))

    def abody(carry):
        out = body_fn(*_to_tensors(list(carry)))
        if not isinstance(out, (tuple, list)):
            out = (out,)
        return tuple(_to_arrays(list(out)))

    out = lax.while_loop(acond, abody, tuple(arrs))
    return _to_tensors(list(out))


def switch_case(branch_index, branch_fns: Union[Dict, List, tuple],
                default: Callable = None, name=None):
    """Dispatch on an integer index with an optional default
    (reference static/nn/control_flow.py:1211)."""
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    elif branch_fns and isinstance(branch_fns[0], (tuple, list)):
        items = sorted((int(k), f) for k, f in branch_fns)
    else:
        items = list(enumerate(branch_fns))
    keys = [int(k) for k, _ in items]
    fns = [f for _, f in items]
    if default is None:
        default = fns[-1]  # reference: last branch doubles as default

    idx = _arr(branch_index)
    if not _is_traced(idx):
        i = int(idx)
        return dict(zip(keys, fns)).get(i, default)()

    karr = jnp.asarray(keys)
    matches = karr == idx.astype(karr.dtype)
    sel = jnp.where(jnp.any(matches), jnp.argmax(matches), len(fns))
    branches = [(lambda f: (lambda: _to_arrays(f())))(f)
                for f in fns + [default]]
    return _to_tensors(lax.switch(sel, branches))


def case(pred_fn_pairs: Sequence, default: Callable = None, name=None):
    """First pair whose predicate holds wins
    (reference static/nn/control_flow.py case). Builds nested cond, so it
    works traced as well as eager."""
    if not pred_fn_pairs:
        raise ValueError("pred_fn_pairs must be non-empty")
    if default is None:
        default = pred_fn_pairs[-1][1]

    def build(pairs):
        if not pairs:
            return default
        (p, f), rest = pairs[0], pairs[1:]
        return lambda: cond(p, f, build(rest))

    return build(list(pred_fn_pairs))()
