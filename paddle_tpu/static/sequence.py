"""Sequence ops + StaticRNN (the static/nn sequence_lod surface).

Reference analog: python/paddle/static/nn/sequence_lod.py — variable-
length sequence operators over level-1 LoD tensors — and
fluid/layers/StaticRNN (a per-step sub-block replayed over time).

TPU-native convention: a level-1 LoD tensor IS a ``(values, lengths)``
pair — ``values [total, ...]`` concatenates every sequence's steps,
``lengths [B]`` gives each sequence's step count (exactly the
information LoD offsets carry). Functions taking a sequence accept that
pair; ``sequence_pad``/``sequence_unpad`` convert to/from the dense
``[B, T, ...]`` + lengths form the rest of the framework (and XLA's
static shapes) prefer. Ragged bookkeeping runs on the host (numpy) —
these are preprocessing-tier ops, not MXU work, same as the reference's
CPU-only LoD kernels.

StaticRNN records its step block into a sub-Program (the reference
records a sub-Block) and replays it per timestep at call time.
"""
from __future__ import annotations

import contextlib
from typing import List, Optional, Tuple

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["sequence_conv", "sequence_softmax", "sequence_pool",
           "sequence_concat", "sequence_first_step", "sequence_last_step",
           "sequence_slice", "sequence_expand", "sequence_expand_as",
           "sequence_pad", "sequence_unpad", "sequence_reshape",
           "sequence_scatter", "sequence_enumerate", "sequence_reverse",
           "StaticRNN"]


def _pair(x):
    """(values, lengths) -> numpy views; a bare dense tensor counts as
    one sequence per row of length 1? No — reject, the LoD ops need
    lengths."""
    if not (isinstance(x, (tuple, list)) and len(x) == 2):
        raise TypeError(
            "sequence ops take a (values, lengths) pair — the level-1 "
            "LoD tensor of the reference. Convert a padded batch with "
            "sequence_unpad(x, lengths) first.")
    v, ln = x
    va = np.asarray(getattr(v, "_array", v))
    la = np.asarray(getattr(ln, "_array", ln)).astype(np.int64).reshape(-1)
    if int(la.sum()) != va.shape[0]:
        raise ValueError(
            f"lengths sum {int(la.sum())} != values rows {va.shape[0]}")
    return va, la


def _wrap(values: np.ndarray, lengths: np.ndarray):
    return (Tensor(jnp.asarray(values)), Tensor(jnp.asarray(lengths)))


def _offsets(lengths):
    return np.concatenate([[0], np.cumsum(lengths)]).astype(np.int64)


def _segments(values, lengths):
    off = _offsets(lengths)
    return [values[off[i]:off[i + 1]] for i in range(len(lengths))]


def sequence_pad(x, pad_value, maxlen=None, name=None):
    """(values, lengths) -> (padded [B, T, ...], lengths)."""
    v, ln = _pair(x)
    pv = np.asarray(getattr(pad_value, "_array", pad_value))
    T = int(maxlen) if maxlen is not None else int(ln.max()) if len(ln) \
        else 0
    if maxlen is not None and len(ln) and int(ln.max()) > T:
        raise ValueError(
            f"sequence_pad: longest sequence ({int(ln.max())}) exceeds "
            f"maxlen ({T}); the reference op requires maxlen >= every "
            "sequence length — it pads, it does not truncate")
    B = len(ln)
    out = np.empty((B, T) + v.shape[1:], v.dtype)
    out[...] = pv
    for i, seg in enumerate(_segments(v, ln)):
        out[i, :min(len(seg), T)] = seg[:T]
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(ln))


def sequence_unpad(x, length, name=None):
    """Dense [B, T, ...] + lengths -> the (values, lengths) pair."""
    xa = np.asarray(getattr(x, "_array", x))
    ln = np.asarray(getattr(length, "_array", length)).astype(
        np.int64).reshape(-1)
    vals = np.concatenate([xa[i, :ln[i]] for i in range(len(ln))], axis=0) \
        if len(ln) else xa[:0].reshape((0,) + xa.shape[2:])
    return _wrap(vals, ln)


def _seq_meta(x):
    """(tensor values, host lengths, host segment ids) keeping the
    VALUES on the tape — the compute-tier sequence ops must stay
    differentiable (the reference's are real ops with grads)."""
    if not (isinstance(x, (tuple, list)) and len(x) == 2):
        raise TypeError(
            "sequence ops take a (values, lengths) pair — the level-1 "
            "LoD tensor of the reference. Convert a padded batch with "
            "sequence_unpad(x, lengths) first.")
    v, ln = x
    vt = v if isinstance(v, Tensor) else Tensor(jnp.asarray(v))
    la = np.asarray(getattr(ln, "_array", ln)).astype(np.int64).reshape(-1)
    if int(la.sum()) != vt.shape[0]:
        raise ValueError(
            f"lengths sum {int(la.sum())} != values rows {vt.shape[0]}")
    ids = np.repeat(np.arange(len(la)), la)
    return vt, la, ids


def sequence_softmax(input, use_cudnn=False, name=None):  # noqa: A002
    """Softmax within each sequence — differentiable (segment ops on
    the tape; only the integer id plan is host-side)."""
    from ..geometric import segment_max, segment_sum
    from ..tensor.manipulation import gather
    from ..tensor.math import exp, subtract, divide

    v, ln, ids = _seq_meta(input)
    idt = Tensor(jnp.asarray(ids))
    mx = segment_max(v, idt)
    e = exp(subtract(v, gather(mx, idt)))
    z = segment_sum(e, idt)
    out = divide(e, gather(z, idt))
    return (out, Tensor(jnp.asarray(ln)))


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0,  # noqa: A002
                  name=None):
    """Per-sequence pooling — differentiable through the values (the
    reference sequence_pool op has a gradient kernel; empty sequences
    yield pad_value like the reference)."""
    from ..geometric import segment_max, segment_mean, segment_sum
    from ..tensor.manipulation import gather
    from ..tensor.math import divide, multiply

    v, ln, ids = _seq_meta(input)
    idt = Tensor(jnp.asarray(ids))
    pt = pool_type.lower()
    if pt == "max":
        out = segment_max(v, idt)
    elif pt in ("average", "avg", "mean"):
        out = segment_mean(v, idt)
    elif pt == "sum":
        out = segment_sum(v, idt)
    elif pt == "sqrt":
        scale = 1.0 / np.sqrt(np.maximum(ln, 1)).astype(np.float32)
        out = multiply(segment_sum(v, idt),
                       Tensor(jnp.asarray(scale.reshape(-1, 1))))
    elif pt == "first":
        off = _offsets(ln)[:-1]
        out = gather(v, Tensor(jnp.asarray(off)))
    elif pt == "last":
        off = _offsets(ln)[1:] - 1
        out = gather(v, Tensor(jnp.asarray(np.maximum(off, 0))))
    else:
        raise ValueError(f"unknown pool_type {pool_type!r}")
    # segment ops only cover ids that appear: pad trailing empty
    # sequences and overwrite empty rows with pad_value
    B = len(ln)
    if out.shape[0] < B or (ln == 0).any():
        oa = getattr(out, "_array", out)
        full = jnp.full((B,) + tuple(oa.shape[1:]), pad_value, oa.dtype)
        from ..core.tensor import apply_op
        empty = Tensor(jnp.asarray((ln == 0)))

        def _fix(o, e):
            f = full.at[:o.shape[0]].set(o)
            return jnp.where(e.reshape((-1,) + (1,) * (f.ndim - 1)),
                             jnp.asarray(pad_value, f.dtype), f)
        out = apply_op(_fix, out, empty, op_name="sequence_pool_pad")
    return out


def sequence_first_step(input, name=None):  # noqa: A002
    return sequence_pool(input, "first")


def sequence_last_step(input, name=None):  # noqa: A002
    return sequence_pool(input, "last")


def sequence_concat(input, name=None):  # noqa: A002
    """Concat several sequence pairs per batch item along time."""
    pairs = [_pair(x) for x in input]
    B = len(pairs[0][1])
    segs_per = [_segments(v, ln) for v, ln in pairs]
    vals, lens = [], []
    for b in range(B):
        parts = [sp[b] for sp in segs_per]
        vals.append(np.concatenate(parts, axis=0))
        lens.append(sum(len(p) for p in parts))
    return _wrap(np.concatenate(vals, axis=0),
                 np.asarray(lens, np.int64))


def sequence_slice(input, offset, length, name=None):  # noqa: A002
    v, ln = _pair(input)
    off = np.asarray(getattr(offset, "_array", offset)).reshape(-1)
    lth = np.asarray(getattr(length, "_array", length)).reshape(-1)
    vals, lens = [], []
    for seg, o, l in zip(_segments(v, ln), off, lth):
        vals.append(seg[int(o):int(o) + int(l)])
        lens.append(int(l))
    return _wrap(np.concatenate(vals, axis=0),
                 np.asarray(lens, np.int64))


def sequence_expand(x, y, ref_level=-1, name=None):
    """Repeat x's sequences to match y's lengths (the LoD broadcast):
    x sequence i is tiled len_y[i] times when x has one step per item,
    else repeated whole."""
    xv, xl = _pair(x)
    _, yl = _pair(y)
    vals, lens = [], []
    for seg, n in zip(_segments(xv, xl), yl):
        rep = np.concatenate([seg] * int(n), axis=0) if int(n) else \
            seg[:0]
        vals.append(rep)
        lens.append(len(rep))
    return _wrap(np.concatenate(vals, axis=0),
                 np.asarray(lens, np.int64))


def sequence_expand_as(x, y, name=None):
    """Expand each single-step x item to y's per-item length."""
    xv, xl = _pair(x)
    _, yl = _pair(y)
    if not np.all(xl == 1):
        raise ValueError("sequence_expand_as expects one step per item "
                         "in x (the reference's constraint)")
    vals = [np.repeat(seg, int(n), axis=0)
            for seg, n in zip(_segments(xv, xl), yl)]
    return _wrap(np.concatenate(vals, axis=0), np.asarray(yl, np.int64))


def sequence_reshape(input, new_dim, name=None):  # noqa: A002
    v, ln = _pair(input)
    d = v.shape[-1]
    new_lens = (ln * d) // new_dim
    if int((ln * d).sum()) % new_dim:
        raise ValueError("total elements not divisible by new_dim")
    return _wrap(v.reshape(-1, new_dim), new_lens.astype(np.int64))


def sequence_scatter(input, index, updates, name=None):  # noqa: A002
    """Scatter-add updates into a DENSE input at per-sequence offsets:
    index/updates are a sequence pair whose segment i addresses row i
    of input."""
    xa = np.asarray(getattr(input, "_array", input)).copy()
    iv, il = _pair(index)
    uv, _ = _pair(updates)
    off = _offsets(il)
    for b in range(len(il)):
        idx = iv[off[b]:off[b + 1]].astype(np.int64).reshape(-1)
        upd = uv[off[b]:off[b + 1]]
        np.add.at(xa[b], idx, upd)
    return Tensor(jnp.asarray(xa))


def sequence_enumerate(input, win_size, pad_value=0, name=None):  # noqa: A002
    v, ln = _pair(input)
    vals = []
    for seg in _segments(v, ln):
        ids = seg.reshape(-1)
        rows = np.full((len(ids), win_size), pad_value, ids.dtype)
        for k in range(win_size):
            take = len(ids) - k
            if take > 0:
                rows[:take, k] = ids[k:]
        vals.append(rows)
    return _wrap(np.concatenate(vals, axis=0) if vals else
                 v.reshape(0, win_size), ln)


def sequence_reverse(x, name=None):
    v, ln = _pair(x)
    vals = [seg[::-1] for seg in _segments(v, ln)]
    return _wrap(np.concatenate(vals, axis=0) if vals else v, ln)


def sequence_conv(input, num_filters, filter_size=3,  # noqa: A002
                  filter_stride=1, padding=True, padding_start=None,
                  bias_attr=None, param_attr=None, act=None, name=None):
    """Context-window convolution per sequence (sequence_conv op): each
    step sees a window of ``filter_size`` neighboring steps (zero at the
    segment boundary) through one dense projection. Differentiable in
    the values, weight, and bias — only the integer window plan is
    host-side."""
    from ..core.tensor import apply_op
    from ..nn import initializer as I
    from ..nn.layer.layers import Layer

    vt, ln, _ids = _seq_meta(input)
    d = vt.shape[-1]
    helper = Layer()
    w = helper.create_parameter([filter_size * d, num_filters],
                                attr=param_attr,
                                default_initializer=I.XavierUniform())
    b = None
    if bias_attr is not False:
        b = helper.create_parameter([num_filters], attr=bias_attr,
                                    is_bias=True,
                                    default_initializer=I.Constant(0.0))
    start = padding_start if padding_start is not None \
        else -(filter_size // 2)
    # host-side window plan: absolute source row per (step, tap), with
    # out-of-segment taps masked
    total = int(ln.sum())
    off = _offsets(ln)
    pos = np.concatenate([np.arange(n) for n in ln]) if total else \
        np.zeros(0, np.int64)
    base = np.repeat(off[:-1], ln)
    seg_len = np.repeat(ln, ln)
    idx = np.zeros((total, filter_size), np.int64)
    mask = np.zeros((total, filter_size), np.float32)
    for k in range(filter_size):
        rel = pos + start + k
        ok = (rel >= 0) & (rel < seg_len)
        idx[:, k] = np.where(ok, base + np.clip(rel, 0, None), 0)
        mask[:, k] = ok
    idx_j = jnp.asarray(idx)
    mask_j = jnp.asarray(mask)

    def _f(va, wa, *mb):
        ctx = jnp.concatenate(
            [va[idx_j[:, k]] * mask_j[:, k:k + 1]
             for k in range(filter_size)], axis=-1)
        o = ctx @ wa
        return o + mb[0] if mb else o

    args = [vt, w] + ([b] if b is not None else [])
    out = apply_op(_f, *args, op_name="sequence_conv")
    if act:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return (out, Tensor(jnp.asarray(ln)))


class StaticRNN:
    """reference: fluid/layers StaticRNN — record the per-step block
    once (into a sub-Program, the reference's sub-Block) and replay it
    over every timestep of the [T, B, ...] inputs at call time."""

    def __init__(self, name=None):
        self._prog = None
        self._inputs: List[Tuple[Tensor, np.ndarray]] = []
        self._mems: List[List] = []   # [placeholder, init, new_value]
        self._outputs: List[Tensor] = []

    @contextlib.contextmanager
    def step(self):
        from .program import Program, program_guard
        self._prog = Program()
        with program_guard(self._prog):
            yield self

    def step_input(self, x):
        xa = np.asarray(getattr(x, "_array", x))
        ph = Tensor(jnp.asarray(xa[0]))
        self._prog._add_feed(f"__rnn_in{len(self._inputs)}", ph)
        self._inputs.append((ph, xa))
        return ph

    def memory(self, init=None, shape=None, batch_ref=None,
               init_value=0.0, init_batch_dim_idx=0, ref_batch_dim_idx=1):
        if init is not None:
            arr = np.asarray(getattr(init, "_array", init))
        else:
            if shape is None:
                raise ValueError("memory() needs init= or shape=")
            b = (np.asarray(getattr(batch_ref, "_array",
                                    batch_ref)).shape[init_batch_dim_idx]
                 if batch_ref is not None else 1)
            dims = [b if d in (-1, None) else d for d in shape]
            arr = np.full(dims, init_value, np.float32)
        ph = Tensor(jnp.asarray(arr))
        self._prog._add_feed(f"__rnn_mem{len(self._mems)}", ph)
        self._mems.append([ph, arr, None])
        return ph

    def update_memory(self, mem, new):
        for slot in self._mems:
            if slot[0] is mem:
                slot[2] = new
                return
        raise ValueError("update_memory: unknown memory tensor")

    def step_output(self, o):
        self._outputs.append(o)

    output = step_output

    def __call__(self):
        if not self._inputs:
            raise RuntimeError("StaticRNN: no step_input was declared")
        T = self._inputs[0][1].shape[0]
        mem_vals = [slot[1] for slot in self._mems]
        collected = [[] for _ in self._outputs]
        for t in range(T):
            env = {}
            for ph, xa in self._inputs:
                env[id(ph)] = jnp.asarray(xa[t])
            for slot, mv in zip(self._mems, mem_vals):
                env[id(slot[0])] = jnp.asarray(mv)
            # captured params/constants bind their live arrays
            for cap in self._prog._captured():
                env.setdefault(id(cap), cap._array)
            env = self._prog._replay(env)
            for i, o in enumerate(self._outputs):
                collected[i].append(env[id(o)])
            mem_vals = [np.asarray(env[id(slot[2])])
                        if slot[2] is not None else mv
                        for slot, mv in zip(self._mems, mem_vals)]
        outs = [Tensor(jnp.stack(c)) for c in collected]
        return outs[0] if len(outs) == 1 else outs
