"""paddle.static parity.

Reference analog: python/paddle/static/ — Program/Executor/program_guard.
TPU-native stance (SURVEY.md §7): the static graph IS the jaxpr/HLO trace;
`Program` wraps a traced function, `Executor.run` invokes the compiled
XLA executable (the InterpreterCore analog), and save/load_inference_model
ride jit.save/load's StableHLO artifacts. This module exists for API
compatibility; new code should use paddle_tpu.jit directly.
"""
from __future__ import annotations

import contextlib

from ..jit.api import InputSpec, StaticFunction, to_static
from ..core.tensor import Tensor

__all__ = ["InputSpec", "Program", "program_guard", "default_main_program",
           "default_startup_program", "Executor", "data", "name_scope",
           "py_func", "save_inference_model", "load_inference_model",
           "gradients"]


class Program:
    """A deferred-build graph: records a python callable + input specs."""

    def __init__(self):
        self._fn = None
        self._input_specs = []
        self._fetch = []

    def clone(self, for_test=False):
        p = Program()
        p._fn = self._fn
        p._input_specs = list(self._input_specs)
        return p

    def global_block(self):
        return self

    # minimal block API for compat
    def var(self, name):
        raise KeyError(name)


_main_program = Program()
_startup_program = Program()


def default_main_program():
    return _main_program


def default_startup_program():
    return _startup_program


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _main_program, _startup_program
    prev = (_main_program, _startup_program)
    _main_program = main_program
    if startup_program is not None:
        _startup_program = startup_program
    try:
        yield
    finally:
        _main_program, _startup_program = prev


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


def data(name, shape, dtype="float32", lod_level=0):
    return InputSpec(shape, dtype, name)


class Executor:
    """reference: python/paddle/fluid/executor.py:1387 Executor.run →
    StandaloneExecutor. Here: calls jit-compiled functions."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True):
        if callable(program):
            args = [v for v in (feed or {}).values()]
            out = program(*args)
            outs = out if isinstance(out, (list, tuple)) else [out]
            if return_numpy:
                return [o.numpy() if isinstance(o, Tensor) else o
                        for o in outs]
            return list(outs)
        return []


def py_func(func, x, out, backward_func=None):
    raise NotImplementedError("py_func: use eager mode / PyLayer instead")


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    from ..jit import save as jsave
    raise NotImplementedError(
        "save_inference_model: use paddle_tpu.jit.save(layer, path, "
        "input_spec=...) — the StableHLO serving path")


def load_inference_model(path_prefix, executor=None, **kwargs):
    from ..jit import load as jload
    return jload(path_prefix)


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..autograd.functional import grad
    return grad(targets, inputs, grad_outputs=target_gradients,
                allow_unused=True)


from . import nn  # noqa: E402
from ..amp import auto_cast as amp  # noqa: E402,F401
