"""paddle.static: the static-graph user surface.

Reference analog: python/paddle/static/ — Program/Executor/program_guard/
data, built on ProgramDesc + the StandaloneExecutor. Here the build side
records every op the API applies (see static/program.py for the full
mapping: op list = ProgramDesc, jit-compiled replay = InterpreterCore,
per-signature executable cache = _ExecutorCache), so the classic
workflow works end to end:

    paddle.enable_static()
    x = static.data("x", [None, 8])
    y = static.data("y", [None, 1])
    loss = paddle.mean((static.nn.fc(x, 1) - y) ** 2)
    paddle.optimizer.SGD(0.01).minimize(loss)
    exe = static.Executor()
    exe.run(static.default_startup_program())
    loss_val, = exe.run(feed={"x": xs, "y": ys}, fetch_list=[loss])

Control flow (static.nn.cond/while_loop/...) lowers to lax.cond /
lax.while_loop (static/control_flow.py); to_static/jit.save remain the
preferred path for new code.
"""
from __future__ import annotations

import contextlib

from ..jit.api import InputSpec, StaticFunction, to_static
from ..core.tensor import Tensor
from .program import (Program, Executor, program_guard,
                      default_main_program, default_startup_program,
                      enable_static, disable_static, in_static_mode, data)

__all__ = ["InputSpec", "Program", "program_guard", "default_main_program",
           "default_startup_program", "Executor", "data", "name_scope",
           "py_func", "save_inference_model", "load_inference_model",
           "gradients", "enable_static", "disable_static",
           "in_static_mode"]


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


def py_func(func, x, out, backward_func=None):
    raise NotImplementedError("py_func: use eager mode / PyLayer instead")


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    raise NotImplementedError(
        "save_inference_model: use paddle_tpu.jit.save(layer, path, "
        "input_spec=...) — the StableHLO serving path")


def load_inference_model(path_prefix, executor=None, **kwargs):
    from ..jit import load as jload
    return jload(path_prefix)


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..autograd.functional import grad
    return grad(targets, inputs, grad_outputs=target_gradients,
                allow_unused=True)


from . import nn  # noqa: E402
from ..amp import auto_cast as amp  # noqa: E402,F401
