"""paddle.static: the static-graph user surface.

Reference analog: python/paddle/static/ — Program/Executor/program_guard/
data, built on ProgramDesc + the StandaloneExecutor. Here the build side
records every op the API applies (see static/program.py for the full
mapping: op list = ProgramDesc, jit-compiled replay = InterpreterCore,
per-signature executable cache = _ExecutorCache), so the classic
workflow works end to end:

    paddle.enable_static()
    x = static.data("x", [None, 8])
    y = static.data("y", [None, 1])
    loss = paddle.mean((static.nn.fc(x, 1) - y) ** 2)
    paddle.optimizer.SGD(0.01).minimize(loss)
    exe = static.Executor()
    exe.run(static.default_startup_program())
    loss_val, = exe.run(feed={"x": xs, "y": ys}, fetch_list=[loss])

Control flow (static.nn.cond/while_loop/...) lowers to lax.cond /
lax.while_loop (static/control_flow.py); to_static/jit.save remain the
preferred path for new code.
"""
from __future__ import annotations

import contextlib

from ..jit.api import InputSpec, StaticFunction, to_static
from ..core.tensor import Tensor
from .program import (Program, Executor, program_guard,
                      default_main_program, default_startup_program,
                      enable_static, disable_static, in_static_mode, data)
from .compat import *  # noqa: F401,F403 — persistence/places/legacy shells
from .compat import __all__ as _compat_all

__all__ = ["InputSpec", "Program", "program_guard", "default_main_program",
           "default_startup_program", "Executor", "data", "name_scope",
           "py_func", "save_inference_model", "load_inference_model",
           "gradients", "enable_static", "disable_static",
           "in_static_mode"] + list(_compat_all)


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


def py_func(func, x, out, backward_func=None):
    raise NotImplementedError("py_func: use eager mode / PyLayer instead")


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Export a static Program's feed→fetch slice as the same StableHLO
    artifact jit.save writes (reference: static/io.py save_inference_model
    prunes the ProgramDesc to the feed/fetch subgraph; here the replay fn
    IS the pruned graph, with captured parameters frozen at save time).
    Loadable by load_inference_model / jit.load / inference.Predictor and
    the native C serving ABI. Placeholders declared with dynamic dims
    (static.data('x', [None, 8])) export shape-polymorphic via
    jax.export symbolic shapes, so the artifact serves any batch size;
    if the program's ops cannot trace symbolically, falls back to the
    concrete build shapes with a warning."""
    import os
    import pickle
    import warnings

    import jax
    from jax import export as jexport

    from ..framework.io import save as fsave
    from .program import default_main_program

    program = program if program is not None else default_main_program()
    feed_vars = list(feed_vars) if isinstance(feed_vars, (list, tuple)) \
        else [feed_vars]
    fetch_vars = list(fetch_vars) if isinstance(fetch_vars, (list, tuple)) \
        else [fetch_vars]
    caps = program._captured()
    params = {f"__cap_{i}": t._array for i, t in enumerate(caps)}

    def pure_forward(params_in, *feed_arrays):
        env = {id(t): a for t, a in zip(feed_vars, feed_arrays)}
        env.update({id(t): params_in[f"__cap_{i}"]
                    for i, t in enumerate(caps)})
        program._replay(env)
        outs = [env[id(t)] for t in fetch_vars]
        return outs[0] if len(outs) == 1 else tuple(outs)

    def _abstract(symbolic):
        # One shared symbol per AXIS POSITION across all feeds (dim 0 of
        # every dynamic feed is "_ax0" etc.): feeds that flow into the
        # same op (x + y, input_ids vs labels) must agree on their
        # dynamic sizes or tracing fails. All symbols come from ONE
        # symbolic_shape call — per-dim calls create distinct symbolic
        # scopes and jax.export refuses to mix them. Programs whose
        # dynamic dims at the same axis are genuinely unrelated fall
        # back to concrete shapes via the except path below.
        dyn_specs = [getattr(t, "_data_spec", None) for t in feed_vars]
        axes = sorted({i for s in dyn_specs if s is not None
                       for i, d in enumerate(s) if d is None})
        n_sym = len(axes)
        if symbolic and n_sym:
            syms = dict(zip(axes, jexport.symbolic_shape(
                ",".join(f"_ax{i}" for i in axes))))
        specs = []
        for t, spec in zip(feed_vars, dyn_specs):
            if symbolic and spec is not None and any(d is None for d in spec):
                dims = tuple(syms[i] if d is None else d
                             for i, d in enumerate(spec))
                specs.append(jax.ShapeDtypeStruct(dims, t._array.dtype))
            else:
                specs.append(jax.ShapeDtypeStruct(t._array.shape,
                                                  t._array.dtype))
        return specs, n_sym

    param_specs = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                   for k, v in params.items()}
    abstract, n_sym = _abstract(symbolic=True)
    polymorphic = n_sym > 0
    try:
        exported = jexport.export(jax.jit(pure_forward))(
            param_specs, *abstract)
    except Exception as e:
        if n_sym == 0:
            raise
        warnings.warn(
            "save_inference_model: shape-polymorphic export of dynamic "
            f"dims failed ({e}); exporting with the concrete build shapes "
            "(dynamic dims baked as 1) — the artifact will only accept "
            "that shape at serving time.", RuntimeWarning, stacklevel=2)
        abstract, _ = _abstract(symbolic=False)
        polymorphic = False
        exported = jexport.export(jax.jit(pure_forward))(
            param_specs, *abstract)
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    fsave({k: Tensor(v) for k, v in params.items()},
          path_prefix + ".pdiparams")
    with open(path_prefix + ".meta", "wb") as f:
        # the meta must describe what the artifact actually accepts: the
        # dynamic spec only when the export really is shape-polymorphic,
        # the baked concrete shapes after a fallback
        pickle.dump({"input_specs": [
            (list(getattr(t, "_data_spec", None) or t._array.shape)
             if polymorphic else list(t._array.shape),
             str(t._array.dtype)) for t in feed_vars]}, f)
    return path_prefix


def load_inference_model(path_prefix, executor=None, **kwargs):
    from ..jit import load as jload
    return jload(path_prefix)


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..autograd.functional import grad
    return grad(targets, inputs, grad_outputs=target_gradients,
                allow_unused=True)


from . import nn  # noqa: E402
from ..amp import auto_cast as amp  # noqa: E402,F401
