"""paddle.static.nn parity — functional layer builders routed to nn.functional.

Reference analog: python/paddle/static/nn/common.py (fc, conv2d, ...). These
exist so static-style model code ports; they construct ephemeral Layers.
"""
from __future__ import annotations

from ..nn import functional as F
from ..nn import Linear, Conv2D, BatchNorm, Embedding
from .control_flow import cond, while_loop, switch_case, case

__all__ = ["fc", "conv2d", "batch_norm", "embedding",
           "cond", "while_loop", "switch_case", "case"]


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    in_features = 1
    for d in x.shape[num_flatten_dims:]:
        in_features *= d
    from ..tensor.manipulation import reshape
    # -1 for the leading (batch-like) extent: static programs are built
    # on placeholder batch 1 but replayed at the fed batch size
    flat = reshape(x, [-1, in_features]) if num_flatten_dims == 1 else \
        reshape(x, list(x.shape[:num_flatten_dims]) + [in_features])
    layer = Linear(in_features, size, weight_attr, bias_attr)
    out = layer(flat)
    if activation:
        out = getattr(F, activation)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0,  # noqa: A002
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, name=None, data_format="NCHW"):
    in_channels = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    layer = Conv2D(in_channels, num_filters, filter_size, stride, padding,
                   dilation, groups, weight_attr=param_attr,
                   bias_attr=bias_attr, data_format=data_format)
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-05,  # noqa: A002
               param_attr=None, bias_attr=None, data_layout="NCHW",
               **kwargs):
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    layer = BatchNorm(c, momentum, epsilon, param_attr, bias_attr,
                      data_layout)
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,  # noqa: A002
              param_attr=None, dtype="float32"):
    layer = Embedding(size[0], size[1], padding_idx=padding_idx,
                      weight_attr=param_attr)
    return layer(input)
