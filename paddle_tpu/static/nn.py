"""paddle.static.nn parity — functional layer builders routed to nn.functional.

Reference analog: python/paddle/static/nn/common.py (fc, conv2d, ...). These
exist so static-style model code ports; they construct ephemeral Layers.
"""
from __future__ import annotations

from ..nn import functional as F
from ..nn import Linear, Conv2D, BatchNorm, Embedding
from .control_flow import cond, while_loop, switch_case, case

__all__ = ["fc", "conv2d", "batch_norm", "embedding",
           "cond", "while_loop", "switch_case", "case"]


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    in_features = 1
    for d in x.shape[num_flatten_dims:]:
        in_features *= d
    from ..tensor.manipulation import reshape
    # -1 for the leading (batch-like) extent: static programs are built
    # on placeholder batch 1 but replayed at the fed batch size
    flat = reshape(x, [-1, in_features]) if num_flatten_dims == 1 else \
        reshape(x, list(x.shape[:num_flatten_dims]) + [in_features])
    layer = Linear(in_features, size, weight_attr, bias_attr)
    out = layer(flat)
    if activation:
        out = getattr(F, activation)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0,  # noqa: A002
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, name=None, data_format="NCHW"):
    in_channels = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    layer = Conv2D(in_channels, num_filters, filter_size, stride, padding,
                   dilation, groups, weight_attr=param_attr,
                   bias_attr=bias_attr, data_format=data_format)
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-05,  # noqa: A002
               param_attr=None, bias_attr=None, data_layout="NCHW",
               **kwargs):
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    layer = BatchNorm(c, momentum, epsilon, param_attr, bias_attr,
                      data_layout)
    out = layer(input)
    if act:
        out = getattr(F, act)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,  # noqa: A002
              param_attr=None, dtype="float32"):
    layer = Embedding(size[0], size[1], padding_idx=padding_idx,
                      weight_attr=param_attr)
    return layer(input)


# ---------------------------------------------------------------------------
# common.py long tail: norms, conv variants, parameterized specials
# ---------------------------------------------------------------------------

def _derive_transpose_filter(in_hw, output_size, stride, padding, nd):
    """reference mode: filter_size=None derives the kernel from the
    requested output size (k = out - (in-1)*stride + 2*pad)."""
    st = (stride,) * nd if isinstance(stride, int) else tuple(stride)
    pd = (padding,) * nd if isinstance(padding, int) else tuple(padding)
    osz = (output_size,) * nd if isinstance(output_size, int) \
        else tuple(output_size)
    return tuple(osz[i] - (in_hw[i] - 1) * st[i] + 2 * pd[i]
                 for i in range(nd))


def conv2d_transpose(input, num_filters, output_size=None,  # noqa: A002
                     filter_size=None, padding=0, stride=1, dilation=1,
                     groups=1, param_attr=None, bias_attr=None, act=None,
                     name=None, data_format="NCHW"):
    from ..nn import Conv2DTranspose
    in_c = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    in_hw = input.shape[2:4] if data_format == "NCHW" \
        else input.shape[1:3]
    if filter_size is None:
        if output_size is None:
            raise ValueError(
                "conv2d_transpose needs filter_size or output_size")
        filter_size = _derive_transpose_filter(in_hw, output_size,
                                               stride, padding, 2)
    layer = Conv2DTranspose(in_c, num_filters, filter_size, stride,
                            padding, dilation=dilation, groups=groups,
                            weight_attr=param_attr, bias_attr=bias_attr,
                            data_format=data_format)
    out = layer(input, output_size=output_size)
    return getattr(F, act)(out) if act else out


def conv3d(input, num_filters, filter_size, stride=1, padding=0,  # noqa: A002
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, name=None, data_format="NCDHW"):
    from ..nn import Conv3D
    in_c = input.shape[1] if data_format == "NCDHW" else input.shape[-1]
    layer = Conv3D(in_c, num_filters, filter_size, stride, padding,
                   dilation, groups, weight_attr=param_attr,
                   bias_attr=bias_attr, data_format=data_format)
    out = layer(input)
    return getattr(F, act)(out) if act else out


def conv3d_transpose(input, num_filters, output_size=None,  # noqa: A002
                     filter_size=None, padding=0, stride=1, dilation=1,
                     groups=1, param_attr=None, bias_attr=None, act=None,
                     name=None, data_format="NCDHW"):
    from ..nn import Conv3DTranspose
    in_c = input.shape[1] if data_format == "NCDHW" else input.shape[-1]
    in_dhw = input.shape[2:5] if data_format == "NCDHW" \
        else input.shape[1:4]
    if filter_size is None:
        if output_size is None:
            raise ValueError(
                "conv3d_transpose needs filter_size or output_size")
        filter_size = _derive_transpose_filter(in_dhw, output_size,
                                               stride, padding, 3)
    layer = Conv3DTranspose(in_c, num_filters, filter_size, stride,
                            padding, dilation=dilation, groups=groups,
                            weight_attr=param_attr, bias_attr=bias_attr,
                            data_format=data_format)
    out = layer(input, output_size=output_size)
    return getattr(F, act)(out) if act else out


def instance_norm(input, epsilon=1e-05, param_attr=None,  # noqa: A002
                  bias_attr=None, name=None):
    from ..nn import InstanceNorm2D
    layer = InstanceNorm2D(input.shape[1], epsilon=epsilon,
                           weight_attr=param_attr, bias_attr=bias_attr)
    return layer(input)


def group_norm(input, groups, epsilon=1e-05, param_attr=None,  # noqa: A002
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    from ..nn import GroupNorm
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    layer = GroupNorm(groups, c, epsilon=epsilon, weight_attr=param_attr,
                      bias_attr=bias_attr, data_format=data_layout)
    out = layer(input)
    return getattr(F, act)(out) if act else out


def layer_norm(input, scale=True, shift=True,  # noqa: A002
               begin_norm_axis=1, epsilon=1e-05, param_attr=None,
               bias_attr=None, act=None, name=None):
    from ..nn import LayerNorm
    shape = list(input.shape[begin_norm_axis:])
    layer = LayerNorm(shape, epsilon=epsilon,
                      weight_attr=param_attr if scale else False,
                      bias_attr=bias_attr if shift else False)
    out = layer(input)
    return getattr(F, act)(out) if act else out


def data_norm(input, act=None, epsilon=1e-05, param_attr=None,  # noqa: A002
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              summary_decay_0=0.9999999, **kw):
    """reference: data_norm — PS-era normalization by accumulated
    batch statistics WITHOUT scale/shift parameters; here expressed as
    batch_norm with affine off (the statistics-normalization core)."""
    from ..nn import BatchNorm2D, BatchNorm1D
    c = input.shape[1]
    cls = BatchNorm2D if input.ndim == 4 else BatchNorm1D
    layer = cls(c, epsilon=epsilon, weight_attr=False, bias_attr=False)
    out = layer(input)
    return getattr(F, act)(out) if act else out


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    from ..nn import PReLU
    n = 1 if mode == "all" else (
        x.shape[1] if data_format == "NCHW" else x.shape[-1])
    layer = PReLU(num_parameters=n, weight_attr=param_attr,
                  data_format=data_format)
    return layer(x)


def deform_conv2d(x, offset, mask, num_filters, filter_size,  # noqa: A002
                  stride=1, padding=0, dilation=1, groups=1,
                  deformable_groups=1, im2col_step=1, param_attr=None,
                  bias_attr=None, name=None):
    from ..nn.layer.layers import Layer
    from ..nn import initializer as I
    from ..vision.ops import deform_conv2d as _dc
    k = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    helper = Layer()
    w = helper.create_parameter(
        [num_filters, x.shape[1] // groups, k[0], k[1]], attr=param_attr,
        default_initializer=I.XavierUniform())
    b = None
    if bias_attr is not False:
        b = helper.create_parameter([num_filters], attr=bias_attr,
                                    is_bias=True,
                                    default_initializer=I.Constant(0.0))
    return _dc(x, offset, w, bias=b, stride=stride, padding=padding,
               dilation=dilation, deformable_groups=deformable_groups,
               groups=groups, mask=mask)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    from ..nn import Bilinear
    layer = Bilinear(x.shape[-1], y.shape[-1], size,
                     weight_attr=param_attr, bias_attr=bias_attr)
    out = layer(x, y)
    return getattr(F, act)(out) if act else out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    from ..nn import SpectralNorm
    layer = SpectralNorm(weight.shape, dim=dim, power_iters=power_iters,
                         eps=eps)
    return layer(weight)


def row_conv(input, future_context_size, param_attr=None,  # noqa: A002
             act=None):
    """reference: row_conv op (lookahead convolution for streaming
    ASR): out[t] = sum_{k=0..future} x[t+k] * w[k], per feature."""
    import jax.numpy as jnp

    from ..core.tensor import apply_op
    from ..nn import initializer as I
    from ..nn.layer.layers import Layer
    helper = Layer()
    d = input.shape[-1]
    w = helper.create_parameter(
        [future_context_size + 1, d], attr=param_attr,
        default_initializer=I.XavierUniform())

    def _f(x, wa):
        T = x.shape[1]
        k = wa.shape[0]
        pad = jnp.pad(x, ((0, 0), (0, k - 1), (0, 0)))
        out = sum(pad[:, i:i + T] * wa[i] for i in range(k))
        return out
    out = apply_op(_f, input, w, op_name="row_conv")
    return getattr(F, act)(out) if act else out


_NCE_CALLS = [0]


def nce(input, label, num_total_classes, sample_weight=None,  # noqa: A002
        param_attr=None, bias_attr=None, num_neg_samples=10, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """reference: nce_op — noise-contrastive estimation loss with a
    uniform negative sampler (the documented default)."""
    import jax
    import jax.numpy as jnp

    from ..core.tensor import apply_op
    from ..nn import initializer as I
    from ..nn.layer.layers import Layer
    helper = Layer()
    d = input.shape[-1]
    w = helper.create_parameter([num_total_classes, d], attr=param_attr,
                                default_initializer=I.XavierUniform())
    b = helper.create_parameter([num_total_classes], attr=bias_attr,
                                is_bias=True,
                                default_initializer=I.Constant(0.0))

    # fresh negatives per CALL (a fixed key would contrast against the
    # same handful of classes all run); under jit the key is baked per
    # trace, matching the reference static-graph sampler's behavior
    _NCE_CALLS[0] += 1
    key0 = jax.random.fold_in(jax.random.PRNGKey(seed), _NCE_CALLS[0])

    def _f(x, y, wa, ba):
        B = x.shape[0]
        negs = jax.random.randint(key0, (B, num_neg_samples), 0,
                                  num_total_classes)
        y = y.reshape(-1).astype(jnp.int32)
        # a negative colliding with the true label would push that
        # class's logit toward 0 and 1 at once: shift collisions off
        negs = jnp.where(negs == y[:, None],
                         (negs + 1) % num_total_classes, negs)
        pos_logit = jnp.sum(x * wa[y], -1) + ba[y]
        neg_logit = jnp.einsum("bd,bkd->bk", x, wa[negs]) + ba[negs]

        def bce(logit, target):
            return jnp.maximum(logit, 0) - logit * target + \
                jnp.log1p(jnp.exp(-jnp.abs(logit)))
        loss = bce(pos_logit, 1.0) + bce(neg_logit, 0.0).sum(-1)
        return loss.reshape(B, 1)
    return apply_op(_f, input, label, w, b, op_name="nce")


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    from . import py_func as _top_py_func
    return _top_py_func(func, x, out, backward_func)


def sparse_embedding(input, size, padding_idx=None,  # noqa: A002
                     is_test=False, entry=None, table_class=None,
                     param_attr=None, dtype="float32", slot=None):
    """reference: fluid sparse_embedding — the PS-backed embedding.
    Maps onto the host-RAM embedding service when the table exceeds the
    device budget; a dense Embedding otherwise (documented collapse:
    distributed/ps/host_embedding.py is the scale-out path)."""
    return embedding(input, size, is_sparse=True,
                     padding_idx=padding_idx, param_attr=param_attr,
                     dtype=dtype)


__all__ += ["conv2d_transpose", "conv3d", "conv3d_transpose",
            "instance_norm", "group_norm", "layer_norm", "data_norm",
            "prelu", "deform_conv2d", "bilinear_tensor_product",
            "spectral_norm", "row_conv", "nce", "py_func",
            "sparse_embedding"]


from .sequence import (  # noqa: E402,F401
    sequence_conv, sequence_softmax, sequence_pool, sequence_concat,
    sequence_first_step, sequence_last_step, sequence_slice,
    sequence_expand, sequence_expand_as, sequence_pad, sequence_unpad,
    sequence_reshape, sequence_scatter, sequence_enumerate,
    sequence_reverse, StaticRNN)

__all__ += ["sequence_conv", "sequence_softmax", "sequence_pool",
            "sequence_concat", "sequence_first_step",
            "sequence_last_step", "sequence_slice", "sequence_expand",
            "sequence_expand_as", "sequence_pad", "sequence_unpad",
            "sequence_reshape", "sequence_scatter",
            "sequence_enumerate", "sequence_reverse", "StaticRNN"]
