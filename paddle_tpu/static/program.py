"""Static graph core: Program recording + Executor replay.

Reference analog: the ProgramDesc build + (Standalone)Executor run split —
python/paddle/fluid/framework.py Program/Block (OpDescs appended by the
LayerHelper as API calls are made) executed by
paddle/fluid/framework/new_executor/interpretercore.cc over a feed/fetch
contract (python/paddle/fluid/executor.py:1387 Executor.run).

TPU-native mapping: "append an OpDesc" = record the jax-traceable pure_fn
that apply_op (core/tensor.py) already routes every framework op through,
together with its input/output Tensor identities. The op list IS the
program. Executor.run replays the list as one pure function of
(feeds, captured state) and jit-compiles it per feed signature — XLA
plays InterpreterCore, the jaxpr plays ProgramDesc, and the compiled-
executable cache plays _ExecutorCache (executor.py:750). Parameters enter
as arguments (not baked constants), so optimizer updates between runs are
picked up without retracing; their update itself rides the eager
optimizer (`Optimizer.step`) on grads computed inside the same jit.

Build-time evaluation note: ops run eagerly on placeholder zeros while
the program is being built (shape inference for free — the InferMeta
analog); the recorded pure_fns are shape-polymorphic jnp code, so
Executor.run may feed any batch size regardless of the placeholder's.
Layer state that mutates during the forward (BatchNorm running stats)
is handled by recorded state-writes (record_state_write): the replay
computes the new values and the Executor persists them into the live
buffers after each run — the in-place-update-on-persistable-variable
semantics of the reference. clone(for_test=True) strips optimizer and
state-writes but replays ops in their build-time mode; rebuild the
program under layer.eval() for inference-mode normalization.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

from ..core.tensor import Tensor

__all__ = ["Program", "Executor", "program_guard", "default_main_program",
           "default_startup_program", "enable_static", "disable_static",
           "in_static_mode", "data"]


class _OpRecord:
    __slots__ = ("pure_fn", "inputs", "outputs", "op_name")

    def __init__(self, pure_fn, inputs, outputs, op_name):
        self.pure_fn = pure_fn
        self.inputs = list(inputs)    # Tensor refs (live: see run notes)
        self.outputs = list(outputs)  # Tensor refs
        self.op_name = op_name


class Program:
    """An op list + feed registry, recorded while the program is active."""

    def __init__(self):
        self._ops: List[_OpRecord] = []
        self._feeds: Dict[str, Tensor] = {}
        self._opt = None          # (optimizer, loss Tensor) from minimize
        # (live tensor, graph value) pairs: layer state the replay must
        # persist after each run (BatchNorm running stats — the
        # reference's in-place updates on persistable variables)
        self._state_writes: List[tuple] = []
        # id(live state tensor) -> latest graph value: later recorded
        # reads of the state chain onto the pending update (a BN layer
        # invoked twice in one program accumulates both batches, like the
        # reference's chained in-place ops)
        self._state_alias: Dict[int, Tensor] = {}
        self._cache: Dict[tuple, object] = {}

    # -- build side ---------------------------------------------------------
    def _record(self, pure_fn, inputs, outputs, op_name):
        if self._state_alias:
            inputs = [self._state_alias.get(id(t), t) for t in inputs]
        self._ops.append(_OpRecord(pure_fn, inputs, outputs, op_name))
        self._cache.clear()

    def _add_feed(self, name: str, t: Tensor):
        if name in self._feeds:
            raise ValueError(f"duplicate feed var name {name!r}")
        self._feeds[name] = t

    def clone(self, for_test=False):
        """Share the recorded graph; a for_test clone drops the optimizer
        and the state writes (reference: Program.clone(for_test=True)
        strips backward + in-place stat-update ops). Ops replay in their
        build-time mode — rebuild under layer.eval() when inference-mode
        layer behavior (BN normalizing by running stats) is needed."""
        p = Program()
        p._ops = self._ops
        p._feeds = self._feeds
        p._opt = None if for_test else self._opt
        p._state_writes = [] if for_test else self._state_writes
        return p

    def global_block(self):
        return self

    # -- persistence (reference: Program.state_dict / io.save_persistables)
    def state_dict(self, mode="all"):
        """Captured (parameter/constant) tensors by name — what
        distributed.io.save_persistables persists."""
        out = {}
        for i, t in enumerate(self._captured()):
            out[getattr(t, "name", None) or f"cap_{i}"] = t
        return out

    def set_state_dict(self, state_dict):
        caps = self._captured()
        by_name = {getattr(t, "name", None) or f"cap_{i}": t
                   for i, t in enumerate(caps)}
        import jax.numpy as jnp
        import numpy as np
        missing = []
        for k, v in state_dict.items():
            t = by_name.get(k)
            if t is None:
                missing.append(k)
                continue
            arr = getattr(v, "_array", v)
            t._set_array(jnp.asarray(np.asarray(arr)))
        if missing:
            import warnings
            warnings.warn(f"set_state_dict: no program vars named "
                          f"{missing[:5]}{'...' if len(missing) > 5 else ''}")
        self._cache.clear()

    def var(self, name: str) -> Tensor:
        if name in self._feeds:
            return self._feeds[name]
        for rec in self._ops:
            for t in rec.outputs:
                if getattr(t, "name", None) == name:
                    return t
        raise KeyError(f"no var named {name!r} in program")

    def list_vars(self):
        seen, out = set(), []
        for t in self._feeds.values():
            seen.add(id(t))
            out.append(t)
        for rec in self._ops:
            for t in rec.outputs:
                if id(t) not in seen:
                    seen.add(id(t))
                    out.append(t)
        return out

    # -- run-side helpers ---------------------------------------------------
    def _captured(self) -> List[Tensor]:
        """Inputs that are neither feeds nor op outputs: parameters and
        build-time constants. Their LIVE arrays become jit arguments."""
        produced = {id(t) for rec in self._ops for t in rec.outputs}
        feed_ids = {id(t) for t in self._feeds.values()}
        seen, caps = set(), []
        for rec in self._ops:
            for t in rec.inputs:
                tid = id(t)
                if tid in produced or tid in feed_ids or tid in seen:
                    continue
                seen.add(tid)
                caps.append(t)
        return caps

    def _replay(self, env: Dict[int, object]):
        """env: tensor-id -> array for feeds+captured; fills op outputs."""
        for rec in self._ops:
            arrs = [env[id(t)] for t in rec.inputs]
            out = rec.pure_fn(*arrs)
            outs = out if isinstance(out, (tuple, list)) else [out]
            for t, o in zip(rec.outputs, outs):
                env[id(t)] = o
        return env


# --------------------------------------------------------------------------
# active-program state (build-time recording)
# --------------------------------------------------------------------------

class _StaticState(threading.local):
    def __init__(self):
        self.enabled = False
        self.program_stack: List[Program] = []


_state = _StaticState()
_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


def recording_program() -> Optional[Program]:
    if _state.program_stack:
        return _state.program_stack[-1]
    if _state.enabled:
        return _main_program
    return None


def enable_static():
    """paddle.enable_static parity: API calls now append ops to the
    default main program instead of (only) executing eagerly."""
    from ..core import tensor as tensor_mod
    _state.enabled = True
    tensor_mod._STATIC_RECORD_HOOK[0] = _record_hook


def disable_static():
    from ..core import tensor as tensor_mod
    _state.enabled = False
    if not _state.program_stack:
        tensor_mod._STATIC_RECORD_HOOK[0] = None


def in_static_mode() -> bool:
    return recording_program() is not None


def _record_hook(pure_fn, inputs, outputs, op_name):
    prog = recording_program()
    if prog is not None:
        prog._record(pure_fn, inputs, outputs, op_name)


def record_state_write(dst: Tensor, src: Tensor):
    """Layers call this when they mutate persistent state during the
    build (BatchNorm running stats): the Executor re-computes ``src``
    each run and writes it back into the live ``dst`` tensor. Later
    recorded reads of ``dst`` resolve to ``src``, chaining repeated
    updates within one program."""
    prog = recording_program()
    if prog is not None:
        prog._state_writes.append((dst, src))
        prog._state_alias[id(dst)] = src
        prog._cache.clear()


class program_guard:
    """Context manager scoping recording to the given programs
    (reference: paddle.static.program_guard)."""

    def __init__(self, main_program: Program, startup_program=None):
        self._main = main_program
        self._startup = startup_program

    def __enter__(self):
        from ..core import tensor as tensor_mod
        global _main_program, _startup_program
        self._prev = (_main_program, _startup_program)
        _main_program = self._main
        if self._startup is not None:
            _startup_program = self._startup
        _state.program_stack.append(self._main)
        tensor_mod._STATIC_RECORD_HOOK[0] = _record_hook
        return self._main

    def __exit__(self, *exc):
        from ..core import tensor as tensor_mod
        global _main_program, _startup_program
        _main_program, _startup_program = self._prev
        _state.program_stack.pop()
        if not _state.program_stack and not _state.enabled:
            tensor_mod._STATIC_RECORD_HOOK[0] = None
        return False


def data(name: str, shape, dtype="float32", lod_level=0) -> Tensor:
    """Feed placeholder: a named Tensor whose build-time value is zeros
    (None/-1 dims as 1); Executor.run substitutes the fed batch.
    Reference: paddle.static.data returns a Variable in the current
    program; same contract here."""
    import jax.numpy as jnp
    from ..core.dtype import convert_dtype
    concrete = [1 if (d is None or int(d) < 0) else int(d) for d in shape]
    t = Tensor(jnp.zeros(concrete, convert_dtype(dtype)),
               stop_gradient=True)
    t.name = name
    # original spec with dynamic dims preserved (None) — consumed by
    # save_inference_model to export a shape-polymorphic artifact
    t._data_spec = [None if (d is None or int(d) < 0) else int(d)
                    for d in shape]
    prog = recording_program()
    if prog is None:
        raise RuntimeError(
            "static.data requires an active program: call "
            "paddle.enable_static() or use static.program_guard")
    prog._add_feed(name, t)
    return t


# --------------------------------------------------------------------------
# Executor
# --------------------------------------------------------------------------

class Executor:
    """Compiles + runs recorded programs (InterpreterCore analog: the op
    list becomes one jitted function per (feed signature, fetch set))."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True):
        if callable(program) and not isinstance(program, Program):
            # legacy convenience: run a jitted/static function directly
            args = [v for v in (feed or {}).values()]
            out = program(*args)
            outs = out if isinstance(out, (list, tuple)) else [out]
            return [o.numpy() if isinstance(o, Tensor) else o
                    for o in outs] if return_numpy else list(outs)
        program = program if program is not None else _main_program
        feed = feed or {}
        fetch_list = list(fetch_list or [])
        if not program._ops and not fetch_list:
            return []  # startup program: params initialized at build

        import jax.numpy as jnp
        feeds = sorted(program._feeds.items())
        missing = [n for n, _ in feeds if n not in feed]
        if missing:
            raise ValueError(f"missing feeds: {missing}")
        feed_arrays = [jnp.asarray(np.asarray(feed[n])) for n, _ in feeds]
        caps = program._captured()
        cap_arrays = [t._array for t in caps]
        fetch_ids = tuple(id(t) for t in fetch_list)

        train = program._opt is not None
        key = (len(program._ops), fetch_ids, train,
               len(program._state_writes),
               tuple((a.shape, str(a.dtype)) for a in feed_arrays))
        # the key contains the fetch tensors' id()s; id reuse after GC
        # cannot alias a stale entry because the cached fn's closure
        # (forward in _build) holds fetch_list alive for the entry's
        # whole lifetime
        fn = program._cache.get(key)
        if fn is None:
            fn = self._build(program, feeds, caps, fetch_list, train)
            from ..profiler import xmem
            if xmem.enabled():
                # compile this feed signature ahead-of-time: the same
                # single XLA compile that would happen on the first call
                # also yields memory/cost analysis, and the cache entry
                # becomes the Compiled itself
                compiled = xmem.aot_compile(
                    "executor",
                    "executor_train" if train else "executor_infer",
                    fn, (feed_arrays, cap_arrays),
                    sig=tuple((tuple(a.shape), str(a.dtype))
                              for a in feed_arrays))
                if compiled is not None:
                    fn = compiled
            program._cache[key] = fn

        if train:
            opt, _loss = program._opt
            trainable = [t for t in caps if not t.stop_gradient]
            if not opt._parameter_list:
                # minimize() during build could not know the program's
                # trainables yet; bind them now (stable order: capture
                # order, which is op order)
                opt._parameter_list = trainable
            fetch_vals, state_vals, grads = fn(feed_arrays, cap_arrays)
            for p, g in zip(trainable, grads):
                p.grad = Tensor(g)
            opt.step()
            opt.clear_grad()
        else:
            fetch_vals, state_vals = fn(feed_arrays, cap_arrays)
        for (dst, _src), val in zip(program._state_writes, state_vals):
            dst._set_array(val)
        if return_numpy:
            return [np.asarray(v) for v in fetch_vals]
        return [Tensor(v) for v in fetch_vals]

    def _build(self, program, feeds, caps, fetch_list, train):
        feed_ts = [t for _, t in feeds]
        trainable_idx = [i for i, t in enumerate(caps)
                         if not t.stop_gradient]
        state_srcs = [src for _dst, src in program._state_writes]

        def forward(feed_arrays, cap_arrays):
            env = {id(t): a for t, a in zip(feed_ts, feed_arrays)}
            env.update({id(t): a for t, a in zip(caps, cap_arrays)})
            program._replay(env)
            return ([env[id(t)] for t in fetch_list],
                    [env[id(t)] for t in state_srcs], env)

        if not train:
            @jax.jit
            def infer(feed_arrays, cap_arrays):
                fetches, svals, _env = forward(feed_arrays, cap_arrays)
                return fetches, svals
            return infer

        opt, loss_t = program._opt

        @jax.jit
        def train_step(feed_arrays, cap_arrays):
            def loss_of(train_arrays):
                full = list(cap_arrays)
                for i, a in zip(trainable_idx, train_arrays):
                    full[i] = a
                fetches, svals, env = forward(feed_arrays, full)
                return env[id(loss_t)].astype(jax.numpy.float32).sum(), \
                    (fetches, svals)
            train_arrays = [cap_arrays[i] for i in trainable_idx]
            (_, (fetches, svals)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(train_arrays)
            return fetches, svals, grads

        return train_step
