"""PyLayer — user-defined forward/backward.

Reference analog: paddle/fluid/eager/pylayer/ + python/paddle/autograd/
py_layer.py. The TPU-native construction records a TapeNode whose vjp is
the user's static backward(), so PyLayers compose with the eager tape and
with jit tracing alike (jax.custom_vjp is the purely-functional sibling,
exposed as `custom_vjp`).
"""
from __future__ import annotations

from typing import Any, List

from ..core.tensor import Tensor, TapeNode, is_grad_enabled, _as_array

import jax
import jax.numpy as jnp


class PyLayerContext:
    def __init__(self):
        self._saved: List[Tensor] = []
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        # capture the ACTIVE pair at save time: the reference's
        # documented usage wraps only forward, and backward may run
        # after the context exits — the unpack that undoes this pack
        # must travel with the saved value, not be looked up later
        if _SAVED_HOOKS:
            pack, unpack = _SAVED_HOOKS[-1]
            self._saved = [(pack(t), unpack) for t in tensors]
        else:
            self._saved = [(t, None) for t in tensors]

    def saved_tensor(self):
        return [unpack(v) if unpack is not None else v
                for v, unpack in self._saved]


# active (pack, unpack) hook pairs, innermost last
_SAVED_HOOKS: List[tuple] = []


class saved_tensors_hooks:  # noqa: N801 — reference spelling
    """reference: autograd/saved_tensors_hooks — context manager whose
    ``pack`` runs when a PyLayer saves a tensor for backward and whose
    ``unpack`` runs when backward retrieves it (the CPU-offload /
    recompute-saved-activations hook point). On this stack the jax-vjp
    tape manages intermediate residuals itself (rematerialize with
    paddle.distributed.recompute); the hooks apply to the EXPLICIT
    save_for_backward channel, which is the reference's documented
    contract surface."""

    def __init__(self, pack_hook, unpack_hook):
        self._pair = (pack_hook, unpack_hook)

    def __enter__(self):
        _SAVED_HOOKS.append(self._pair)
        return self

    def __exit__(self, *exc):
        _SAVED_HOOKS.remove(self._pair)
        return False

    # paddle also allows arbitrary attribute stashing — __dict__ covers it.


class PyLayerMeta(type):
    def __call__(cls, *args, **kwargs):
        raise RuntimeError(
            f"{cls.__name__} should not be instantiated; call "
            f"{cls.__name__}.apply(...) instead.")


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        outputs = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(outputs, (tuple, list))
        out_list = list(outputs) if multi else [outputs]
        out_tensors = [o if isinstance(o, Tensor) else Tensor(o)
                       for o in out_list]

        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        requires = (is_grad_enabled()
                    and any(not t.stop_gradient for t in tensor_inputs))
        if requires:
            def vjp_fn(cot):
                cots = cot if isinstance(cot, tuple) else (cot,)
                grads = cls.backward(ctx, *[Tensor(c) for c in cots])
                if not isinstance(grads, (tuple, list)):
                    grads = (grads,)
                garrs = []
                gi = iter(grads)
                for t in tensor_inputs:
                    try:
                        g = next(gi)
                    except StopIteration:
                        g = None
                    garrs.append(jnp.zeros_like(t._array) if g is None
                                 else _as_array(g))
                return tuple(garrs)

            for t in out_tensors:
                t.stop_gradient = False
            node = TapeNode(vjp_fn, tensor_inputs, out_tensors,
                            op_name=cls.__name__, multi_out=multi)
            for t in out_tensors:
                t._node = node
        if multi:
            return tuple(out_tensors)
        return out_tensors[0]


def custom_vjp(fwd=None, bwd=None):
    """Functional custom-VJP helper over jax.custom_vjp for kernel authors
    (the fused-op extension point; reference: paddle custom op ABI)."""
    def deco(fn):
        cfn = jax.custom_vjp(fn)
        cfn.defvjp(fwd, bwd)
        return cfn
    return deco
