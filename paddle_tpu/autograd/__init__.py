from .functional import grad, vjp, jvp, jacobian, hessian
from .pylayer import PyLayer, PyLayerContext, saved_tensors_hooks
from .backward_mode import backward
from ..core.tensor import no_grad, enable_grad, set_grad_enabled, \
    is_grad_enabled

__all__ = ["grad", "vjp", "jvp", "jacobian", "hessian", "PyLayer",
           "PyLayerContext", "backward", "no_grad", "enable_grad",
           "set_grad_enabled", "is_grad_enabled", "saved_tensors_hooks"]
