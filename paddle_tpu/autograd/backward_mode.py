"""paddle.autograd.backward parity."""
from __future__ import annotations

from ..core.tensor import run_backward


def backward(tensors, grad_tensors=None, retain_graph=False):
    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is not None and not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]
    run_backward(list(tensors), grad_tensors, retain_graph)
