"""Functional autograd API.

Reference analog: python/paddle/autograd/ — paddle.grad (GeneralGrad partial
graphs, paddle/fluid/eager/general_grad.h) and the incubate functional
jacobian/hessian/vjp/jvp. Here partial-graph grad runs on the same eager
tape as backward(); jacobian/hessian delegate to jax.jacrev/jax.hessian.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, TapeNode, run_backward, _as_array


def _listify(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _replay_pure_fn(outputs, inputs):
    """Rebuild the tape subgraph from `inputs` to `outputs` as a pure
    array function (the replacement for the reference's ProgramDesc — the
    recorded graph replayed functionally; enables higher-order AD and
    to_static of eager code)."""
    input_ids = {id(t) for t in inputs}
    nodes = {}
    stack = [t._node for t in outputs if t._node is not None]
    while stack:
        n = stack.pop()
        if n is None or n.index in nodes:
            continue
        nodes[n.index] = n
        for inp in n.inputs:
            if id(inp) not in input_ids and inp._node is not None:
                stack.append(inp._node)
    order = sorted(nodes)

    def pure(*arrs):
        env = {id(t): a for t, a in zip(inputs, arrs)}
        for idx in order:
            node = nodes[idx]
            if node.fwd_fn is None:
                raise RuntimeError(
                    f"op '{node.op_name}' does not support replay "
                    "(create_graph)")
            in_arrs = [env.get(id(t), t._array) for t in node.inputs]
            out = node.fwd_fn(*in_arrs)
            outs = list(out) if isinstance(out, (tuple, list)) else [out]
            for ref, o in zip(node.out_refs, outs):
                t = ref()
                if t is not None:
                    env[id(t)] = o
        return tuple(env.get(id(t), t._array) for t in outputs)
    return pure


def _grad_create_graph(outputs, inputs, grad_outputs, allow_unused):
    from ..core.tensor import apply_op
    pure = _replay_pure_fn(outputs, inputs)
    seeds = []
    for t, g in zip(outputs, grad_outputs):
        seeds.append(jnp.ones_like(t._array) if g is None else _as_array(g))

    def grad_fn(*arrs):
        _, vjp_fn = jax.vjp(pure, *arrs)
        return vjp_fn(tuple(seeds))
    outs = apply_op(grad_fn, *inputs, op_name="grad", n_outs=len(inputs))
    if not isinstance(outs, tuple):
        outs = (outs,)
    return list(outs)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None, name=None):
    """paddle.grad parity: gradients of outputs w.r.t. inputs without
    touching .grad on other leaves."""
    outputs = _listify(outputs)
    inputs = _listify(inputs)
    grad_outputs = _listify(grad_outputs) or [None] * len(outputs)
    if create_graph:
        return _grad_create_graph(outputs, inputs, grad_outputs,
                                  allow_unused)

    # Save and clear .grad of targets, run tape backward, collect, restore.
    saved = [(t, t.grad) for t in inputs]
    for t in inputs:
        t.grad = None
    # Temporarily mark no_grad_vars
    ngv = _listify(no_grad_vars)
    saved_sg = [(t, t.stop_gradient) for t in ngv]
    for t in ngv:
        t.stop_gradient = True
    try:
        run_backward(outputs, grad_outputs,
                     retain_graph=bool(retain_graph) or create_graph)
        results = []
        for t in inputs:
            if t.grad is None:
                if not allow_unused:
                    raise RuntimeError(
                        "One of the differentiated tensors appears unused; "
                        "pass allow_unused=True to get None for it.")
                results.append(None)
            else:
                g = t.grad
                g.stop_gradient = not create_graph
                results.append(g)
    finally:
        for t, g in saved:
            t.grad = g
        for t, sg in saved_sg:
            t.stop_gradient = sg
    return results


def _wrap_fn(func):
    """Adapt a Tensor-level callable to array-level for jax transforms."""
    def array_fn(*arrays):
        tensors = [Tensor(a, stop_gradient=False) for a in arrays]
        out = func(*tensors)
        if isinstance(out, (list, tuple)):
            return tuple(_as_array(o) for o in out)
        return _as_array(out)
    return array_fn


def vjp(func, xs, v=None):
    xs_list = _listify(xs)
    arrays = [t._array for t in xs_list]
    out, vjp_fn = jax.vjp(_wrap_fn(func), *arrays)
    multi_out = isinstance(out, tuple)
    if v is None:
        cot = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        v_list = _listify(v)
        cots = [t._array if isinstance(t, Tensor) else jnp.asarray(t)
                for t in v_list]
        cot = tuple(cots) if multi_out else cots[0]
    grads = vjp_fn(cot)
    out_t = (tuple(Tensor(o) for o in out) if multi_out else Tensor(out))
    grads_t = [Tensor(g) for g in grads]
    return out_t, grads_t if len(grads_t) > 1 else grads_t[0]


def jvp(func, xs, v=None):
    xs_list = _listify(xs)
    arrays = [t._array for t in xs_list]
    if v is None:
        tangents = [jnp.ones_like(a) for a in arrays]
    else:
        tangents = [t._array if isinstance(t, Tensor) else jnp.asarray(t)
                    for t in _listify(v)]
    out, jv = jax.jvp(_wrap_fn(func), tuple(arrays), tuple(tangents))
    to_t = lambda o: (tuple(Tensor(x) for x in o) if isinstance(o, tuple)
                      else Tensor(o))
    return to_t(out), to_t(jv)


def jacobian(func, xs, is_batched=False):
    xs_list = _listify(xs)
    arrays = [t._array for t in xs_list]
    jac = jax.jacrev(_wrap_fn(func), argnums=tuple(range(len(arrays))))(*arrays)
    def conv(j):
        if isinstance(j, tuple):
            return tuple(conv(x) for x in j)
        return Tensor(j)
    out = conv(jac)
    if len(arrays) == 1 and isinstance(out, tuple) and len(out) == 1:
        return out[0]
    return out


def hessian(func, xs, is_batched=False):
    xs_list = _listify(xs)
    arrays = [t._array for t in xs_list]
    hes = jax.hessian(_wrap_fn(func), argnums=tuple(range(len(arrays))))(*arrays)
    def conv(h):
        if isinstance(h, tuple):
            return tuple(conv(x) for x in h)
        return Tensor(h)
    out = conv(hes)
    if len(arrays) == 1 and isinstance(out, tuple) and len(out) == 1:
        o = out[0]
        return o[0] if isinstance(o, tuple) and len(o) == 1 else o
    return out
