"""paddle.nn parity surface."""
from .layer.layers import (Layer, Parameter, Sequential, LayerList,
                           ParameterList, LayerDict)
from .layer.common import *  # noqa: F401,F403
from .layer.conv import (Conv1D, Conv2D, Conv3D, Conv1DTranspose,
                         Conv2DTranspose, Conv3DTranspose)
from .layer.norm import *  # noqa: F401,F403
from .layer.activation import *  # noqa: F401,F403
from .layer.pooling import *  # noqa: F401,F403
from .layer.loss import *  # noqa: F401,F403
from .layer.rnn import *  # noqa: F401,F403
from .layer.transformer import (MultiHeadAttention, TransformerEncoderLayer,
                                TransformerEncoder, TransformerDecoderLayer,
                                TransformerDecoder, Transformer)
from .decode import BeamSearchDecoder, dynamic_decode
from .clip import (ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm)
from . import functional
from . import initializer
from . import utils
