"""Gradient clipping.

Reference analog: python/paddle/fluid/clip.py (ClipGradByValue/ByNorm/
ByGlobalNorm) — applied by optimizers before the update step. The
distributed variant (global norm across TP/PP shards) lives in
distributed/fleet (HybridParallelClipGrad analog).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm",
           "clip_grad_norm_", "clip_grad_value_"]


class ClipGradBase:
    def __call__(self, params_grads):
        return self._clip(params_grads)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):  # noqa: A002
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def _clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._array, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(g._array.astype(jnp.float32) ** 2))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12),
                                1.0)
            out.append((p, Tensor((g._array * scale).astype(g._array.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _clip(self, params_grads):
        sq = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                continue
            sq.append(jnp.sum(g._array.astype(jnp.float32) ** 2))
        if not sq:
            return params_grads
        global_norm = jnp.sqrt(sum(sq))
        scale = jnp.minimum(self.clip_norm
                            / jnp.maximum(global_norm, 1e-12), 1.0)
        self._record_norms(global_norm)
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
                continue
            out.append((p, Tensor((g._array * scale).astype(g._array.dtype))))
        return out

    def _record_norms(self, global_norm):
        """Numerics telemetry (FLAGS_tpu_metrics): pre/post-clip global
        grad norms — the trajectory that shows a divergence *before* the
        update (post-clip pins at clip_norm, pre-clip keeps climbing).
        Disabled path: one dict lookup; traced arrays are skipped."""
        from ..profiler import metrics as _metrics
        if not _metrics.enabled():
            return
        import jax
        if isinstance(global_norm, jax.core.Tracer):
            return
        pre = float(global_norm)
        post = min(pre, self.clip_norm)
        _metrics.gauge("grad_global_norm_preclip",
                       "Global grad norm before ClipGradByGlobalNorm"
                       ).set(pre)
        _metrics.gauge("grad_global_norm_postclip",
                       "Global grad norm after ClipGradByGlobalNorm"
                       ).set(post)
        if pre > self.clip_norm:
            _metrics.counter("grad_clip_activations_total",
                             "Steps where global-norm clipping engaged"
                             ).inc()
        from ..profiler import numerics as _numerics
        _numerics.note("grad_global_norm_preclip", pre)
        _numerics.note("grad_global_norm_postclip", post)


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.asarray(0.0))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._array))
                                   for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g._array.astype(jnp.float32)) ** norm_type)
             for g in grads])) ** (1.0 / norm_type)
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-12), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad._set_array((p.grad._array * scale).astype(
                p.grad._array.dtype))
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad._set_array(jnp.clip(p.grad._array, -clip_value,
                                       clip_value))
