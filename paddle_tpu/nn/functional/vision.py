"""Vision-related functional ops (reference:
python/paddle/nn/functional/vision.py — affine_grid, grid_sample,
pixel_shuffle...; CUDA kernels at paddle/phi/kernels/gpu/grid_sample_*).

grid_sample is pure gather + lerp — XLA lowers it to dynamic-gathers that
vectorize on the VPU; all shapes static, no data-dependent control flow.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import apply_op
from ...ops.registry import _ensure_tensor

__all__ = ["affine_grid", "grid_sample", "temporal_shift"]


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """theta [N, 2, 3] → sampling grid [N, H, W, 2]
    (reference: nn/functional/vision.py affine_grid)."""
    theta = _ensure_tensor(theta)
    if hasattr(out_shape, "numpy"):
        out_shape = [int(v) for v in out_shape.numpy()]
    N, C, H, W = [int(v) for v in out_shape]

    def _f(th):
        def axis_coords(n):
            if align_corners:
                return jnp.linspace(-1.0, 1.0, n)
            step = 2.0 / n
            return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, n)
        ys = axis_coords(H)
        xs = axis_coords(W)
        gx, gy = jnp.meshgrid(xs, ys)            # [H, W]
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)  # [H, W, 3]
        # [N,2,3] x [H,W,3] → [N,H,W,2]
        return jnp.einsum("nij,hwj->nhwi", th, base)
    return apply_op(_f, theta, op_name="affine_grid")


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """x [N,C,H,W], grid [N,Hg,Wg,2] (xy in [-1,1]) → [N,C,Hg,Wg]
    (reference: nn/functional/vision.py grid_sample)."""
    assert mode in ("bilinear", "nearest")
    assert padding_mode in ("zeros", "border", "reflection")
    x, grid = _ensure_tensor(x), _ensure_tensor(grid)

    def _unnormalize(coord, size):
        if align_corners:
            return (coord + 1.0) / 2.0 * (size - 1)
        return ((coord + 1.0) * size - 1.0) / 2.0

    def _reflect(coord, low, high):
        # reflect into [low, high] (continuous reflection padding);
        # a size-1 dim has span 0 — mod-by-zero would NaN, so clamp
        span = high - low
        if span <= 0:
            return jnp.full_like(coord, low)
        coord = jnp.abs((coord - low) % (2 * span) - span) + low
        return coord

    def _f(xa, ga):
        N, C, H, W = xa.shape
        gx = _unnormalize(ga[..., 0], W)          # [N,Hg,Wg]
        gy = _unnormalize(ga[..., 1], H)
        if padding_mode == "border":
            gx = jnp.clip(gx, 0, W - 1)
            gy = jnp.clip(gy, 0, H - 1)
        elif padding_mode == "reflection":
            if align_corners:
                gx = _reflect(gx, 0.0, W - 1.0)
                gy = _reflect(gy, 0.0, H - 1.0)
            else:
                gx = jnp.clip(_reflect(gx, -0.5, W - 0.5), 0, W - 1)
                gy = jnp.clip(_reflect(gy, -0.5, H - 0.5), 0, H - 1)

        def gather(iy, ix):
            iyc = jnp.clip(iy, 0, H - 1)
            ixc = jnp.clip(ix, 0, W - 1)
            # vals [N, C, Hg, Wg]
            vals = jnp.take_along_axis(
                xa.reshape(N, C, H * W),
                (iyc * W + ixc).reshape(N, 1, -1).astype(jnp.int32)
                .repeat(C, axis=1),
                axis=2).reshape(N, C, *iy.shape[1:])
            if padding_mode == "zeros":
                valid = ((iy >= 0) & (iy < H) & (ix >= 0)
                         & (ix < W))[:, None]
                vals = jnp.where(valid, vals, 0.0)
            return vals

        if mode == "nearest":
            return gather(jnp.round(gy).astype(jnp.int32),
                          jnp.round(gx).astype(jnp.int32))
        x0 = jnp.floor(gx)
        y0 = jnp.floor(gy)
        wx = (gx - x0)[:, None]
        wy = (gy - y0)[:, None]
        x0i, y0i = x0.astype(jnp.int32), y0.astype(jnp.int32)
        v00 = gather(y0i, x0i)
        v01 = gather(y0i, x0i + 1)
        v10 = gather(y0i + 1, x0i)
        v11 = gather(y0i + 1, x0i + 1)
        top = v00 * (1 - wx) + v01 * wx
        bot = v10 * (1 - wx) + v11 * wx
        return top * (1 - wy) + bot * wy
    return apply_op(_f, x, grid, op_name="grid_sample")


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None,
                   data_format="NCHW"):
    """TSM channel shift along the segment (time) axis
    (reference: nn/functional/vision.py temporal_shift → phi
    temporal_shift kernel)."""
    assert data_format in ("NCHW", "NHWC")
    if not 0.0 <= shift_ratio <= 0.5:
        raise ValueError(
            f"temporal_shift: shift_ratio must be in [0, 0.5], got "
            f"{shift_ratio} (the two shifted blocks may not overlap)")
    x = _ensure_tensor(x)

    def _f(a):
        if data_format == "NHWC":
            a = jnp.transpose(a, (0, 3, 1, 2))
        NT, C, H, W = a.shape
        T = seg_num
        N = NT // T
        a = a.reshape(N, T, C, H, W)
        fold = int(C * shift_ratio)
        left = jnp.concatenate(
            [a[:, 1:, :fold], jnp.zeros_like(a[:, :1, :fold])], axis=1)
        right = jnp.concatenate(
            [jnp.zeros_like(a[:, :1, fold:2 * fold]),
             a[:, :-1, fold:2 * fold]], axis=1)
        mid = a[:, :, 2 * fold:]
        out = jnp.concatenate([left, right, mid], axis=2)
        out = out.reshape(NT, C, H, W)
        if data_format == "NHWC":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out
    return apply_op(_f, x, op_name="temporal_shift")


from ...ops.registry import register as _register  # noqa: E402
for _n in __all__:
    _register(_n, globals()[_n])
