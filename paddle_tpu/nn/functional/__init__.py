"""paddle.nn.functional parity surface."""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .vision import *  # noqa: F401,F403
from .extended import *  # noqa: F401,F403
from ...tensor.manipulation import pad  # noqa: F401

# reference parity extras: inplace activation variants ride the shared
# tensor inplace machinery; diag_embed lives on the tensor surface;
# sparse_attention is the incubate implementation re-exported
from ...tensor.extras import _inplace as _mk_inplace  # noqa: E402
from .activation import elu, softmax, tanh  # noqa: E402

elu_ = _mk_inplace(elu)
softmax_ = _mk_inplace(softmax)
tanh_ = _mk_inplace(tanh)

from ...tensor.creation import diag_embed  # noqa: E402,F401
from ...incubate.nn.functional import sparse_attention  # noqa: E402,F401
