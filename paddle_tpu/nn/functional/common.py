"""Common functionals: linear, dropout, embedding, interpolate, attention.

Reference analog: python/paddle/nn/functional/common.py + input.py +
fused attention ops (paddle/fluid/operators/fused/fused_attention_op.cu —
here scaled_dot_product_attention is a single jnp composition XLA fuses;
a Pallas flash-attention kernel overrides it for long sequences via
paddle_tpu.ops.pallas_ops when available).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...core.tensor import Tensor, apply_op
from ...ops.registry import register, _ensure_tensor
from ...framework.random import next_key

__all__ = [
    "linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout",
    "embedding", "one_hot", "label_smooth", "cosine_similarity",
    "pixel_shuffle", "pixel_unshuffle", "channel_shuffle", "interpolate",
    "upsample", "bilinear", "unfold", "fold", "scaled_dot_product_attention",
    "pairwise_distance", "zeropad2d",
]


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b with paddle's [in, out] weight layout."""
    x, weight = _ensure_tensor(x), _ensure_tensor(weight)
    if bias is not None:
        return apply_op(lambda a, w, b: jnp.matmul(a, w) + b, x, weight,
                        _ensure_tensor(bias), op_name="linear")
    return apply_op(jnp.matmul, x, weight, op_name="linear")


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None, rng_key=None):
    x = _ensure_tensor(x)
    if not training or p == 0:
        if mode == "downscale_in_infer" and not training:
            return apply_op(lambda a: a * (1 - p), x, op_name="dropout_infer")
        return x
    key = rng_key if rng_key is not None else next_key()

    def _f(a):
        shape = list(a.shape)
        if axis is not None:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            shape = [s if i in axes else 1 for i, s in enumerate(a.shape)]
        keep = jax.random.bernoulli(key, 1 - p, shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)
    return apply_op(_f, x, op_name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axes = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axes, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axes = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axes, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = _ensure_tensor(x)
    if not training or p == 0:
        return x
    key = next_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def _f(a):
        keep = jax.random.bernoulli(key, 1 - p, a.shape)
        q = 1 - p
        a_coef = (q + alpha_p ** 2 * q * p) ** -0.5
        b_coef = -a_coef * alpha_p * p
        return (a_coef * jnp.where(keep, a, alpha_p) + b_coef).astype(a.dtype)
    return apply_op(_f, x, op_name="alpha_dropout")


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    x, weight = _ensure_tensor(x), _ensure_tensor(weight)

    def _f(ids, w):
        out = jnp.take(w, ids.astype(jnp.int32), axis=0)
        if padding_idx is not None:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out
    return apply_op(_f, x, weight, op_name="embedding")


def one_hot(x, num_classes, name=None):
    x = _ensure_tensor(x)
    return apply_op(
        lambda a: jax.nn.one_hot(a.astype(jnp.int32), num_classes),
        x, op_name="one_hot")


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    label = _ensure_tensor(label)
    args = [label]
    if prior_dist is not None:
        args.append(_ensure_tensor(prior_dist))

    def _f(y, *pd):
        k = y.shape[-1]
        if pd:
            return (1 - epsilon) * y + epsilon * pd[0]
        return (1 - epsilon) * y + epsilon / k
    return apply_op(_f, *args, op_name="label_smooth")


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    x1, x2 = _ensure_tensor(x1), _ensure_tensor(x2)

    def _f(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.linalg.norm(a, axis=axis)
        nb = jnp.linalg.norm(b, axis=axis)
        return dot / jnp.maximum(na * nb, eps)
    return apply_op(_f, x1, x2, op_name="cosine_similarity")


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    x, y = _ensure_tensor(x), _ensure_tensor(y)
    return apply_op(
        lambda a, b: jnp.sum(jnp.abs(a - b + epsilon) ** p, axis=-1,
                             keepdims=keepdim) ** (1.0 / p),
        x, y, op_name="pairwise_distance")


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    x = _ensure_tensor(x)
    r = upscale_factor

    def _f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            out = a.reshape(n, c // (r * r), r, r, h, w)
            out = out.transpose(0, 1, 4, 2, 5, 3)
            return out.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = a.shape
        out = a.reshape(n, h, w, r, r, c // (r * r))
        out = out.transpose(0, 1, 3, 2, 4, 5)
        return out.reshape(n, h * r, w * r, c // (r * r))
    return apply_op(_f, x, op_name="pixel_shuffle")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    x = _ensure_tensor(x)
    r = downscale_factor

    def _f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            out = a.reshape(n, c, h // r, r, w // r, r)
            out = out.transpose(0, 1, 3, 5, 2, 4)
            return out.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = a.shape
        out = a.reshape(n, h // r, r, w // r, r, c)
        out = out.transpose(0, 1, 3, 2, 4, 5)
        return out.reshape(n, h // r, w // r, c * r * r)
    return apply_op(_f, x, op_name="pixel_unshuffle")


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    x = _ensure_tensor(x)

    def _f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            out = a.reshape(n, groups, c // groups, h, w)
            out = out.transpose(0, 2, 1, 3, 4)
            return out.reshape(n, c, h, w)
        n, h, w, c = a.shape
        out = a.reshape(n, h, w, groups, c // groups)
        out = out.transpose(0, 1, 2, 4, 3)
        return out.reshape(n, h, w, c)
    return apply_op(_f, x, op_name="channel_shuffle")


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    x = _ensure_tensor(x)
    channels_last = data_format.endswith("C") and len(data_format) > 3 \
        or data_format in ("NHWC", "NDHWC", "NLC")
    nd = x.ndim - 2
    spatial = x.shape[1:1 + nd] if channels_last else x.shape[2:2 + nd]
    if size is not None:
        if isinstance(size, Tensor):
            size = size.tolist()
        out_sizes = [int(s.item()) if isinstance(s, Tensor) else int(s)
                     for s in (size if isinstance(size, (list, tuple))
                               else [size])]
    else:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
            else [scale_factor] * nd
        out_sizes = [int(s * f) for s, f in zip(spatial, sf)]

    jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "trilinear": "linear", "bicubic": "cubic",
             "area": "linear"}[mode]

    def _f(a):
        if channels_last:
            new_shape = (a.shape[0],) + tuple(out_sizes) + (a.shape[-1],)
        else:
            new_shape = a.shape[:2] + tuple(out_sizes)
        if jmode == "nearest":
            # paddle nearest: floor(src = dst * scale)
            idxs = []
            for i, (n_in, n_out) in enumerate(zip(spatial, out_sizes)):
                scale_ = n_in / n_out
                idx = jnp.floor(jnp.arange(n_out) * scale_).astype(jnp.int32)
                idxs.append(jnp.clip(idx, 0, n_in - 1))
            out = a
            for i, idx in enumerate(idxs):
                ax = (1 if channels_last else 2) + i
                out = jnp.take(out, idx, axis=ax)
            return out
        method = jmode
        return jax.image.resize(a, new_shape, method=method)
    return apply_op(_f, x, op_name="interpolate")


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW",
             name=None):
    return interpolate(x, size, scale_factor, mode, align_corners,
                       align_mode, data_format)


def bilinear(x1, x2, weight, bias=None, name=None):
    x1, x2 = _ensure_tensor(x1), _ensure_tensor(x2)
    weight = _ensure_tensor(weight)
    args = [x1, x2, weight]
    if bias is not None:
        args.append(_ensure_tensor(bias))

    def _f(a, b, w, *bi):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bi:
            out = out + bi[0]
        return out
    return apply_op(_f, *args, op_name="bilinear")


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (paddle layout: NCHW -> [N, C*kh*kw, L])."""
    x = _ensure_tensor(x)
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) \
        else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2
    if len(pd) == 2:
        pd = [pd[0], pd[1], pd[0], pd[1]]

    def _f(a):
        n, c, h, w = a.shape
        a_p = jnp.pad(a, [(0, 0), (0, 0), (pd[0], pd[2]), (pd[1], pd[3])])
        patches = lax.conv_general_dilated_patches(
            a_p, filter_shape=ks, window_strides=st, padding="VALID",
            rhs_dilation=dl, dimension_numbers=("NCHW", "OIHW", "NCHW"))
        # patches: [N, C*kh*kw, oh, ow]
        return patches.reshape(n, patches.shape[1], -1)
    return apply_op(_f, x, op_name="unfold")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    x = _ensure_tensor(x)
    os_ = output_sizes if isinstance(output_sizes, (list, tuple)) \
        else [output_sizes] * 2
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) \
        else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2

    def _f(a):
        n, ckk, L = a.shape
        c = ckk // (ks[0] * ks[1])
        oh = (os_[0] + 2 * pd[0] - dl[0] * (ks[0] - 1) - 1) // st[0] + 1
        ow = (os_[1] + 2 * pd[1] - dl[1] * (ks[1] - 1) - 1) // st[1] + 1
        a_r = a.reshape(n, c, ks[0], ks[1], oh, ow)
        out = jnp.zeros((n, c, os_[0] + 2 * pd[0], os_[1] + 2 * pd[1]),
                        a.dtype)
        for i in range(ks[0]):
            for j in range(ks[1]):
                hi = i * dl[0]
                wj = j * dl[1]
                out = out.at[:, :, hi:hi + oh * st[0]:st[0],
                             wj:wj + ow * st[1]:st[1]].add(a_r[:, :, i, j])
        return out[:, :, pd[0]:out.shape[2] - pd[0],
                   pd[1]:out.shape[3] - pd[1]]
    return apply_op(_f, x, op_name="fold")


def zeropad2d(x, padding, data_format="NCHW", name=None):
    from ...tensor.manipulation import pad as pad_fn
    return pad_fn(x, padding, mode="constant", value=0.0,
                  data_format=data_format)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """Fused-attention surface (reference: fused_attention_op.cu).

    Layout: [batch, seq, heads, head_dim] (paddle/flash-attn convention).
    Lowered as one jnp composition; XLA fuses QK^T+softmax+PV. For long
    sequences the Pallas flash kernel (ops/pallas_ops.py) is used instead
    when shapes allow.
    """
    query, key, value = (_ensure_tensor(query), _ensure_tensor(key),
                         _ensure_tensor(value))
    args = [query, key, value]
    has_mask = attn_mask is not None
    if has_mask:
        args.append(_ensure_tensor(attn_mask))
    drop_key = next_key() if (dropout_p > 0 and training) else None

    def _f(q, k, v, *m):
        scale = 1.0 / np.sqrt(q.shape[-1])
        # [B,S,H,D] -> [B,H,S,D]
        qt = jnp.swapaxes(q, 1, 2)
        kt = jnp.swapaxes(k, 1, 2)
        vt = jnp.swapaxes(v, 1, 2)
        logits = jnp.einsum("bhsd,bhtd->bhst", qt, kt) * scale
        if is_causal:
            s, t = logits.shape[-2], logits.shape[-1]
            causal = jnp.tril(jnp.ones((s, t), bool))
            logits = jnp.where(causal, logits, -jnp.inf)
        if m:
            mask = m[0]
            if mask.dtype == jnp.bool_:
                logits = jnp.where(mask, logits, -jnp.inf)
            else:
                logits = logits + mask
        probs = jax.nn.softmax(logits.astype(jnp.float32),
                               axis=-1).astype(q.dtype)
        if drop_key is not None:
            keep = jax.random.bernoulli(drop_key, 1 - dropout_p, probs.shape)
            probs = jnp.where(keep, probs / (1 - dropout_p), 0.0)
        out = jnp.einsum("bhst,bhtd->bhsd", probs, vt)
        return jnp.swapaxes(out, 1, 2)
    return apply_op(_f, *args, op_name="scaled_dot_product_attention")


for _n in __all__:
    register(_n, globals()[_n])
