"""Sequence / margin-loss / beam-search functional ops.

Reference: python/paddle/nn/functional/extension.py (sequence_mask,
gather_tree, temporal_shift), python/paddle/nn/functional/loss.py
(margin_cross_entropy — the ArcFace family over the
c_softmax_with_cross_entropy TP kernel,
paddle/fluid/operators/collective/c_softmax_with_cross_entropy_op.cu),
python/paddle/nn/functional/common.py (class_center_sample, kernel
paddle/phi/kernels/gpu/class_center_sample_kernel.cu).

TPU notes: margin_cross_entropy under GSPMD shards the class axis with a
PartitionSpec on the logits — XLA inserts the psum the reference's
collective op does by hand. class_center_sample is host-side data prep
(dynamic shapes), like the reference's CPU path.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...core.tensor import Tensor, apply_op
from ...ops.registry import _ensure_tensor

__all__ = ["sequence_mask", "gather_tree", "class_center_sample",
           "margin_cross_entropy"]


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """lengths → 0/1 mask [.., maxlen]
    (reference: nn/functional/extension.py sequence_mask)."""
    x = _ensure_tensor(x)
    if maxlen is None:
        from jax.core import Tracer
        if isinstance(x._array, Tracer):
            raise ValueError(
                "sequence_mask(maxlen=None) under jit would make the mask "
                "width data-dependent (XLA needs static shapes); pass an "
                "explicit maxlen")
        # scalar readback only (not the whole array) to size the mask
        maxlen = int(jnp.max(x._array))
    from ...core.dtype import convert_dtype

    def _f(a):
        return (jnp.arange(maxlen) < a[..., None]).astype(
            convert_dtype(dtype))
    return apply_op(_f, x, op_name="sequence_mask")


def gather_tree(ids, parents):
    """Beam-search backtrace: [T, B, beam] ids + parent indices → full
    beams (reference: nn/functional/extension.py gather_tree → phi
    gather_tree kernel). Reverse lax.scan over time."""
    ids, parents = _ensure_tensor(ids), _ensure_tensor(parents)

    def _f(ids_a, par_a):
        T, B, K = ids_a.shape
        binds = jnp.arange(B)[:, None]

        def step(beam_idx, t):
            # beam_idx [B, K] = which beam each output slot follows at t+1
            out = ids_a[t][binds, beam_idx]
            prev = par_a[t][binds, beam_idx]
            return prev, out

        last = jnp.broadcast_to(jnp.arange(K)[None, :], (B, K))
        _, outs = lax.scan(step, last, jnp.arange(T), reverse=True)
        return outs
    return apply_op(_f, ids, parents, op_name="gather_tree")


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample class centers (PartialFC): keeps every positive class and
    pads with negatives to `num_samples`; returns (remapped_label,
    sampled_class_center). Host-side numpy — dynamic-shaped data prep
    (reference: nn/functional/common.py class_center_sample)."""
    lab = np.asarray(label._array if isinstance(label, Tensor) else label)
    pos = np.unique(lab)
    if len(pos) >= num_samples:
        sampled = pos
    else:
        rest = np.setdiff1d(np.arange(num_classes), pos)
        # global numpy RNG: fresh negatives each call, seedable via
        # np.random.seed for reproducible runs
        extra = np.random.choice(rest, size=num_samples - len(pos),
                                 replace=False)
        sampled = np.concatenate([pos, np.sort(extra)])
    remap = -np.ones(num_classes, np.int64)
    remap[sampled] = np.arange(len(sampled))
    return (Tensor(jnp.asarray(remap[lab])),
            Tensor(jnp.asarray(sampled.astype(np.int64))))


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """ArcFace-family margin softmax CE: target cos θ becomes
    cos(m1·θ + m2) − m3, all logits scaled by `scale`
    (reference: nn/functional/loss.py margin_cross_entropy over the
    c_softmax_with_cross_entropy TP kernel)."""
    logits, label = _ensure_tensor(logits), _ensure_tensor(label)

    def _f(lg, lb):
        lb = lb.reshape(-1).astype(jnp.int32)
        one_hot = jax.nn.one_hot(lb, lg.shape[-1], dtype=lg.dtype)
        # epsilon keeps arccos' (infinite slope at ±1) off the clip
        # boundary — at exactly ±1 the 0·inf product would NaN the grads
        eps = 1e-6
        cos = jnp.clip(lg, -1.0 + eps, 1.0 - eps)
        theta = jnp.arccos(cos)
        target = jnp.cos(margin1 * theta + margin2) - margin3
        adjusted = jnp.where(one_hot > 0, target, cos) * scale
        logp = jax.nn.log_softmax(adjusted, axis=-1)
        loss = -jnp.sum(one_hot * logp, axis=-1)
        if reduction == "mean":
            loss_out = jnp.mean(loss)
        elif reduction == "sum":
            loss_out = jnp.sum(loss)
        else:
            loss_out = loss
        if return_softmax:
            return loss_out, jnp.exp(logp)
        return loss_out

    if return_softmax:
        return apply_op(_f, logits, label, op_name="margin_cross_entropy",
                        n_outs=2)
    return apply_op(_f, logits, label, op_name="margin_cross_entropy")


from ...ops.registry import register as _register  # noqa: E402
for _n in __all__:
    _register(_n, globals()[_n])


def edit_distance(input, label, normalized=True, ignored_tokens=None,  # noqa: A002
                  input_length=None, label_length=None, name=None):
    """Levenshtein distance between token sequence batches (reference:
    edit_distance op). Host-side DP like the reference CPU kernel —
    dynamic lengths make this inherently sequential. Returns
    (distances [B, 1] float32, sequence_num [1])."""
    import numpy as _np

    a = _np.asarray(_ensure_tensor(input)._array)
    b = _np.asarray(_ensure_tensor(label)._array)
    il = None if input_length is None else \
        _np.asarray(_ensure_tensor(input_length)._array).reshape(-1)
    ll = None if label_length is None else \
        _np.asarray(_ensure_tensor(label_length)._array).reshape(-1)
    ignored = set(ignored_tokens or [])
    B = a.shape[0]
    out = _np.zeros((B, 1), _np.float32)
    for n in range(B):
        s = a[n][:il[n]] if il is not None else a[n]
        t = b[n][:ll[n]] if ll is not None else b[n]
        s = [int(v) for v in s if int(v) not in ignored]
        t = [int(v) for v in t if int(v) not in ignored]
        dp = _np.arange(len(t) + 1, dtype=_np.float32)
        for i in range(1, len(s) + 1):
            prev = dp.copy()
            dp[0] = i
            for j in range(1, len(t) + 1):
                dp[j] = min(prev[j] + 1, dp[j - 1] + 1,
                            prev[j - 1] + (s[i - 1] != t[j - 1]))
        d = dp[len(t)]
        if normalized:
            d = d / max(len(t), 1)
        out[n, 0] = d
    from ...core.tensor import Tensor as _T
    import jax.numpy as _jnp
    return _T(_jnp.asarray(out)), _T(_jnp.asarray(_np.asarray([B], _np.int64)))


_register("edit_distance", edit_distance)
__all__ += ["edit_distance"]
