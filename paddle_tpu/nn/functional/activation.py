"""Activation functionals.

Reference analog: python/paddle/nn/functional/activation.py, PHI activation
kernels (paddle/phi/kernels/*/activation_kernel*). One jnp/jax.nn call each;
XLA fuses them into neighboring matmuls (the fused-epilogue analog).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ...core.tensor import apply_op
from ...ops.registry import register, _ensure_tensor

__all__ = [
    "relu", "relu_", "relu6", "leaky_relu", "prelu", "elu", "selu", "celu",
    "gelu", "silu", "swish", "mish", "hardswish", "hardsigmoid", "hardtanh",
    "hardshrink", "softshrink", "tanhshrink", "softplus", "softsign",
    "sigmoid", "log_sigmoid", "tanh", "softmax", "log_softmax", "gumbel_softmax",
    "maxout", "glu", "rrelu", "thresholded_relu",
]


def relu(x, name=None):
    return apply_op(lambda a: jnp.maximum(a, 0), _ensure_tensor(x),
                    op_name="relu")


def relu_(x):
    from ...core.tensor import rebind_inplace, tape_snapshot
    return rebind_inplace(x, relu(tape_snapshot(x)))


def relu6(x, name=None):
    return apply_op(lambda a: jnp.clip(a, 0, 6), _ensure_tensor(x),
                    op_name="relu6")


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply_op(lambda a: jnp.where(a >= 0, a, negative_slope * a),
                    _ensure_tensor(x), op_name="leaky_relu")


def prelu(x, weight, data_format="NCHW", name=None):
    x, weight = _ensure_tensor(x), _ensure_tensor(weight)

    def _f(a, w):
        if w.size > 1:
            ch_axis = 1 if data_format[1] == "C" else a.ndim - 1
            shape = [1] * a.ndim
            shape[ch_axis] = w.size
            w = w.reshape(shape)
        return jnp.where(a >= 0, a, w * a)
    return apply_op(_f, x, weight, op_name="prelu")


def elu(x, alpha=1.0, name=None):
    return apply_op(lambda a: jnp.where(a > 0, a,
                                        alpha * (jnp.exp(a) - 1)),
                    _ensure_tensor(x), op_name="elu")


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply_op(
        lambda a: scale * jnp.where(a > 0, a, alpha * (jnp.exp(a) - 1)),
        _ensure_tensor(x), op_name="selu")


def celu(x, alpha=1.0, name=None):
    return apply_op(
        lambda a: jnp.maximum(a, 0) + jnp.minimum(
            0, alpha * (jnp.exp(a / alpha) - 1)),
        _ensure_tensor(x), op_name="celu")


def gelu(x, approximate=False, name=None):
    return apply_op(lambda a: jax.nn.gelu(a, approximate=approximate),
                    _ensure_tensor(x), op_name="gelu")


def silu(x, name=None):
    return apply_op(lambda a: a * lax.logistic(a), _ensure_tensor(x),
                    op_name="silu")


def swish(x, name=None):
    return silu(x)


def mish(x, name=None):
    return apply_op(lambda a: a * jnp.tanh(jax.nn.softplus(a)),
                    _ensure_tensor(x), op_name="mish")


def hardswish(x, name=None):
    return apply_op(lambda a: a * jnp.clip(a + 3, 0, 6) / 6,
                    _ensure_tensor(x), op_name="hardswish")


def hardsigmoid(x, slope=1 / 6, offset=0.5, name=None):
    return apply_op(lambda a: jnp.clip(slope * a + offset, 0, 1),
                    _ensure_tensor(x), op_name="hardsigmoid")


def hardtanh(x, min=-1.0, max=1.0, name=None):  # noqa: A002
    return apply_op(lambda a: jnp.clip(a, min, max), _ensure_tensor(x),
                    op_name="hardtanh")


def hardshrink(x, threshold=0.5, name=None):
    return apply_op(
        lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0),
        _ensure_tensor(x), op_name="hardshrink")


def softshrink(x, threshold=0.5, name=None):
    return apply_op(
        lambda a: jnp.where(a > threshold, a - threshold,
                            jnp.where(a < -threshold, a + threshold, 0.0)),
        _ensure_tensor(x), op_name="softshrink")


def tanhshrink(x, name=None):
    return apply_op(lambda a: a - jnp.tanh(a), _ensure_tensor(x),
                    op_name="tanhshrink")


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply_op(
        lambda a: jnp.where(beta * a > threshold, a,
                            jnp.log1p(jnp.exp(beta * a)) / beta),
        _ensure_tensor(x), op_name="softplus")


def softsign(x, name=None):
    return apply_op(lambda a: a / (1 + jnp.abs(a)), _ensure_tensor(x),
                    op_name="softsign")


def sigmoid(x, name=None):
    return apply_op(lax.logistic, _ensure_tensor(x), op_name="sigmoid")


def log_sigmoid(x, name=None):
    return apply_op(jax.nn.log_sigmoid, _ensure_tensor(x),
                    op_name="log_sigmoid")


def tanh(x, name=None):
    return apply_op(jnp.tanh, _ensure_tensor(x), op_name="tanh")


def softmax(x, axis=-1, dtype=None, name=None):
    x = _ensure_tensor(x)

    def _f(a):
        if dtype is not None:
            from ...core import dtype as dtype_mod
            a = a.astype(dtype_mod.convert_dtype(dtype))
        return jax.nn.softmax(a, axis=axis)
    return apply_op(_f, x, op_name="softmax")


def log_softmax(x, axis=-1, dtype=None, name=None):
    x = _ensure_tensor(x)

    def _f(a):
        if dtype is not None:
            from ...core import dtype as dtype_mod
            a = a.astype(dtype_mod.convert_dtype(dtype))
        return jax.nn.log_softmax(a, axis=axis)
    return apply_op(_f, x, op_name="log_softmax")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...framework.random import next_key
    x = _ensure_tensor(x)
    key = next_key()

    def _f(a):
        g = jax.random.gumbel(key, a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            onehot = jnp.zeros_like(y)
            onehot = jnp.put_along_axis(onehot, idx,
                                        jnp.ones_like(idx, y.dtype), axis,
                                        inplace=False)
            y = onehot + y - lax.stop_gradient(y)
        return y
    return apply_op(_f, x, op_name="gumbel_softmax")


def maxout(x, groups, axis=1, name=None):
    x = _ensure_tensor(x)

    def _f(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        new_shape = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1:]
        return jnp.max(a.reshape(new_shape), axis=ax + 1)
    return apply_op(_f, x, op_name="maxout")


def glu(x, axis=-1, name=None):
    x = _ensure_tensor(x)

    def _f(a):
        a1, a2 = jnp.split(a, 2, axis=axis)
        return a1 * lax.logistic(a2)
    return apply_op(_f, x, op_name="glu")


def rrelu(x, lower=1 / 8.0, upper=1 / 3.0, training=True, name=None):
    from ...framework.random import next_key
    x = _ensure_tensor(x)
    if training:
        key = next_key()

        def _f(a):
            slope = jax.random.uniform(key, a.shape, a.dtype, lower, upper)
            return jnp.where(a >= 0, a, slope * a)
        return apply_op(_f, x, op_name="rrelu")
    mid = (lower + upper) / 2
    return leaky_relu(x, mid)


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply_op(lambda a: jnp.where(a > threshold, a, value),
                    _ensure_tensor(x), op_name="thresholded_relu")


for _n in __all__:
    if not _n.endswith("_"):
        register(_n, globals()[_n])
